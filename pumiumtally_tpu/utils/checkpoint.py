"""Checkpoint / resume for tally runs.

The reference has no persistence besides the final VTK write (SURVEY.md §5:
"Checkpoint / resume. Absent.") — but its state is additive, so the natural
checkpoint is exactly (flux accumulator, particle state, iteration counter).
This module saves/restores that as a single compressed ``.npz`` with a mesh
fingerprint so a checkpoint can never be resumed against a different mesh.

Durability contract (the resilience subsystem's foundation,
``resilience/``):

  * every write is ATOMIC — serialized to a same-directory temp file,
    fsync'd, then ``os.replace``d over the target, so a crash or ENOSPC
    mid-write can never leave a truncated ``.npz`` under the real name;
  * every array carries a sha256 digest in the meta block, verified on
    load BEFORE any tally state is overwritten (``verify_checkpoint`` /
    ``CheckpointIntegrityError``), so silent bit-rot or a torn copy is
    detected instead of resumed;
  * restore validates format/kind/mesh/dtype/sd_mode/run-shape and
    raises on any mismatch rather than silently resuming (or silently
    CASTING — an f64 checkpoint into an f32 tally would lose the
    precision contract) a different run.

Sharded generations (two-phase commit; the elastic-recovery layer's
foundation, ``resilience/coordinator.py``/``elastic.py``): a generation
named ``<name>.shards`` is a DIRECTORY of per-mesh-part ``shard-*.npz``
payload splits (each an atomic, digest-carrying npz like the single
file, written concurrently) plus a ``MANIFEST.json`` committed LAST.
The manifest names every shard with its whole-file sha256, so the
generation is valid only once the commit record exists and every named
shard hashes clean — a torn multi-shard write (crash before the
manifest, or a shard corrupted after it) can never produce a
Frankenstein restore: the whole generation is rejected atomically and
the resilience layer falls back to an older one. Single-file ``.npz``
generations remain fully supported (backward compatible): every
``save_*``/``restore_*``/``verify_checkpoint`` entry point dispatches
on the ``.shards`` suffix / directory form.

``snapshot_state``/``restore_state`` expose the same payload as
in-memory host copies — the ``ResilientRunner``'s retry anchor, no
serialization.

Used by ``PumiTally.save_checkpoint`` / ``PumiTally.restore_checkpoint``
(and the partitioned equivalents); host-side glue, not a hot path.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile

import numpy as np

FORMAT_VERSION = 1

#: Suffix marking a sharded (directory) generation; everything else is
#: the single-file ``.npz`` layout.
SHARD_SUFFIX = ".shards"

#: The two-phase-commit record of a sharded generation, written LAST.
MANIFEST_NAME = "MANIFEST.json"


class CheckpointIntegrityError(ValueError):
    """A checkpoint file failed its integrity check (truncated container,
    missing array, or per-array sha256 mismatch). Distinct from the
    plain ``ValueError`` of a *mismatched* (wrong mesh/config) but
    intact checkpoint: the resilience layer skips corrupt generations
    and falls back, while a genuine mismatch propagates to the caller."""


def mesh_fingerprint(mesh) -> str:
    """Stable content hash of the mesh the tally ran on (connectivity +
    coordinates + region ids)."""
    h = hashlib.sha256()
    for arr in (mesh.tet2vert, mesh.coords, mesh.class_id):
        a = np.ascontiguousarray(np.asarray(arr))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _array_digest(arr) -> str:
    """sha256 over dtype + shape + raw bytes — the per-array integrity
    unit stored in the meta block and re-checked on load."""
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def _normalize(filename: str) -> str:
    # np.savez_compressed silently appends ".npz"; normalize on both the
    # save and load side so any filename round-trips.
    return filename if filename.endswith(".npz") else filename + ".npz"


def is_sharded(path: str) -> bool:
    """True when ``path`` names a sharded (directory) generation —
    either by the ``.shards`` suffix (save side, may not exist yet) or
    by being a directory on disk (restore side)."""
    return path.endswith(SHARD_SUFFIX) or os.path.isdir(path)


def fsync_dir(directory: str) -> None:
    """Best-effort fsync of a DIRECTORY, making the renames/unlinks
    inside it durable across power loss (a data fsync alone only makes
    the file contents durable — the directory entry pointing at them
    lives in the directory's own metadata block). Shared by
    ``atomic_savez`` (after the rename) and ``CheckpointStore``'s
    keep-N rotation (after the deletions): without the latter, a power
    cut after rotation could resurrect a deleted older generation AND
    lose the rename of the newest, leaving ``find_latest`` a stale
    view. Unsupported filesystems (some network mounts) are tolerated —
    the data fsync + rename already rule out torn files there."""
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def atomic_savez(filename: str, **arrays) -> str:
    """``np.savez_compressed`` with crash-safe semantics: write to a
    same-directory temp file, flush + fsync, then ``os.replace`` over
    the target (and fsync the directory so the rename itself is
    durable). A crash/ENOSPC at any point leaves either the old file or
    nothing — never a truncated ``.npz`` under the real name."""
    filename = _normalize(filename)
    directory = os.path.dirname(os.path.abspath(filename)) or "."
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(filename) + ".tmp-"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, filename)
        fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return filename


def _write_checkpoint(filename: str, meta: dict, arrays: dict) -> str:
    meta = dict(
        meta,
        array_sha256={k: _array_digest(v) for k, v in arrays.items()},
    )
    return atomic_savez(
        filename,
        meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        **arrays,
    )


def _verify_integrity(arrays: dict, meta: dict, filename: str) -> None:
    """Re-hash every loaded array against the meta block's digests
    (arrays are hashed in memory — each member is decompressed exactly
    once per restore). Pre-digest files (no ``array_sha256`` key) pass
    — their container CRC is the only protection they ever had."""
    digests = meta.get("array_sha256")
    if digests is None:
        return
    for name, want in digests.items():
        if name not in arrays:
            raise CheckpointIntegrityError(
                f"checkpoint {filename}: array {name!r} missing"
            )
        got = _array_digest(arrays[name])
        if got != want:
            raise CheckpointIntegrityError(
                f"checkpoint {filename}: array {name!r} sha256 mismatch "
                f"(stored {want[:12]}…, recomputed {got[:12]}…) — the "
                "file is corrupt; falling back to an older generation "
                "is the resilience layer's job (CheckpointStore)"
            )


def verify_checkpoint(filename: str) -> dict:
    """Standalone integrity check: load the meta block and re-hash every
    array. Returns the meta dict on success; raises
    ``CheckpointIntegrityError`` (or the container's own zip/OS errors)
    on corruption. Does not touch any tally. Sharded generations
    (directories) route through the manifest check."""
    if is_sharded(filename):
        return verify_sharded_checkpoint(filename)
    filename = _normalize(filename)
    with np.load(filename) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        if meta.get("format_version") != FORMAT_VERSION:
            # An intact file of another format is a MISMATCH, not
            # corruption — plain ValueError, so CheckpointStore's
            # lookup rules treat it exactly like restore would.
            raise ValueError(
                f"checkpoint {filename}: format "
                f"{meta.get('format_version')} != {FORMAT_VERSION}"
            )
        arrays = {k: z[k] for k in z.files if k != "meta"}
        _verify_integrity(arrays, meta, filename)
    return meta


def load_meta(filename: str) -> dict:
    if is_sharded(filename):
        return _read_manifest(filename)["meta"]
    with np.load(_normalize(filename)) as z:
        return json.loads(bytes(z["meta"].tobytes()).decode())


# --------------------------------------------------------------------- #
# Sharded generations: per-part payload splits + two-phase manifest
# --------------------------------------------------------------------- #
def _file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def atomic_write_bytes(filename: str, data: bytes) -> None:
    """The ``atomic_savez`` durability contract for a small opaque blob
    (the manifest, the journal document, committed JSON baselines):
    tmp + fsync + rename + directory fsync.  Public alongside
    ``atomic_savez``/``fsync_dir`` — every module that persists durable
    state routes through one of these (graft-check PUMI008)."""
    directory = os.path.dirname(os.path.abspath(filename)) or "."
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(filename) + ".tmp-"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, filename)
        fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(filename: str, obj) -> None:
    """Committed-JSON convenience over ``atomic_write_bytes``: the
    repo's canonical serialization for captures/baselines/journals
    (indent=1, sorted keys, trailing newline) in one place, so the six
    writers cannot drift apart."""
    atomic_write_bytes(
        filename,
        (json.dumps(obj, indent=1, sort_keys=True) + "\n").encode(),
    )


def shard_name(index: int) -> str:
    return f"shard-{int(index):03d}.npz"


def save_sharded_checkpoint(
    dirname: str, tally, n_shards: int | None = None
) -> int:
    """Write one SHARDED generation with two-phase commit semantics.

    Phase 1 splits the facade payload into ``n_shards`` leading-axis
    chunks (one per mesh part by default — every payload array is
    per-particle, per-element, or per-slot, so a first-axis split is
    layout-independent and reassembly is a concatenation) and writes
    one digest-carrying npz per shard CONCURRENTLY through the
    existing atomic tmp+fsync+rename path. Phase 2 commits
    ``MANIFEST.json`` — the facade meta plus every shard's whole-file
    sha256 — atomically, LAST. A pre-existing manifest is removed
    BEFORE any shard is touched (un-commit), so a crash mid-rewrite
    leaves an invalid (manifest-less) directory — detected and
    skipped, never a manifest naming half-overwritten shards. NOTE:
    that means rewriting an existing generation IN PLACE sacrifices
    the old copy for the duration of the write; callers that must
    never lose the previous generation write to a fresh path (the
    ``CheckpointStore``'s per-iteration naming, plus the runner's
    skip of re-flushes onto valid generations, guarantee this).
    Returns the shard count written."""
    if hasattr(tally, "flux_slabs"):
        meta, arrays = _partitioned_payload(tally)
    else:
        meta, arrays = _plain_payload(tally)
    if n_shards is None:
        n_shards = int(getattr(tally, "n_parts", 1))
    n_shards = max(1, int(n_shards))
    os.makedirs(dirname, exist_ok=True)
    manifest_path = os.path.join(dirname, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        os.unlink(manifest_path)
        fsync_dir(dirname)
    chunks = {
        name: np.array_split(np.asarray(a), n_shards)
        for name, a in arrays.items()
    }

    def _write(i: int) -> str:
        shard_meta = {
            "format_version": FORMAT_VERSION,
            "shard": int(i),
            "n_shards": int(n_shards),
        }
        shard_arrays = {
            name: np.ascontiguousarray(chunks[name][i]) for name in arrays
        }
        return _write_checkpoint(
            os.path.join(dirname, shard_name(i)), shard_meta, shard_arrays
        )

    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(n_shards, 8)) as ex:
        paths = list(ex.map(_write, range(n_shards)))
    manifest = {
        "format_version": FORMAT_VERSION,
        "meta": meta,
        "n_shards": int(n_shards),
        "shards": {os.path.basename(p): _file_digest(p) for p in paths},
    }
    atomic_write_bytes(
        manifest_path, json.dumps(manifest, indent=1).encode()
    )
    return n_shards


def _read_manifest(dirname: str) -> dict:
    """Load the commit record; its ABSENCE (torn multi-shard write:
    the crash came before phase 2) is corruption by definition — the
    resilience layer must skip the whole generation."""
    manifest_path = os.path.join(dirname, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        raise CheckpointIntegrityError(
            f"sharded checkpoint {dirname}: {MANIFEST_NAME} missing — "
            "the generation was never committed (torn multi-shard "
            "write); falling back to an older generation is the "
            "resilience layer's job (CheckpointStore)"
        )
    try:
        with open(manifest_path, "rb") as f:
            manifest = json.loads(f.read().decode())
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"sharded checkpoint {dirname}: unreadable manifest ({e})"
        ) from e
    if manifest.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"sharded checkpoint {dirname}: format "
            f"{manifest.get('format_version')} != {FORMAT_VERSION}"
        )
    return manifest


def _verify_shard_files(dirname: str, manifest: dict) -> list[str]:
    """Every shard the manifest names must exist and hash clean; any
    miss rejects the WHOLE generation (atomic torn-write semantics).
    Returns shard paths in shard order."""
    shards = manifest.get("shards", {})
    if len(shards) != int(manifest.get("n_shards", -1)):
        raise CheckpointIntegrityError(
            f"sharded checkpoint {dirname}: manifest names "
            f"{len(shards)} shard(s) but declares "
            f"n_shards={manifest.get('n_shards')}"
        )
    def _index(name: str) -> int:
        # Numeric shard order, NOT lexicographic: %03d padding stops
        # helping past 999 shards ('shard-1000' < 'shard-101'
        # lexically), and a wrong order would concatenate the restore
        # silently scrambled — the exact Frankenstein class the
        # manifest exists to prevent.
        digits = "".join(c for c in name if c.isdigit())
        return int(digits) if digits else -1

    paths = []
    for name in sorted(shards, key=_index):
        path = os.path.join(dirname, name)
        if not os.path.exists(path):
            raise CheckpointIntegrityError(
                f"sharded checkpoint {dirname}: shard {name!r} missing"
            )
        got = _file_digest(path)
        if got != shards[name]:
            raise CheckpointIntegrityError(
                f"sharded checkpoint {dirname}: shard {name!r} sha256 "
                f"mismatch (manifest {shards[name][:12]}…, recomputed "
                f"{got[:12]}…) — torn or bit-rotted shard; the whole "
                "generation is rejected"
            )
        paths.append(path)
    return paths


def _load_sharded_arrays(dirname: str, manifest: dict) -> dict:
    """Digest-verify every shard file, then load and concatenate the
    per-shard chunks back into the full payload arrays (all BEFORE any
    tally state is overwritten)."""
    parts = []
    for path in _verify_shard_files(dirname, manifest):
        with np.load(path) as z:
            smeta = json.loads(bytes(z["meta"].tobytes()).decode())
            arrays = {k: z[k] for k in z.files if k != "meta"}
            _verify_integrity(arrays, smeta, path)
            parts.append(arrays)
    return {
        name: np.concatenate([p[name] for p in parts], axis=0)
        for name in parts[0]
    }


def verify_sharded_checkpoint(dirname: str) -> dict:
    """Standalone integrity check of a sharded generation: manifest
    present + every named shard exists and hashes clean. Returns the
    facade meta on success; ``CheckpointIntegrityError`` on any torn/
    corrupt condition (the whole generation is invalid)."""
    manifest = _read_manifest(dirname)
    _verify_shard_files(dirname, manifest)
    return manifest["meta"]


def _restore_sharded(dirname: str, tally, expected_kind) -> None:
    manifest = _read_manifest(dirname)
    meta = manifest["meta"]
    _validate_meta(meta, tally, expected_kind=expected_kind)
    arrays = _load_sharded_arrays(dirname, manifest)
    if expected_kind == "partitioned":
        _apply_partitioned(tally, meta, arrays)
    else:
        _apply_plain(tally, meta, arrays)


def _validate_meta(meta: dict, tally, expected_kind: str | None) -> None:
    """Shared restore-side validation: format, kind, mesh identity, dtype,
    run shape. Raises on any mismatch rather than silently resuming a
    different run (both facades)."""
    if meta["format_version"] != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {meta['format_version']} != "
            f"{FORMAT_VERSION}"
        )
    kind = meta.get("kind")
    if kind != expected_kind:
        raise ValueError(
            f"checkpoint kind {kind!r} does not match this facade "
            f"(expected {expected_kind!r}: use "
            f"{'PartitionedTally' if kind == 'partitioned' else 'PumiTally'}"
            ".restore_checkpoint for this file)"
        )
    if meta["mesh_fingerprint"] != mesh_fingerprint(tally.mesh):
        raise ValueError("checkpoint was written against a different mesh")
    ck_dt = meta.get("dtype")
    if ck_dt is not None and np.dtype(ck_dt) != np.dtype(
        tally.config.dtype
    ):
        raise ValueError(
            f"checkpoint dtype is {ck_dt} but this tally is configured "
            f"dtype={np.dtype(tally.config.dtype)}; restoring would "
            "silently cast the accumulator (e.g. f64 → f32 loses the "
            "precision contract) — rebuild the tally with the "
            "checkpoint's dtype"
        )
    ck_sd = meta.get("sd_mode", "segment")  # pre-r5 files: segment
    if ck_sd != getattr(tally.config, "sd_mode", "segment"):
        raise ValueError(
            f"checkpoint slot-1 statistic is sd_mode={ck_sd!r} but this "
            f"tally is configured sd_mode={tally.config.sd_mode!r}; "
            "per-segment and per-move batch squares cannot be mixed"
        )
    if meta["num_particles"] != tally.num_particles:
        raise ValueError(
            f"checkpoint has {meta['num_particles']} particles, tally "
            f"has {tally.num_particles}"
        )
    if meta["n_groups"] != tally.config.n_groups:
        raise ValueError(
            f"checkpoint has {meta['n_groups']} energy groups, config "
            f"has {tally.config.n_groups}"
        )


# --------------------------------------------------------------------- #
# Plain (single-chip) facade payload
# --------------------------------------------------------------------- #
def _plain_payload(tally) -> tuple[dict, dict]:
    s = tally.state
    meta = {
        "format_version": FORMAT_VERSION,
        "mesh_fingerprint": mesh_fingerprint(tally.mesh),
        "num_particles": tally.num_particles,
        "n_groups": tally.config.n_groups,
        "iter_count": tally.iter_count,
        "total_segments": tally.total_segments,
        "initialized": tally._initialized,
        "dtype": str(np.dtype(tally.config.dtype)),
        # Slot-1 statistic: per-segment squares vs per-move batch
        # squares are NOT mixable — validated on restore.
        "sd_mode": tally.config.sd_mode,
        # Adaptive-replan state: compact_stages='adaptive' replans the
        # ladder once from the FIRST move's measured stats; a resumed
        # run must reuse that ladder, not replan from a later move's
        # stats (different ladder -> different scatter grouping ->
        # ~1e-15 flux drift, breaking the bitwise-resume guarantee).
        "replanned": bool(tally._replanned),
        "compact_stages_planned": (
            [list(s) for s in tally._compact_stages]
            if tally._replanned and tally._compact_stages is not None
            else None
        ),
    }
    arrays = {
        # Canonical on-disk shape is [ntet, n_groups, 2] regardless of the
        # device layout (flat since round 4), so checkpoints stay portable
        # across layout changes.
        #
        # Every device-derived array is COPIED, never viewed:
        # np.asarray of a jax array can be a zero-copy view of the
        # device buffer on CPU, and the flux buffer is DONATED to the
        # next trace — a viewed "snapshot" would silently morph into
        # the post-move flux, doubling the move on a retry rollback
        # (snapshot_state is the ResilientRunner's retry anchor).
        "flux": np.array(tally.raw_flux, copy=True),
        "origin": np.array(s.origin, copy=True),
        "dest": np.array(s.dest, copy=True),
        "elem": np.array(s.elem, copy=True),
        "in_flight": np.array(s.in_flight, copy=True),
        "weight": np.array(s.weight, copy=True),
        "group": np.array(s.group, copy=True),
        "material_id": np.array(s.material_id, copy=True),
        "particle_id": np.array(s.particle_id, copy=True),
        "perm": (
            np.asarray(tally._perm)
            if tally._perm is not None
            else np.empty(0, np.int64)
        ),
        # Per-lane quarantine counts are resumable state: a resumed (or
        # retry-rolled-back) run must not lose or double its degraded-
        # mode report. Empty when the quarantine is off.
        "quarantined": (
            tally._quarantined.copy()
            if getattr(tally, "_quarantined", None) is not None
            else np.empty(0, np.int64)
        ),
    }
    return meta, arrays


def _apply_plain(tally, meta: dict, arrays: dict) -> None:
    import jax.numpy as jnp

    dtype = tally.config.dtype
    # Device accumulator is flat (api make_flux flat=True); accept
    # both 3-D (canonical/older) and flat on-disk arrays.
    tally.flux = jnp.asarray(arrays["flux"], dtype).reshape(-1)
    tally.state = tally.state._replace(
        origin=jnp.asarray(arrays["origin"], dtype),
        dest=jnp.asarray(arrays["dest"], dtype),
        elem=jnp.asarray(arrays["elem"], jnp.int32),
        in_flight=jnp.asarray(arrays["in_flight"], bool),
        weight=jnp.asarray(arrays["weight"], dtype),
        group=jnp.asarray(arrays["group"], jnp.int32),
        material_id=jnp.asarray(arrays["material_id"], jnp.int32),
        particle_id=jnp.asarray(arrays["particle_id"], jnp.int32),
    )
    tally.iter_count = int(meta["iter_count"])
    tally.total_segments = int(meta["total_segments"])
    tally._initialized = bool(meta["initialized"])
    perm = arrays["perm"]
    tally._perm = None if perm.size == 0 else perm.astype(np.int64)
    # Packed-pipeline derived state: re-derive the device-resident slot
    # permutation from the restored particle_id, and force the next
    # periodic sort to recompute its cached artifacts.
    if hasattr(tally, "_refresh_perm_device"):
        tally._refresh_perm_device()
    if hasattr(tally, "_traces_since_sort"):
        tally._traces_since_sort = 1
    if "replanned" in meta:
        tally._replanned = bool(meta["replanned"])
        planned = meta.get("compact_stages_planned")
        if tally._replanned and planned is not None:
            tally._compact_stages = tuple(
                tuple(int(x) for x in s) for s in planned
            )
    if hasattr(tally, "_reset_convergence"):
        # Batch statistics are monitor state, not resumable tally state:
        # re-base them on the restored accumulator (obs/convergence.py).
        tally._reset_convergence()
    _apply_quarantined(tally, arrays)
    if getattr(tally, "_prev_even", None) is not None:
        # sd_mode="batch": the even-entry snapshot is derived state —
        # the per-move fold runs after every move, so at any
        # checkpoint boundary it equals the current even entries.
        tally._prev_even = tally.flux[0::2]


def save_checkpoint(filename: str, tally, n_shards: int | None = None) -> None:
    """Serialize a PumiTally's resumable state (atomic write + per-array
    digests, see module docstring). A ``.shards`` filename writes the
    sharded two-phase layout instead (``n_shards`` splits)."""
    if is_sharded(filename):
        save_sharded_checkpoint(filename, tally, n_shards=n_shards)
        return
    meta, arrays = _plain_payload(tally)
    _write_checkpoint(_normalize(filename), meta, arrays)


def restore_checkpoint(filename: str, tally) -> None:
    """Restore state saved by save_checkpoint into a PumiTally constructed
    with the same mesh and config. Raises on any mismatch or integrity
    failure BEFORE overwriting any tally state."""
    if is_sharded(filename):
        _restore_sharded(filename, tally, expected_kind=None)
        return
    with np.load(_normalize(filename)) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        _validate_meta(meta, tally, expected_kind=None)
        arrays = {k: z[k] for k in z.files if k != "meta"}
        _verify_integrity(arrays, meta, filename)
    _apply_plain(tally, meta, arrays)


def _apply_quarantined(tally, arrays: dict) -> None:
    """Restore the per-lane quarantine counts where both sides track
    them (quarantine on, payload carries a matching array)."""
    q = arrays.get("quarantined")
    if (
        getattr(tally, "_quarantined", None) is not None
        and q is not None
        and q.size == tally._quarantined.size
    ):
        tally._quarantined = np.asarray(q, np.int64).copy()


# --------------------------------------------------------------------- #
# Partitioned facade payload
# --------------------------------------------------------------------- #
def _partitioned_payload(tally) -> tuple[dict, dict]:
    # Device-sourced megastep state folds back to the host mirrors
    # first (run_source_moves keeps slot state device-resident between
    # dispatches); the slot layout itself is ALSO persisted below so a
    # same-layout restore resumes bitwise (re-distributing from the
    # per-particle fields would re-bucket slots and change the flux
    # summation order).
    if getattr(tally, "_src", None) is not None:
        tally._sync_source_state()
    meta = {
        "format_version": FORMAT_VERSION,
        "kind": "partitioned",
        "mesh_fingerprint": mesh_fingerprint(tally.mesh),
        "num_particles": tally.num_particles,
        "n_groups": tally.config.n_groups,
        "iter_count": tally.iter_count,
        "total_segments": tally.total_segments,
        "total_rounds": tally.total_rounds,
        "initialized": tally._initialized,
        "dtype": str(np.dtype(tally.config.dtype)),
        "sd_mode": tally.config.sd_mode,
    }
    arrays = {
        # raw_flux assembles a fresh host array, but copy defensively
        # for the same donation-aliasing reason as the plain payload.
        "flux": np.array(tally.raw_flux, copy=True),
        "positions": tally.positions.copy(),
        "elem_global": tally.elem_global.copy(),
        "material_id": tally.material_id.copy(),
        "quarantined": (
            tally._quarantined.copy()
            if getattr(tally, "_quarantined", None) is not None
            else np.empty(0, np.int64)
        ),
    }
    if hasattr(tally, "weights"):
        # Persistent physics lanes of the device-sourced move loop.
        arrays["weights"] = np.asarray(tally.weights).copy()
        arrays["groups"] = np.asarray(tally.groups).copy()
        arrays["alive"] = np.asarray(tally.alive).copy()
    if getattr(tally, "_src", None) is not None:
        meta["src_layout"] = [int(tally.n_parts), int(tally.cap)]
        for name, arr in tally._src.items():
            arrays[f"src_{name}"] = np.array(
                np.asarray(arr), copy=True
            )
    return meta, arrays


def _apply_partitioned(tally, meta: dict, arrays: dict) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh_partition import disassemble_global_flux
    from ..parallel.particle_sharding import PARTICLE_AXIS

    slabs = disassemble_global_flux(
        tally.partition,
        np.asarray(arrays["flux"]).astype(np.dtype(tally.config.dtype)),
    )
    # Device slabs are FLAT per chip (partitioned_api flux_slabs).
    tally.flux_slabs = jax.device_put(
        jnp.asarray(slabs.reshape(slabs.shape[0], -1)),
        NamedSharding(tally.device_mesh, P(PARTICLE_AXIS)),
    )
    tally.positions = np.asarray(arrays["positions"]).copy()
    tally.elem_global = np.asarray(arrays["elem_global"]).copy()
    tally.material_id = np.asarray(arrays["material_id"]).copy()
    if hasattr(tally, "weights") and "weights" in arrays:
        tally.weights = np.asarray(arrays["weights"], np.float64).copy()
        tally.groups = np.asarray(arrays["groups"], np.int32).copy()
        tally.alive = np.asarray(arrays["alive"]).astype(bool).copy()
    if hasattr(tally, "_src"):
        # Megastep slot state: rebuild the exact device layout when the
        # checkpoint's partition shape matches (bitwise resume of the
        # device-sourced loop); otherwise drop it — the next
        # run_source_moves re-distributes from the per-particle fields
        # (correct, but the flux summation order may differ).
        layout = meta.get("src_layout")
        if layout is not None and layout == [
            int(tally.n_parts), int(tally.cap)
        ]:
            sh = NamedSharding(tally.device_mesh, P(PARTICLE_AXIS))
            dtype = tally.config.dtype
            src = {}
            for name in ("pos", "elem", "material_id", "weight",
                         "group", "pid", "valid", "alive"):
                arr = jnp.asarray(arrays[f"src_{name}"])
                if name in ("pos", "weight"):
                    arr = arr.astype(dtype)
                src[name] = jax.device_put(arr, sh)
            tally._src = src
        else:
            tally._src = None
    tally.iter_count = int(meta["iter_count"])
    tally.total_segments = int(meta["total_segments"])
    tally.total_rounds = int(meta["total_rounds"])
    tally._initialized = bool(meta["initialized"])
    _apply_quarantined(tally, arrays)
    if getattr(tally, "_prev_even", None) is not None:
        # Batch-sd snapshot is derived state (== current even
        # entries at any move boundary), re-slabbed alongside flux.
        tally._prev_even = tally.flux_slabs[:, 0::2]
    if hasattr(tally, "_reset_convergence"):
        # Batch statistics re-base on the restored slabs (see
        # _apply_plain).
        tally._reset_convergence()


def save_partitioned_checkpoint(
    filename: str, tally, n_shards: int | None = None
) -> None:
    """Serialize a PartitionedTally's resumable state.

    The flux is stored ASSEMBLED (global element order), so a checkpoint
    is partition-layout independent: it can resume under a different
    part count or halo depth (the owned-slab layout is derived state).
    Particle state is the facade's host-side arrays. Atomic write +
    per-array digests like the plain facade. A ``.shards`` filename
    writes the sharded two-phase layout (one npz per mesh part by
    default + manifest committed last) instead.
    """
    if is_sharded(filename):
        save_sharded_checkpoint(filename, tally, n_shards=n_shards)
        return
    meta, arrays = _partitioned_payload(tally)
    _write_checkpoint(_normalize(filename), meta, arrays)


def restore_partitioned_checkpoint(filename: str, tally) -> None:
    """Restore state saved by save_partitioned_checkpoint into a
    PartitionedTally on the same mesh (any partition layout). Validation
    and integrity checks run BEFORE any state is overwritten."""
    if is_sharded(filename):
        _restore_sharded(filename, tally, expected_kind="partitioned")
        return
    with np.load(_normalize(filename)) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        _validate_meta(meta, tally, expected_kind="partitioned")
        arrays = {k: z[k] for k in z.files if k != "meta"}
        _verify_integrity(arrays, meta, filename)
    _apply_partitioned(tally, meta, arrays)


# --------------------------------------------------------------------- #
# In-memory snapshots (the ResilientRunner's retry anchor)
# --------------------------------------------------------------------- #
def snapshot_state(tally) -> tuple:
    """Host-side copy of the resumable state — the same payload a
    checkpoint file carries, without serialization. Cheap relative to a
    checkpoint write; the runner takes one after every successful move
    so a transient device failure can roll back WITHOUT losing the
    moves since the last on-disk generation."""
    if hasattr(tally, "flux_slabs"):
        meta, arrays = _partitioned_payload(tally)
        return ("partitioned", meta, arrays)
    meta, arrays = _plain_payload(tally)
    return ("plain", meta, arrays)


def restore_state(tally, snap: tuple) -> None:
    """Apply a ``snapshot_state`` payload back onto the tally it came
    from (no validation — same-process, same-object roll-back)."""
    kind, meta, arrays = snap
    if kind == "partitioned":
        _apply_partitioned(tally, meta, arrays)
    else:
        _apply_plain(tally, meta, arrays)
