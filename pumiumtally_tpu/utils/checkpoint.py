"""Checkpoint / resume for tally runs.

The reference has no persistence besides the final VTK write (SURVEY.md §5:
"Checkpoint / resume. Absent.") — but its state is additive, so the natural
checkpoint is exactly (flux accumulator, particle state, iteration counter).
This module saves/restores that as a single compressed ``.npz`` with a mesh
fingerprint so a checkpoint can never be resumed against a different mesh.

Used by ``PumiTally.save_checkpoint`` / ``PumiTally.restore_checkpoint``;
host-side glue, not a hot path.
"""
from __future__ import annotations

import hashlib
import json

import numpy as np

FORMAT_VERSION = 1


def mesh_fingerprint(mesh) -> str:
    """Stable content hash of the mesh the tally ran on (connectivity +
    coordinates + region ids)."""
    h = hashlib.sha256()
    for arr in (mesh.tet2vert, mesh.coords, mesh.class_id):
        a = np.ascontiguousarray(np.asarray(arr))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _normalize(filename: str) -> str:
    # np.savez_compressed silently appends ".npz"; normalize on both the
    # save and load side so any filename round-trips.
    return filename if filename.endswith(".npz") else filename + ".npz"


def save_checkpoint(filename: str, tally) -> None:
    """Serialize a PumiTally's resumable state."""
    filename = _normalize(filename)
    s = tally.state
    meta = {
        "format_version": FORMAT_VERSION,
        "mesh_fingerprint": mesh_fingerprint(tally.mesh),
        "num_particles": tally.num_particles,
        "n_groups": tally.config.n_groups,
        "iter_count": tally.iter_count,
        "total_segments": tally.total_segments,
        "initialized": tally._initialized,
        "dtype": str(np.dtype(tally.config.dtype)),
        # Slot-1 statistic: per-segment squares vs per-move batch
        # squares are NOT mixable — validated on restore.
        "sd_mode": tally.config.sd_mode,
    }
    np.savez_compressed(
        filename,
        meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        # Canonical on-disk shape is [ntet, n_groups, 2] regardless of the
        # device layout (flat since round 4), so checkpoints stay portable
        # across layout changes.
        flux=np.asarray(tally.raw_flux),
        origin=np.asarray(s.origin),
        dest=np.asarray(s.dest),
        elem=np.asarray(s.elem),
        in_flight=np.asarray(s.in_flight),
        weight=np.asarray(s.weight),
        group=np.asarray(s.group),
        material_id=np.asarray(s.material_id),
        particle_id=np.asarray(s.particle_id),
        perm=(
            np.asarray(tally._perm)
            if tally._perm is not None
            else np.empty(0, np.int64)
        ),
    )


def load_meta(filename: str) -> dict:
    with np.load(_normalize(filename)) as z:
        return json.loads(bytes(z["meta"].tobytes()).decode())


def _validate_meta(meta: dict, tally, expected_kind: str | None) -> None:
    """Shared restore-side validation: format, kind, mesh identity, run
    shape. Raises on any mismatch rather than silently resuming a
    different run (both facades)."""
    if meta["format_version"] != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {meta['format_version']} != "
            f"{FORMAT_VERSION}"
        )
    kind = meta.get("kind")
    if kind != expected_kind:
        raise ValueError(
            f"checkpoint kind {kind!r} does not match this facade "
            f"(expected {expected_kind!r}: use "
            f"{'PartitionedTally' if kind == 'partitioned' else 'PumiTally'}"
            ".restore_checkpoint for this file)"
        )
    if meta["mesh_fingerprint"] != mesh_fingerprint(tally.mesh):
        raise ValueError("checkpoint was written against a different mesh")
    ck_sd = meta.get("sd_mode", "segment")  # pre-r5 files: segment
    if ck_sd != getattr(tally.config, "sd_mode", "segment"):
        raise ValueError(
            f"checkpoint slot-1 statistic is sd_mode={ck_sd!r} but this "
            f"tally is configured sd_mode={tally.config.sd_mode!r}; "
            "per-segment and per-move batch squares cannot be mixed"
        )
    if meta["num_particles"] != tally.num_particles:
        raise ValueError(
            f"checkpoint has {meta['num_particles']} particles, tally "
            f"has {tally.num_particles}"
        )
    if meta["n_groups"] != tally.config.n_groups:
        raise ValueError(
            f"checkpoint has {meta['n_groups']} energy groups, config "
            f"has {tally.config.n_groups}"
        )


def restore_checkpoint(filename: str, tally) -> None:
    """Restore state saved by save_checkpoint into a PumiTally constructed
    with the same mesh and config. Raises on any mismatch rather than
    silently resuming a different run."""
    import jax.numpy as jnp

    with np.load(_normalize(filename)) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        _validate_meta(meta, tally, expected_kind=None)
        dtype = tally.config.dtype
        # Device accumulator is flat (api make_flux flat=True); accept
        # both 3-D (canonical/older) and flat on-disk arrays.
        tally.flux = jnp.asarray(z["flux"], dtype).reshape(-1)
        tally.state = tally.state._replace(
            origin=jnp.asarray(z["origin"], dtype),
            dest=jnp.asarray(z["dest"], dtype),
            elem=jnp.asarray(z["elem"], jnp.int32),
            in_flight=jnp.asarray(z["in_flight"], bool),
            weight=jnp.asarray(z["weight"], dtype),
            group=jnp.asarray(z["group"], jnp.int32),
            material_id=jnp.asarray(z["material_id"], jnp.int32),
            particle_id=jnp.asarray(z["particle_id"], jnp.int32),
        )
        tally.iter_count = int(meta["iter_count"])
        tally.total_segments = int(meta["total_segments"])
        tally._initialized = bool(meta["initialized"])
        perm = z["perm"]
        tally._perm = None if perm.size == 0 else perm.astype(np.int64)
        if getattr(tally, "_prev_even", None) is not None:
            # sd_mode="batch": the even-entry snapshot is derived state —
            # the per-move fold runs after every move, so at any
            # checkpoint boundary it equals the current even entries.
            tally._prev_even = tally.flux[0::2]


def save_partitioned_checkpoint(filename: str, tally) -> None:
    """Serialize a PartitionedTally's resumable state.

    The flux is stored ASSEMBLED (global element order), so a checkpoint
    is partition-layout independent: it can resume under a different
    part count or halo depth (the owned-slab layout is derived state).
    Particle state is the facade's host-side arrays.
    """
    filename = _normalize(filename)
    meta = {
        "format_version": FORMAT_VERSION,
        "kind": "partitioned",
        "mesh_fingerprint": mesh_fingerprint(tally.mesh),
        "num_particles": tally.num_particles,
        "n_groups": tally.config.n_groups,
        "iter_count": tally.iter_count,
        "total_segments": tally.total_segments,
        "total_rounds": tally.total_rounds,
        "initialized": tally._initialized,
        "dtype": str(np.dtype(tally.config.dtype)),
        "sd_mode": tally.config.sd_mode,
    }
    np.savez_compressed(
        filename,
        meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        flux=np.asarray(tally.raw_flux),
        positions=tally.positions,
        elem_global=tally.elem_global,
        material_id=tally.material_id,
    )


def restore_partitioned_checkpoint(filename: str, tally) -> None:
    """Restore state saved by save_partitioned_checkpoint into a
    PartitionedTally on the same mesh (any partition layout)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.particle_sharding import PARTICLE_AXIS

    with np.load(_normalize(filename)) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        _validate_meta(meta, tally, expected_kind="partitioned")
        from ..parallel.mesh_partition import disassemble_global_flux

        slabs = disassemble_global_flux(
            tally.partition,
            z["flux"].astype(np.dtype(tally.config.dtype)),
        )
        # Device slabs are FLAT per chip (partitioned_api flux_slabs).
        tally.flux_slabs = jax.device_put(
            jnp.asarray(slabs.reshape(slabs.shape[0], -1)),
            NamedSharding(tally.device_mesh, P(PARTICLE_AXIS)),
        )
        tally.positions = z["positions"].copy()
        tally.elem_global = z["elem_global"].copy()
        tally.material_id = z["material_id"].copy()
        tally.iter_count = int(meta["iter_count"])
        tally.total_segments = int(meta["total_segments"])
        tally.total_rounds = int(meta["total_rounds"])
        tally._initialized = bool(meta["initialized"])
        if getattr(tally, "_prev_even", None) is not None:
            # Batch-sd snapshot is derived state (== current even
            # entries at any move boundary), re-slabbed alongside flux.
            tally._prev_even = tally.flux_slabs[:, 0::2]
