"""Structured logging for the framework.

The reference logs with bare ``printf`` tagged ``[INFO]``/``[ERROR]``/
``[TIME]`` and has no levels or structure (SURVEY.md §5; reference
.cpp:26-33, 533-534, 873). Here the same tags ride on the stdlib logging
machinery: levels, an env-controlled threshold (``PUMI_TPU_LOG=debug``),
and an optional JSON-lines mode (``PUMI_TPU_LOG_JSON=1``) for machine
consumption of timing/metric records.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import time

_LOGGER_NAME = "pumiumtally_tpu"
_TAGS = {
    logging.DEBUG: "[DEBUG]",
    logging.INFO: "[INFO]",
    logging.WARNING: "[WARN]",
    logging.ERROR: "[ERROR]",
}


class _TagFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        if os.environ.get("PUMI_TPU_LOG_JSON") == "1":
            payload = {
                "ts": round(time.time(), 3),
                "level": record.levelname.lower(),
                "msg": record.getMessage(),
            }
            extra = getattr(record, "fields", None)
            if extra:
                payload.update(extra)
            return json.dumps(payload)
        tag = getattr(record, "tag", None) or _TAGS.get(
            record.levelno, f"[{record.levelname}]"
        )
        fields = getattr(record, "fields", None)
        rendered = getattr(record, "fields_in_message", ())
        if fields and rendered:
            # Drop only the fields already present in the message text;
            # caller-supplied extras still print.
            fields = {k: v for k, v in fields.items() if k not in rendered}
        suffix = (
            " " + " ".join(f"{k}={v}" for k, v in fields.items())
            if fields
            else ""
        )
        return f"{tag} {record.getMessage()}{suffix}"


class _StderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at emit time (not at handler creation), so
    stream redirection — pytest capsys, host-side log capture — works."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = _StderrHandler()
        handler.setFormatter(_TagFormatter())
        logger.addHandler(handler)
        logger.propagate = False
        level = os.environ.get("PUMI_TPU_LOG", "info").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
    return logger


def log_info(msg: str, **fields) -> None:
    get_logger().info(msg, extra={"fields": fields} if fields else None)


def log_debug(msg: str, **fields) -> None:
    get_logger().debug(msg, extra={"fields": fields} if fields else None)


def log_warn(msg: str, **fields) -> None:
    get_logger().warning(msg, extra={"fields": fields} if fields else None)


def log_error(msg: str, **fields) -> None:
    get_logger().error(msg, extra={"fields": fields} if fields else None)


def metrics_path() -> str | None:
    """Path of the JSONL metrics sink, from ``PUMI_TPU_METRICS=jsonl:/path``
    (the obs flight recorder's emission channel). None when unset or when
    the spec names an unknown scheme — metric emission is best-effort and
    must never take a run down."""
    spec = os.environ.get("PUMI_TPU_METRICS", "")
    if spec.startswith("jsonl:"):
        return spec[len("jsonl:"):] or None
    return None


_metric_sink_warned: set = set()


def emit_metric(fields: dict, path: str | None = None) -> None:
    """Emit one metrics record: a debug-level record through the logger
    (so ``PUMI_TPU_LOG_JSON=1`` renders it with the same JSON machinery
    as every other record), plus one appended JSON line to the
    ``PUMI_TPU_METRICS=jsonl:<path>`` sink when configured. The JSONL
    payload mirrors the log formatter's shape: ts + level + msg, then
    the flat fields. Best-effort: an unwritable sink logs one warning
    per path and never takes the run down."""
    kind = str(fields.get("kind", "metric"))
    get_logger().debug(
        kind, extra={"fields": fields, "tag": "[METRIC]"}
    )
    path = path or metrics_path()
    if not path:
        return
    payload = {
        "ts": round(time.time(), 3),
        "level": "metric",
        "msg": kind,
        **fields,
    }
    try:
        with open(path, "a") as f:
            f.write(json.dumps(payload, default=str) + "\n")
    except OSError as e:
        if path not in _metric_sink_warned:
            _metric_sink_warned.add(path)
            get_logger().warning(
                f"metrics sink {path!r} unwritable ({e}); dropping "
                "metric records for this path"
            )


def log_time(phase: str, seconds: float, **fields) -> None:
    """[TIME]-tagged record (TallyTimes print parity, reference .cpp:26-33).
    The phase/seconds fields feed the JSON mode; the text mode already has
    them in the message."""
    get_logger().info(
        f"{phase}: {seconds:.6f} s",
        extra={
            "fields": {
                "phase": phase, "seconds": round(seconds, 6), **fields
            },
            "fields_in_message": ("phase", "seconds"),
            "tag": "[TIME]",
        },
    )
