"""Per-phase wall-clock accumulators (TallyTimes parity).

Mirrors the reference's TallyTimes struct and its facade-level chrono
wrappers (pumipic_particle_data_structure.cpp:19-35, 923-957). Device work
is asynchronous under JAX exactly as under CUDA, so — like the reference's
PUMI_MEASURE_TIME-guarded Kokkos::fence() (cpp:216-218, 259-261) — timed
sections call jax.block_until_ready only when measurement is enabled.
"""
from __future__ import annotations

import contextlib
import dataclasses
import time

import jax


@dataclasses.dataclass
class TallyTimes:
    initialization_time: float = 0.0
    total_time_to_tally: float = 0.0
    vtk_file_write_time: float = 0.0
    # Moves accumulated into total_time_to_tally — the reference prints
    # its iteration count with the timers (cpp:923-957); carrying it
    # here closes that parity gap and prices the per-move cost directly.
    n_moves: int = 0

    def print_times(self) -> None:
        from .log import log_time

        total = (
            self.initialization_time
            + self.total_time_to_tally
            + self.vtk_file_write_time
        )
        log_time("initialization", self.initialization_time)
        log_time("tally", self.total_time_to_tally, n_moves=self.n_moves)
        if self.n_moves:
            log_time(
                "tally_per_move",
                self.total_time_to_tally / self.n_moves,
                n_moves=self.n_moves,
            )
        log_time("vtk_write", self.vtk_file_write_time)
        log_time("total", total)


class phase_timer(contextlib.AbstractContextManager):
    """Accumulate elapsed wall-clock into ``times.<field>``; when enabled,
    call .sync(x) inside the block to register device output to block on
    before the clock is read (the PUMI_MEASURE_TIME Kokkos::fence analog)."""

    def __init__(self, times: TallyTimes, field: str, enabled: bool):
        self._times, self._field, self._enabled = times, field, enabled
        self._sync = None

    def sync(self, x):
        self._sync = x
        return x

    def __enter__(self):
        if self._enabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._enabled:
            if self._sync is not None:
                jax.block_until_ready(self._sync)
            setattr(
                self._times,
                self._field,
                getattr(self._times, self._field)
                + (time.perf_counter() - self._start),
            )
        return False
