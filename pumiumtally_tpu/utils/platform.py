"""Backend-selection helpers shared by the benchmark/capture scripts."""
from __future__ import annotations

import os


def maybe_force_cpu() -> bool:
    """Pin JAX to the CPU backend when PUMI_FORCE_CPU=1.

    Env ``JAX_PLATFORMS=cpu`` is overridden by the site's TPU plugin
    registration; only the config update reliably wins (see
    tests/conftest.py). Lets benches/sweeps run (as rehearsal, or while
    the TPU tunnel is down — numbers are then CPU-only, not
    comparable). Call after ``import jax`` but before any backend use.
    Returns True when the override was applied.
    """
    if os.environ.get("PUMI_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
