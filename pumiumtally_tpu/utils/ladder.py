"""Compaction-ladder planning from crossing-count statistics.

The walk's cost is executed SLOTS — Σ stage_width × stage_span — plus a
fixed cost per compaction round; both are set entirely by the
distribution of boundary crossings per move (the "decay curve") and the
schedule. This module turns a decay curve into a schedule:

  * :func:`survivors` — decay curve from measured per-particle crossing
    counts (``record_xpoints=1`` walk, or ``n_segments/n`` for just the
    mean);
  * :func:`exp_survivors` — analytic curve for a given mean
    crossings/move (exponential path lengths through a uniform mesh —
    the bench workload's measured curve matches this family);
  * :func:`simulate_ladder` — EXECUTIONAL cost model: a histogram of
    remaining iterations is advanced stage by stage exactly as
    ops/walk.py schedules lanes, so stages narrower than the live count
    price their deferred overflow honestly (the round-4 planner's
    "fake-cheap overflow" caveat is gone — and the measurement says
    moderate under-width stages are genuinely cheap: the dense ladder's
    model estimate matched hardware within 1%, BENCHMARKS.md r4 grid);
  * :func:`plan_stages` — beam search over (start, width) sequences
    under the executional model.

Cost calibration (round-4 hardware fit, scripts/fit_ladder_model.py):
time ≈ 81 ns/slot + 110 ms/round on the v5e bench config — a round
costs ≈ 1.3·n_particles slot-equivalents (its fixed part is the
first_k_active scans + gather/scatter over the full batch). The
round-4 DP assumed 250k (5× too cheap) and pinned widths ≥ the live
count; both biases pushed it away from the measured-best dense ladder.
Reference analog: the schedule exists to keep the GPU-resident walk of
pumipic_particle_data_structure.cpp's search loop from running every
lane to the slowest straggler; the reference has no equivalent knob.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "survivors",
    "exp_survivors",
    "simulate_ladder",
    "plan_stages",
]


def survivors(counts: np.ndarray, kmax: int | None = None) -> np.ndarray:
    """active[k] = lanes needing body iteration k, from measured
    per-particle crossing counts (a lane with c crossings executes c+1
    iterations; the last reaches the destination).

    active[k] = #{lanes with iterations > k} — so active[0] is every
    lane and a 0-crossing lane (1 iteration) contributes to active[0]
    only. (scripts/plan_ladder.py's variant of this shifts by one
    iteration — a 1-in-~15 bias at bench statistics; kept there
    unchanged as the round-4 historical model, fixed here.)"""
    counts = np.asarray(counts)
    iters = counts + 1
    if kmax is None:
        kmax = int(iters.max()) + 1
    hist = np.bincount(np.minimum(iters, kmax), minlength=kmax + 1)
    return (iters.size - np.cumsum(hist)).astype(float)


def exp_survivors(n: int, mean_crossings: float,
                  kmax: int | None = None) -> np.ndarray:
    """Analytic decay curve: crossings/move ~ Exponential(mean).

    Matches the measured bench curve family (mean 14.9 at the 55-cell
    mesh; crossings/move scale with move length × mesh density, so the
    mean scales ∝ cells when reusing the calibration on a denser
    mesh)."""
    m = max(float(mean_crossings), 0.25)
    if kmax is None:
        kmax = int(np.ceil(m * 12)) + 2
    k = np.arange(kmax + 1, dtype=float)
    # iterations = crossings + 1 → survivors(k) = P(crossings >= k) at
    # k-1; P(c >= x) = exp(-x/m) for the exponential family.
    return n * np.exp(-np.maximum(k - 1, 0) / m)


def _chunk(span: int, unroll: int) -> int:
    return -(-max(span, 0) // unroll) * unroll


def _advance(hist: np.ndarray, width: float, span: int, unroll: int = 1):
    """Advance min(width, active) lanes of `hist` (remaining-iteration
    histogram; index 0 = done) by `span` iterations, selecting lanes
    PROPORTIONALLY across buckets — the expectation of ops/walk.py's
    first-k-by-index pick, which is index-random w.r.t. remaining work.
    Returns (new_hist, executed_span) where executed_span <= span stops
    at the selected lanes' max remaining ROUNDED UP to an unroll chunk
    (the real while_loop's exit check runs between chunks, so a lane
    with 3 remaining still costs a full 8-iteration chunk at
    unroll=8)."""
    active = hist[1:].sum()
    if active <= 0:
        return hist, 0
    nz = np.nonzero(hist[1:])[0]
    max_rem = int(nz[-1]) + 1
    run = min(span, _chunk(max_rem, unroll))
    f = min(width / active, 1.0)
    sel = hist * f
    sel[0] = 0.0
    out = hist - sel
    # Selected lanes with remaining r move to max(r - run, 0).
    shifted = np.zeros_like(out)
    r = np.arange(len(hist))
    dst = np.maximum(r - run, 0)
    np.add.at(shifted, dst, sel)
    return out + shifted, run


def simulate_ladder(
    active_or_hist: np.ndarray,
    n: float,
    stages: tuple,
    *,
    unroll: int = 8,
    round_cost: float | None = None,
    max_crossings: int | None = None,
) -> tuple[float, int]:
    """Executed (slots, rounds) of `stages` under the executional model.

    `active_or_hist` is a survivors curve (monotone non-increasing —
    converted internally) or a remaining-iteration histogram. Every
    phase runs in `unroll`-sized chunks. Returns (slots, rounds);
    apply your own per-slot/per-round costs (fit_ladder_model.py's
    hardware fit, or plan_stages' default)."""
    a = np.asarray(active_or_hist, float)
    if len(a) >= 2 and np.all(np.diff(a) <= 1e-9):
        # survivors curve a[k] = #{iterations > k} → remaining-iteration
        # histogram hist[r] = #{iterations == r} = a[r-1] - a[r] for
        # r >= 1, hist[0] = 0, plus a tail bucket a[-1] for lanes
        # clipped past the curve's end.
        hist = np.concatenate([[0.0], -np.diff(a), [a[-1]]])
    else:
        hist = a.copy()
    kmax = len(hist) + 2
    slots, rounds = 0.0, 0

    stages = tuple(stages)
    first = stages[0][0] if stages else (max_crossings or kmax)
    # Phase 1: full width to the first stage start.
    h, run = _advance(hist, n, _chunk(first, unroll), unroll)
    slots += n * run
    hist = h
    for i, st in enumerate(stages):
        start, width = int(st[0]), float(st[1])
        if hist[1:].sum() <= 0:
            break
        if i + 1 < len(stages):
            span = _chunk(int(stages[i + 1][0]) - start, unroll)
            hist, run = _advance(hist, width, span, unroll)
            slots += width * run
            rounds += 1
        else:
            # Final stage: rounds of `width` to completion (bounded the
            # way the real walk bounds them: ceil(n/width)+1).
            guard = int(-(-n // max(width, 1))) + 2
            while hist[1:].sum() > 0 and guard > 0:
                hist, run = _advance(
                    hist, width, _chunk(kmax, unroll), unroll
                )
                slots += width * run
                rounds += 1
                guard -= 1
    if round_cost is not None:
        return slots + rounds * round_cost, rounds
    return slots, rounds


def plan_stages(
    n_particles: int,
    mean_crossings: float,
    *,
    counts: np.ndarray | None = None,
    unroll: int = 8,
    round_cost: float | None = None,
    width_floor: int | None = None,
    passes: int = 4,
) -> tuple:
    """Plan a compaction ladder for the given crossing statistics.

    Uses the measured decay (``counts``, per-particle crossing counts)
    when provided, else the analytic exponential family at
    ``mean_crossings``. ``round_cost`` defaults to 1.3·n_particles
    slot-equivalents — the round-4 hardware fit (110 ms/round ÷ 81
    ns/slot at n=1M; the fixed part of a round — first_k_active scans,
    gather/scatter — scales with the batch).

    Construction: seed with the HUG ladder — a stage at every survivor
    halving of the decay curve, each width the live count rounded up
    (the shape of the measured-best dense ladder, generalized to the
    curve at hand) — then hill-climb under :func:`simulate_ladder`'s
    executional score (shift starts, rescale widths, drop stages)
    until no move improves. The result is >= the hug seed by
    construction, and the seed reproduces the dense ladder's score at
    the bench statistics. (A cost-so-far beam search was tried first
    and rejected: states that under-serve lanes look locally cheap and
    crowd out the hug family.) Returns ((start, width), ...); possibly
    empty — small batches plan no ladder."""
    n = float(n_particles)
    if counts is not None:
        act = survivors(np.asarray(counts))
        act = act * (n / act[0])
    else:
        act = exp_survivors(n, mean_crossings)
    if round_cost is None:
        round_cost = 1.3 * n
    if width_floor is None:
        width_floor = max(int(n) // 128, 64)
    kmax = len(act) - 1
    gran = 4096 if n >= 65536 else 64

    def hug(a):
        w = -(-int(np.ceil(a)) // gran) * gran
        return int(min(max(w, width_floor), n))

    def score(stages):
        slots, rounds = simulate_ladder(
            act, n, stages, unroll=unroll, max_crossings=kmax + 2
        )
        return slots + rounds * round_cost

    # Seed: a stage wherever the survivor count halves, width hugging
    # the live count from above (dense-ladder shape).
    starts = []
    j = 1
    while n / 2**j >= width_floor and j < 32:
        k = int(np.searchsorted(-act, -(n / 2**j), side="left"))
        k = max(4, -(-k // 4) * 4)
        if k >= kmax:
            break
        if not starts or k > starts[-1]:
            starts.append(k)
        j += 1
    sched = tuple((k, hug(act[min(k, kmax)])) for k in starts)
    if not sched:
        return ()
    best = (score(sched), sched)

    def neighbors(stages):
        for i in range(len(stages)):
            k, w = stages[i]
            lo = stages[i - 1][0] if i else 0
            hi = stages[i + 1][0] if i + 1 < len(stages) else kmax
            for dk in (-8, -4, 4, 8):
                k2 = k + dk
                if lo < k2 < hi:
                    yield stages[:i] + ((k2, hug(act[min(k2, kmax)])),
                                        ) + stages[i + 1:]
            for f in (0.5, 0.75, 1.5):
                w2 = int(min(max(w * f, width_floor), n))
                if w2 != w:
                    yield stages[:i] + ((k, w2),) + stages[i + 1:]
            yield stages[:i] + stages[i + 1:]  # drop the stage

    for _ in range(passes):
        improved = False
        for cand in list(neighbors(best[1])):
            s = score(cand)
            if s < best[0] - 1e-6:
                best = (s, cand)
                improved = True
        if not improved:
            break
    if score(()) <= best[0]:
        return ()
    return best[1]
