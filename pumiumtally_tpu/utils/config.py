"""Runtime configuration for the tally framework.

Every hardcoded constant in the reference becomes a config field here
(reference: pumipic_particle_data_structure.cpp:123,206 tolerance 1e-8;
.cpp:256 migration period 100; .cpp:531 hardcoded 2 energy groups;
.cpp:298 output filename "fluxresult.vtk"; .cpp:789 GPU launch shape).
"""
from __future__ import annotations


def dense_ladder(n_particles: int) -> tuple:
    """The slot-planned dense compaction ladder (``compact_stages="auto"``
    and the benchmark default — one definition for both): stage widths
    track an exponential active-lane decay with mean ~15 crossings/move
    (scripts/plan_ladder.py scores it at 26.4 Mslots/step vs the
    3-stage schedule's 45.8 at bench statistics)."""
    M = n_particles
    return (
        (8, 5 * M // 8), (16, 3 * M // 8), (24, M // 4),
        (32, M // 8), (48, max(M // 16, 256)),
        (64, max(M // 32, 256)), (96, max(M // 64, 256)),
    )

import dataclasses
import os
from typing import Any

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TallyConfig:
    """Static configuration for a :class:`~pumiumtally_tpu.api.PumiTally` run.

    Attributes:
      n_groups: number of energy groups in the flux tally. The reference
        hardcodes 2 (cpp:531, marked FIXME there); here it is configurable
        and defaults to 2 for drop-in parity.
      tolerance: geometric tolerance for the ray-tet face walk. Matches the
        reference's pumipic adjacency tolerance of 1e-8 (cpp:123, 206).
      max_crossings: static upper bound on element-boundary crossings per
        particle per move. The `lax.while_loop` exits early once every
        particle is done, so a generous bound costs nothing at runtime;
        it only guards against infinite cycling on degenerate meshes.
        ``None`` → derived from the mesh size at trace build time.
      compact_after: full-batch crossings before straggler compaction kicks
        in (ops/walk.py module docstring); None disables compaction. The
        facade disables it automatically for small particle counts.
      compact_size: straggler subset lane count (default n_particles // 8).
      compact_stages: multi-stage compaction schedule
        ((start_crossing, subset_size[, unroll]), ...) overriding the
        two knobs above (ops/walk.py docstring), or the string
        ``"auto"`` for the slot-planned dense ladder — the best known
        schedule for walks with ~10-20 crossings per move
        (scripts/plan_ladder.py; BENCHMARKS.md "Slot-exact ladder
        planning"); ``"plan"`` for the executional planner
        (utils/ladder.plan_stages) at a mesh-density-estimated mean;
        ``"adaptive"`` (PumiTally only) to re-plan once from the
        MEASURED crossings/move after the first move. CAUTION:
        per-stage unroll >= 16 on a sparse (< 6 stage) schedule
        measured ~35x SLOWER on TPU (round-4 grid); the walk warns
        when it sees that shape.
      unroll: boundary crossings advanced per while-loop iteration
        (ops/walk.py). The TPU while_loop is dispatch-bound, so unrolling
        the body ~2x's throughput (scripts/sweep_unroll.py); done lanes
        make the extra evaluations no-ops.
      migration_period: every how many moves the particle axis is re-sorted
        by parent element for tally/gather locality (the TPU analog of the
        reference's `iter_count_ % 100` rebuild+migrate, cpp:256).
      sort_by_element: whether periodic element-sorting is enabled at all.
      dtype: floating dtype for coordinates/flux. float64 reproduces the
        reference oracle to 1e-8 on CPU; float32 (or bfloat16 mesh tables)
        is the TPU-native fast path.
      output_filename: VTK output path (reference hardcodes
        "fluxresult.vtk", cpp:298).
      score_squares: accumulate per-segment squared contributions in
        flux[..., 1] exactly like the reference (cpp:640-641). Turning it
        off halves scatter traffic when uncertainties are not needed.
      measure_time: accumulate per-phase wall-clock (TallyTimes parity,
        cpp:19-35). Device sync happens only when this is on, mirroring
        the PUMI_MEASURE_TIME fence guard (cpp:216-218).
      checkify_invariants: enable extra host-side input validation
        (finite positions/weights) on every call, the analog of the
        reference's OMEGA_H_CHECK_PRINTF device asserts (cpp:605-608).
        Group-bounds violations (cpp:634-638) are always rejected.
      record_xpoints: when set to K, every trace records each particle's
        first K boundary-crossing points, retrievable via
        PumiTally.intersection_points() (tracer getIntersectionPoints()
        parity, reference test:403-479). Composes with straggler
        compaction (the recording buffers ride the compaction rounds);
        costs one extra [n,3] store per crossing plus [S,K,3] traffic
        per compaction round. The default (None) pays nothing.
      robust: the walk's degeneracy-recovery machinery (ops/walk.py,
        "Degeneracy robustness"). False gives the reference tracer's
        truncate-on-degeneracy semantics (identical results on clean
        meshes, cheaper body); keep True unless the mesh is known
        well-behaved.
      tally_scatter / gathers: walk scheduling strategies (ops/walk.py
        docstring) — benchmark-tunable, numerically identical.
        tally_scatter "auto" resolves per backend at trace time
        (interleaved on TPU, pair elsewhere — round-4 hardware A/B).
      ledger: accumulate the per-particle track-length conservation
        ledger (TraceResult.track_length; required by the debug_checks
        consistency assert). One elementwise op per crossing — off only
        when squeezing the last percent from the hot loop.
      walk_stats: fold the per-move telemetry vector into the jitted
        walk (TraceResult.stats; obs/walk_stats.py schema — crossings,
        max crossings/particle, chase hops, truncations, compaction
        occupancy, segments, loop iters). The facade then reads ONE
        small vector per move instead of scanning the ``done`` array
        host-side, and feeds the flight recorder / ``telemetry()``.
        Cost is two int32 lanes updated elementwise per crossing (the
        ledger's cost class). False restores the pre-telemetry walk
        carry and the host-side truncation scan.

    quarantine: bad-particle quarantine (resilience/quarantine.py).
        When True, per-move inputs that would poison the additive flux
        accumulator — non-finite destinations or weights, destinations
        absurdly far outside the mesh bounding box — are MASKED out of
        the walk (the lane is parked and reports its held position,
        like flying=0) instead of raising (checkify_invariants) or
        scoring garbage (default). Quarantined lanes are counted
        per-lane and per-reason into ``telemetry()["quarantined"]`` and
        the ``pumi_quarantined_lanes_total`` counter. Off by default:
        parity runs should fail loudly on bad inputs.
    truncation_retries: escalation policy for truncated walks
        (resilience semantics; ops/walk.py rewalk_truncated). 0 (the
        default) keeps the warn-and-drop behavior. N > 0 re-walks ONLY
        the truncated lanes with doubled max_crossings, up to N
        attempts, before declaring them lost; recovered lanes score
        their remaining segments normally, lost lanes are counted in
        ``telemetry()`` (``pumi_lost_walks_total``) and still warn.
        The partitioned facade re-arms the SAME compiled step per
        attempt (additive crossing budget) instead of doubling the
        static bound — same bounded-retry contract without recompiling
        the partitioned program.

    sd_mode: standard-deviation accumulation strategy.
        "segment" (default, reference parity): the walk scatters (c, c²)
        per scored segment — slot 1 is Σc².
        "batch": the walk scatters only c (score_squares path measured
        −20% TPU step time, round-4 nosq A/B) and the facade folds ONE
        squared per-bin delta per MOVE into slot 1
        (core.tally.accumulate_batch_squares), so slot 1 is Σ(per-move
        bin totals)². The sd estimand is the same when particle scores
        are independent; the estimator has M−1 degrees of freedom
        (M = moves) instead of N·M−1, i.e. a noisier sd-of-sd by
        ~sqrt((N·M)/M) — quantified against the analytic variance
        oracle in tests/test_tally_oracle.py. Honored by PumiTally and
        PartitionedTally (per-chip elementwise fold over the owned
        slabs — halo scores are already on owner rows at step end);
        StreamingTallyPipeline rejects it (in-flight batches overlap).

    io_pipeline: move-loop I/O staging strategy (ops/staging.py).
        "packed" (default): destinations/flying/weights/groups are
        packed into ONE contiguous host record per move (one H2D
        transfer), the slot permutation is applied on device, and
        positions/material ids/done/stats come back as ONE coalesced
        device record (one D2H transfer) — bit-identical outputs to
        "legacy", structurally fewer transfers (asserted in CI via a
        jax.transfer_guard test).
        "overlap": "packed" plus double-buffered host staging records
        and deferred telemetry folding, so host-side bookkeeping of
        move k overlaps the device walk of move k+1 (flushed at every
        read surface; truncation warnings stay in-call).
        "legacy": the pre-pipeline multi-transfer path (one jnp.asarray
        per input array, per-array readbacks).
        The env var ``PUMI_TPU_IO_PIPELINE`` overrides the field (the
        CI faults step uses it to prove resilience holds under
        pipelining).  Both facades fall back to "legacy" automatically
        when record_xpoints or checkify_invariants is set (those paths
        need the un-packed result surface).

    integrity: the self-verification escalation mode
        (integrity/policy.py). "off" (default): no invariant programs,
        today's exact behavior. Any other mode folds the on-device
        conservation invariants into the walk programs (weighted
        scored-vs-path track length over completed lanes, flux
        non-negativity/finiteness, lane-count conservation — riding the
        PR 3 packed readback tail at zero extra transfers) and
        escalates violations: "warn" counts
        (``pumi_integrity_violations_total{check=...}``) and warns;
        "retry" raises a RETRYABLE ``TransientIntegrityViolation`` the
        ``ResilientRunner`` absorbs with its last-good rollback;
        "halt" raises fatally (the runner flushes a last-good
        checkpoint first). Outputs are bit-identical in every mode —
        the checks read, never write.
    integrity_tol: per-lane conservation-residual threshold (default:
        dtype- and mesh-scale-aware, integrity/invariants.py
        conservation_tolerance).
    audit_lanes: shadow-audit sample size K (integrity/audit.py). When
        > 0, every ``audit_every``-th move re-walks K randomly sampled
        completed lanes through an independent float64 host-reference
        walker and compares final positions and scored track lengths
        within ``audit_tol`` — a continuous SDC / kernel-regression
        detector. Mismatches are ``sdc_audit`` violations under the
        ``integrity`` policy; outcomes land in the flight recorder and
        ``telemetry()["integrity"]``. 0 (default) pays nothing.
    audit_every / audit_tol / audit_seed: audit cadence, comparison
        threshold (default dtype-aware) and sampling seed (the sample
        is deterministic per (seed, move), so replays audit the same
        lanes).
    move_deadline_s: dispatch-watchdog deadline around each compiled
        step + readback (integrity/watchdog.py). A hung dispatch
        surfaces as a retryable ``DispatchTimeoutError`` (counted under
        check="watchdog") instead of blocking forever, so the PR 2
        retry machinery re-arms and replays. None (default): no
        watchdog thread, zero overhead.

    convergence: statistical-convergence observability
        (obs/convergence.py). When True, both facades keep
        device-resident batch accumulators, fuse the per-bin
        relative-error reduction into the walk programs (riding the
        packed readback tail — the steady-state 1 H2D + 1 D2H
        invariant still holds), feed the ``pumi_rel_err_max`` /
        ``pumi_rel_err_mean`` / ``pumi_converged_fraction`` /
        ``pumi_fom`` gauges and per-batch flight records, and answer
        ``tally.converged()`` / ``tally.relative_error()`` /
        ``telemetry()["convergence"]``.  The reductions READ the
        accumulator and never write it: flux outputs are bit-identical
        with the flag on or off.  Works with ``score_squares=False``
        and ``sd_mode="batch"`` (only the even Σc entries are read).
        Off (default): nothing is traced, allocated, or transferred.
    rel_err_target: per-bin relative-error threshold defining a
        "converged" bin (the MCNP-style steering statistic; default
        0.05).
    batch_moves: moves per statistical batch (default: 1 — every move
        closes a batch, the finest monitoring grain). Larger values
        give fewer, better-estimated batches; ``tally.end_batch()``
        closes one explicitly regardless of cadence (and restarts it).
        Only meaningful with ``convergence=True``.
    converged_fraction: fraction of scored bins that must be at or
        below ``rel_err_target`` before ``tally.converged()`` answers
        True (default 0.95; at least 2 completed batches are always
        required — before that every scored bin reports rel-err 1).

    kernel: walk-kernel backend (ops/walk.py vs ops/walk_pallas.py).
        "xla" (default): the scattered XLA walk — every mesh size,
        every feature surface, the production default until a hardware
        window validates the Mosaic path.
        "pallas": the Mosaic kernel — VMEM-resident decoded walk table,
        blocked one-hot MXU gather, matrixized tally scatter flushed to
        HBM once per launch (ops/walk_pallas.py module docstring).
        Bitwise identical to the "xla" walk's FLAT loop
        (tests/test_kernel_pallas.py; straggler compaction is an
        XLA-path scheduling strategy the kernel ignores — with a
        compaction ladder active the backends agree numerically, not
        bit-for-bit, exactly like two different XLA schedules);
        targets the small/medium-mesh regime where the XLA walk's
        per-crossing HBM gather latency dominates. Outside its regime
        (no packed geo20 table, working set over the VMEM budget
        ``PUMI_TPU_PALLAS_VMEM_MB``) construction fails at resolve
        time; debug surfaces the kernel cannot carry (record_xpoints,
        checkify_invariants) and the fused megastep program are
        rejected at resolve time too (resolve_kernel).
        "auto": "pallas" whenever the workload fits the regime — packed
        table, VMEM budget, a real TPU backend (or
        ``PUMI_TPU_PALLAS_INTERPRET=1`` opting interpret mode in) and
        no conflicting feature — silently "xla" otherwise
        (walk_pallas.select_backend).
        The env var ``PUMI_TPU_KERNEL`` overrides the field (the CI
        kernel steps and the bench A/B drive it); an env-forced
        "pallas" degrades gracefully like PUMI_TPU_IO_PIPELINE does —
        over a config carrying a debug surface it downgrades to "xla"
        (resolve_kernel), and outside the kernel's regime (unpacked or
        over-budget mesh, the partitioned facade, the fused megastep
        program) the facades fall back to the XLA walk silently
        (select_backend(strict=False)) so one env var can blanket a
        whole suite — while the same conflict written INTO the config
        is an error.
        The partitioned facade accepts "auto" (resolving to its own
        fused per-chip program — the halo-table layout has no geo20
        packing to put in VMEM) and rejects an explicit "pallas" at
        construction.

    pallas_lane_block: the Mosaic kernel's one-hot block width B
        (ops/walk_pallas.py — the [B, ntet] blocked gather / [ntet, B]
        outer-product tally tile granularity; previously only reachable
        through the private ``lane_block=`` kwarg on the kernel entry).
        Validated at resolve time (``resolve_lane_block``): must be a
        positive power of two; clamped to the batch size; counted into
        the ``kernel_vmem_bytes`` working set that gates the VMEM
        budget (a larger block can push a mesh out of the Pallas
        regime).  Every rung of the ladder is BITWISE identical — the
        one-hot contraction is exact and the peel order is per-block
        ascending-lane (tests/test_tuning.py pins the parity) — so the
        knob is pure scheduling.  Env ``PUMI_TPU_PALLAS_LANE_BLOCK``
        beats the field.  None (default): the tuning database's winner
        for the shape class when one is active, else the kernel default
        (walk_pallas.DEFAULT_LANE_BLOCK = 128).  Ignored by the XLA
        walk.

    tuning: the autotuning database (tuning/db.py TUNING.json) the
        facades consult ONCE at construction for the knobs left at
        their defer values — kernel="auto"'s backend pick, the Pallas
        lane_block, megastep K.  A path enables it; None (default) and
        "off" disable it.  Env ``PUMI_TPU_TUNING=off|<path>`` beats the
        field.  Precedence per knob: an explicitly set knob (env
        override first, then the config field) always beats the
        database, and a database miss — no entry for the workload's
        shape class, or no database at all — falls back to today's
        defaults, so behavior without a database is byte-identical to
        a build without the tuning subsystem (every database winner is
        bitwise parity-gated by scripts/tune.py anyway).  A database
        captured under a different environment (backend / x64 / device
        count) or schema version is REFUSED at construction, exactly
        like CONTRACTS.json refuses cross-environment compares.

    megastep: moves fused per dispatch on the DEVICE-SOURCED move loop
        (``run_source_moves`` on both facades; ops/walk.py ``megastep``
        / ops/walk_partitioned.py ``make_partitioned_megastep``).  Each
        dispatch runs K complete moves — re-source (counter-based RNG
        keyed by (seed, move): isotropic direction, exponential flight
        distance from the per-region Σt table), walk (with migration
        rolled into the scanned body on the partitioned facade), and
        collision/roulette physics — as ONE compiled program, so the
        host performs 1 H2D + 1 D2H per K moves instead of per move.
        RNG streams are keyed by (seed, move, particle id), so
        megastep=K is bitwise identical to K megastep=1 dispatches
        (pinned by tests/test_megastep.py).  None (the default) means
        K=1 — per-dispatch moves, still device-sourced.  The OpenMC-
        facade ``move_to_next_location`` path is never affected: its
        destinations come from the caller, per the reference's
        per-advance-event contract.  Env override ``PUMI_TPU_MEGASTEP``
        beats the field (the CI faults step drives it).  Self-driven
        runs (models/transport.py, models/depletion.py, bench.py)
        default to megastep mode.

    Scope: ``ledger`` and ``gathers`` are honored by the single-chip and
    streaming-pipeline walks only. The partitioned walk
    (ops/walk_partitioned.py) always accumulates and migrates the ledger
    (it is the cross-cut conservation check) and always uses its own
    table layout; ``ledger=False`` / ``gathers`` are ignored there.
    ``walk_stats=False`` is likewise single-chip only: the partitioned
    walk always folds its per-chip stats vector (the counters double as
    the migration/truncation diagnostics).
    """

    n_groups: int = 2
    tolerance: float = 1e-8
    max_crossings: int | None = None
    compact_after: int | None = 32
    compact_size: int | None = None
    compact_stages: tuple | str | None = None
    unroll: int = 8
    migration_period: int = 100
    sort_by_element: bool = False
    dtype: Any = jnp.float32
    output_filename: str = "fluxresult.vtk"
    score_squares: bool = True
    measure_time: bool = False
    checkify_invariants: bool = False
    record_xpoints: int | None = None
    robust: bool = True
    tally_scatter: str = "auto"
    gathers: str = "merged"
    ledger: bool = True
    walk_stats: bool = True
    sd_mode: str = "segment"
    quarantine: bool = False
    truncation_retries: int = 0
    io_pipeline: str = "packed"
    integrity: str = "off"
    integrity_tol: float | None = None
    audit_lanes: int = 0
    audit_every: int = 1
    audit_tol: float | None = None
    audit_seed: int = 0
    move_deadline_s: float | None = None
    convergence: bool = False
    rel_err_target: float = 0.05
    batch_moves: int | None = None
    converged_fraction: float = 0.95
    megastep: int | None = None
    kernel: str = "xla"
    pallas_lane_block: int | None = None
    tuning: str | None = None

    def resolve_kernel(self) -> str:
        """Validate and return the walk-kernel knob ("xla" | "pallas" |
        "auto"; env ``PUMI_TPU_KERNEL`` beats the field).

        Invalid feature combos fail HERE, at resolve time, never deep
        inside dispatch: the Mosaic kernel keeps no per-crossing
        recording buffers (``record_xpoints``), cannot thread checkify
        device asserts (``checkify_invariants``), and does not ride the
        fused megastep program (``megastep``).  An env-forced "pallas"
        over a config carrying one of those debug surfaces downgrades
        to "xla" instead (the surface wins, exactly like
        ``PUMI_TPU_IO_PIPELINE`` vs record_xpoints in
        resolve_io_pipeline) so operational env sweeps never break
        debug runs; writing the conflict INTO the config is an error.
        The workload-dependent half of the decision (packed table, VMEM
        budget, backend) happens against a concrete mesh in
        ops/walk_pallas.py ``select_backend`` — also at facade
        construction, also before any dispatch."""
        env = os.environ.get("PUMI_TPU_KERNEL")
        kernel = env or self.kernel
        if kernel not in ("xla", "pallas", "auto"):
            raise ValueError(
                f"kernel must be 'xla', 'pallas' or 'auto': {kernel!r}"
            )
        if kernel == "pallas":
            from_env_sweep = bool(env) and self.kernel != "pallas"
            conflict = None
            if self.record_xpoints is not None:
                conflict = (
                    "kernel='pallas' cannot record intersection points "
                    "(the Mosaic kernel keeps no per-crossing recording "
                    "buffers); use kernel='xla' or drop record_xpoints"
                )
            elif self.checkify_invariants:
                conflict = (
                    "kernel='pallas' cannot thread checkify device "
                    "asserts through the Mosaic kernel; use "
                    "kernel='xla' or drop checkify_invariants"
                )
            elif self.megastep is not None:
                conflict = (
                    "kernel='pallas' does not compose with the fused "
                    "megastep program (megastep=K fuses source sampling "
                    "+ walk + physics into one scanned XLA body); use "
                    "kernel='xla' for device-sourced megastep runs, or "
                    "drop megastep and drive per-move dispatches"
                )
            if conflict is not None:
                if from_env_sweep:
                    return "xla"
                raise ValueError(conflict)
        return kernel

    def resolve_tuning(self) -> str | None:
        """The effective autotuning-database path (None = tuning off).
        Env ``PUMI_TPU_TUNING`` beats the field; ``"off"`` (either
        spelling) disables explicitly.  Pure knob resolution — loading,
        schema/environment validation and the shape-class lookup live
        in tuning/db.py ``resolve_tuned``."""
        env = os.environ.get("PUMI_TPU_TUNING")
        val = env if env else self.tuning
        if val in (None, "", "off"):
            return None
        return val

    def resolve_lane_block(
        self, n_particles: int | None = None, *, tuned=None
    ) -> int | None:
        """Validate and return the Pallas one-hot block width, or None
        for "kernel default" (walk_pallas.DEFAULT_LANE_BLOCK).

        Precedence: env ``PUMI_TPU_PALLAS_LANE_BLOCK`` > the
        ``pallas_lane_block`` field > the tuning database's winner for
        this shape class (``tuned``, a tuning.TunedDecision) > None.
        The value must be a positive power of two and is clamped to the
        batch size when ``n_particles`` is known (the kernel never runs
        a block wider than the batch); the caller feeds the result into
        ``select_backend``'s VMEM-budget check, so an oversized block
        is counted against ``PUMI_TPU_PALLAS_VMEM_MB`` rather than
        silently spilling."""
        env = os.environ.get("PUMI_TPU_PALLAS_LANE_BLOCK")
        if env:
            lb = int(env)
        elif self.pallas_lane_block is not None:
            lb = int(self.pallas_lane_block)
        elif tuned is not None and tuned.lane_block:
            lb = int(tuned.lane_block)
        else:
            return None
        if lb < 1 or (lb & (lb - 1)) != 0:
            raise ValueError(
                f"pallas_lane_block must be a positive power of two "
                f"(the one-hot block tiles the MXU): {lb}"
            )
        if n_particles is not None:
            lb = min(lb, max(int(n_particles), 1))
        return lb

    def resolve_megastep(self, *, tuned=None) -> int:
        """Effective moves-per-dispatch K for the device-sourced move
        loop (``run_source_moves``): the ``PUMI_TPU_MEGASTEP`` env
        override beats the field, the field beats the tuning database's
        winner (``tuned``, a tuning.TunedDecision consulted by the
        facades at construction), and with nothing set K is 1 (one
        dispatch per move).  Any K is bitwise identical to K=1 — RNG
        streams are keyed by (seed, move, particle id) — so a database
        K changes dispatch granularity, never results.

        Every ``run_source_moves`` entry point resolves the knob FIRST,
        so feature combos the fused megastep program cannot carry fail
        fast here — at resolve time, with an actionable message — for
        any K (even K=1 runs the megastep program): recorded
        intersection points and checkify device asserts are per-move
        facade surfaces."""
        env = os.environ.get("PUMI_TPU_MEGASTEP")
        if env:
            k = int(env)
        elif self.megastep is not None:
            k = int(self.megastep)
        elif tuned is not None and tuned.megastep:
            k = int(tuned.megastep)
        else:
            k = 1
        if k < 1:
            raise ValueError(f"megastep must be >= 1: {k}")
        if self.record_xpoints is not None:
            raise ValueError(
                "the device-sourced megastep program cannot record "
                "intersection points (record_xpoints); use the per-move "
                "facade path (move_to_next_location) or drop "
                "record_xpoints"
            )
        if self.checkify_invariants:
            raise ValueError(
                "the device-sourced megastep program cannot thread "
                "checkify device asserts (checkify_invariants); use the "
                "per-move facade path (move_to_next_location) or drop "
                "checkify_invariants"
            )
        return k

    def resolve_integrity(self) -> str:
        """Validate and return the self-verification mode
        (integrity/policy.py escalation ladder). Conservation invariants
        need the track-length ledger; the shadow-audit knobs must be
        coherent."""
        mode = self.integrity
        if mode not in ("off", "warn", "retry", "halt"):
            raise ValueError(
                "integrity must be 'off', 'warn', 'retry' or 'halt': "
                f"{mode!r}"
            )
        if mode != "off" and not self.ledger:
            raise ValueError(
                "integrity checks need the track-length conservation "
                "ledger: keep ledger=True (the default) or set "
                "integrity='off'"
            )
        if self.audit_lanes < 0:
            raise ValueError(
                f"audit_lanes must be >= 0: {self.audit_lanes}"
            )
        if self.audit_every < 1:
            raise ValueError(
                f"audit_every must be >= 1: {self.audit_every}"
            )
        if self.audit_lanes and not self.ledger:
            raise ValueError(
                "shadow audits compare the track-length ledger: keep "
                "ledger=True (the default) or set audit_lanes=0"
            )
        if (
            self.move_deadline_s is not None
            and self.move_deadline_s <= 0
        ):
            raise ValueError(
                f"move_deadline_s must be positive: {self.move_deadline_s}"
            )
        return mode

    def resolve_convergence(self) -> int | None:
        """Validate the convergence-observability knobs and return the
        effective moves-per-batch (None when the feature is off)."""
        if not self.convergence:
            if self.batch_moves is not None:
                raise ValueError(
                    "batch_moves only applies to convergence "
                    "observability: set convergence=True or drop it"
                )
            return None
        if not self.rel_err_target > 0:
            raise ValueError(
                f"rel_err_target must be positive: {self.rel_err_target}"
            )
        if not 0 < self.converged_fraction <= 1:
            raise ValueError(
                "converged_fraction must be in (0, 1]: "
                f"{self.converged_fraction}"
            )
        bm = 1 if self.batch_moves is None else int(self.batch_moves)
        if bm < 1:
            raise ValueError(f"batch_moves must be >= 1: {bm}")
        if self.checkify_invariants:
            # The checkify debug wrapper treats every trace kwarg as
            # static and cannot thread the device-resident batch
            # accumulators; the two debug surfaces are mutually
            # exclusive rather than silently dropping one.
            raise ValueError(
                "convergence observability does not compose with "
                "checkify_invariants (the checkified walk cannot carry "
                "the batch accumulators); disable one of them"
            )
        return bm

    def resolve_io_pipeline(self) -> str:
        """The effective move-loop I/O mode: the env override
        ``PUMI_TPU_IO_PIPELINE`` beats the field; debug surfaces that
        need the un-packed result (recorded intersection points,
        checkify invariants) force "legacy"."""
        mode = os.environ.get("PUMI_TPU_IO_PIPELINE") or self.io_pipeline
        if mode not in ("packed", "overlap", "legacy"):
            raise ValueError(
                "io_pipeline must be 'packed', 'overlap' or 'legacy': "
                f"{mode!r}"
            )
        if self.record_xpoints is not None or self.checkify_invariants:
            return "legacy"
        return mode

    def resolve_max_crossings(self, ntet: int) -> int:
        if self.max_crossings is not None:
            return self.max_crossings
        # A straight segment intersects a convex tet in a single interval, so
        # a walk can enter each element at most once: ntet (+ slack) is a
        # safe universal bound. The while_loop exits as soon as every
        # particle is done, so the generous bound costs nothing at runtime.
        return ntet + 64

    def resolve_compaction(self, n_particles: int) -> tuple[int | None, int | None]:
        """Compaction kicks in only where the straggler tail matters; tiny
        batches stay on the flat loop."""
        if self.compact_after is None or n_particles < 1024:
            return None, None
        size = self.compact_size
        if size is None:
            size = max(256, n_particles // 8)
        return self.compact_after, min(size, n_particles)

    def resolve_compact_stages(
        self, n_particles: int, ntet: int | None = None
    ) -> tuple | None:
        """Clamp a configured stage schedule to the batch size (None when
        unset — the single-stage knobs apply).

        ``"auto"`` selects the dense ladder — the measured-best TPU
        schedule (7.60 Mseg/s vs the 3-stage schedule's 4.84, round-4
        hardware grid) — with stage STARTS scaled by mesh density when
        ``ntet`` is known: crossings/move grow with path/element-size,
        so the 55-cell-calibrated boundaries stretch by
        (ntet/998250)^(1/3), exactly the scaling bench.py applies and
        the 10M/119-cell rung validated against the DP planner.

        ``"plan"`` runs the executional ladder planner
        (utils/ladder.plan_stages) on the analytic decay at the same
        density-estimated mean — it scores ~9% under the dense ladder
        in the simulator (31.3M vs 34.2M slot-equivalents at bench
        stats) and adapts the whole shape, not just the starts, to the
        mesh; hardware A/B pending (wave-3 row staged), which is why
        "auto" still means the measured-best dense ladder."""
        if self.compact_stages is None or n_particles < 1024:
            return None
        if isinstance(self.compact_stages, str):
            density = (
                (max(ntet, 1) / 998250.0) ** (1.0 / 3.0)
                if ntet is not None
                else 1.0
            )
            if self.compact_stages == "auto":
                scale = max(1.0, density)
                return tuple(
                    (int(round(start * scale)), *rest)
                    for start, *rest in dense_ladder(n_particles)
                )
            if self.compact_stages in ("plan", "adaptive"):
                from .ladder import plan_stages

                # 14.9 = measured mean crossings/move at the bench
                # workload (55-cell unit box, mean_path 0.08).
                # "adaptive" starts from the same density estimate; the
                # PumiTally facade then RE-plans from the measured
                # crossings/move after the first move (_maybe_replan) —
                # the move-length statistics the density estimate
                # cannot see. One extra trace compile; results
                # identical up to fp summation order. Only PumiTally
                # replans — the other facades REJECT "adaptive" rather
                # than silently degrading to the static plan.
                return plan_stages(
                    n_particles, 14.9 * density, unroll=self.unroll
                ) or None
            raise ValueError(
                "unknown compact_stages string "
                f"{self.compact_stages!r}; expected 'auto', 'plan', "
                "'adaptive' or an explicit "
                "((start, size[, unroll]), ...) schedule"
            )
        return tuple(
            (int(start), min(max(int(size), 1), n_particles),
             *(int(u) for u in rest))
            for start, size, *rest in self.compact_stages
        )
