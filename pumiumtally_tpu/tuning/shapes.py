"""Shape-class bucketing for the kernel autotuner.

A tuning decision (walk-kernel backend, Pallas ``lane_block``, megastep
K) is a property of the *workload shape*, not of one exact particle
count: the compiled programs themselves are shape-specialized, and the
performance landscape moves smoothly enough that one measurement per
padded bucket covers every concrete workload inside it.  This module
defines the bucketing: concrete ``(ntet, n_particles, n_groups, dtype,
packed)`` workloads collapse onto a padded power-of-two ladder in the
two large axes (``ntet``, ``n_particles``) and stay exact in the small
ones (``n_groups``, dtype, packedness — each changes the program
structurally, so they never share a bucket).

The same ladder IS the shape key of the serving layer (ROADMAP item
3): the AOT program bank (serving/bank.py) and the request scheduler
(serving/scheduler.py) bucket jobs by padded shape class through
``bucket``/``classify``/``ShapeClass.key()`` unchanged.
"""
from __future__ import annotations

import dataclasses

# Floor of the padded ladder: everything at-or-below the floor shares
# one rung (tiny workloads are all dispatch-bound; distinguishing a
# 12-tet mesh from a 48-tet mesh buys nothing).
PAD_FLOOR = 64


def pow2_ceil(x: int) -> int:
    """Smallest power of two >= x (>= 1)."""
    n = 1
    while n < x:
        n <<= 1
    return n


def bucket(x: int) -> int:
    """Pad one ladder axis: power-of-two ceiling, floored at PAD_FLOOR."""
    return max(PAD_FLOOR, pow2_ceil(max(int(x), 1)))


@dataclasses.dataclass(frozen=True)
class ShapeClass:
    """One rung of the padded workload ladder.

    ``ntet`` / ``n_particles`` are the PADDED bucket values (power-of-two
    ceilings), not the concrete workload sizes; ``dtype`` is the
    canonical numpy name ("float32"/"float64"); ``packed`` records
    whether the mesh carries the geo20 packed walk table (the Pallas
    kernel's structural precondition — packed and unpacked workloads
    can never share a tuning entry)."""

    ntet: int
    n_particles: int
    n_groups: int
    dtype: str
    packed: bool

    def key(self) -> str:
        """Stable database key, e.g. ``ntet4096.n8192.g2.float32.packed``."""
        p = "packed" if self.packed else "unpacked"
        return (
            f"ntet{self.ntet}.n{self.n_particles}"
            f".g{self.n_groups}.{self.dtype}.{p}"
        )


def classify(
    ntet: int,
    n_particles: int,
    n_groups: int,
    dtype,
    packed: bool,
) -> ShapeClass:
    """Bucket one concrete workload onto the padded ladder."""
    import numpy as np

    return ShapeClass(
        ntet=bucket(ntet),
        n_particles=bucket(n_particles),
        n_groups=int(n_groups),
        dtype=np.dtype(dtype).name,
        packed=bool(packed),
    )
