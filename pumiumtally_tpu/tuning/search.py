"""The shape-class autotuner: search, parity gate, calibration join.

ROADMAP item 1's "stop hand-picking" mechanism.  Per shape class
(tuning/shapes.py) the driver times the REAL jitted programs across the
candidate grid —

  * walk-kernel backend {xla, pallas}, with the Pallas one-hot block
    width swept over the ``lane_block`` ladder {64, 128, 256, 512},
    clamped to the batch and to the ``kernel_vmem_bytes`` VMEM budget
    (a rung whose working set exceeds ``PUMI_TPU_PALLAS_VMEM_MB`` is
    not a candidate at all);
  * megastep K over {1, 4, 16, 64} (clamped to the move budget), timed
    through the real ``run_source_moves`` facade loop;

— with warmup/median-of-N discipline (one un-timed compile+warmup call,
then the median of N timed repetitions), and gates EVERY candidate on
bitwise parity against the reference XLA walk before it is eligible to
win: a candidate whose outputs differ by one bit from the reference —
however fast — is recorded with ``parity: "failed"`` and excluded.
The POLAR-PIC per-problem-instance co-design search (PAPERS.md), run
once per shape class and persisted (tuning/db.py) instead of re-derived
per run.

Ranking modes
-------------
``mode="hardware"`` ranks by the measured median, with a small relative
tie band (``TIE_TOL``) broken toward the canonical candidate order
(today's defaults first) so timing jitter between near-equal candidates
cannot flip the committed winner between captures.

``mode="rehearsal"`` (the CPU path: no device window, Pallas running in
interpret mode) still measures and records every candidate — the
calibration join needs the timings — but ranks by the PR 9 cost model's
PREDICTED seconds (``analysis/costmodel.predict_seconds`` over each
candidate's compiled flop/byte signature at nominal coefficients):
interpret-mode wall clock says nothing about TPU relative performance,
and a deterministic model ranking is what makes the tuner reproduce
identical winners across fresh processes (the CI gate and
tests/test_tuning.py pin exactly that).

Calibration
-----------
Every candidate contributes a ``(flops, bytes, seconds-per-move)``
point; per shape class the driver fits effective-throughput /
effective-bandwidth coefficients (``analysis/costmodel
.calibrate_points``) and records them in the entry, so the compile-time
contracts can translate a future capture's flop/byte drift into
predicted seconds — a hardware-regression estimate between device
windows.

Fault hook: ``PUMI_TPU_TUNE_FAULT=kernel:pallas:<lane_block>`` or
``megastep:<K>`` corrupts that candidate's outputs by one ULP before
the parity compare (tests prove the gate rejects it; the reference
candidate cannot be corrupted — it IS the definition of correct).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

from .db import TUNING_SCHEMA, empty_db, env_key, environment
from .shapes import classify

LANE_BLOCK_LADDER = (64, 128, 256, 512)
MEGASTEP_LADDER = (1, 4, 16, 64)
# Measured medians within this relative band are a tie, broken toward
# the canonical candidate order (defaults first) — winner stability
# across captures beats chasing sub-noise deltas.
TIE_TOL = 0.05

# The canonical shape classes.  smoke1/smoke2 are the two smallest —
# the CI rehearsal set and the committed smoke database; ab12/ab14 are
# the round-6 Pallas A/B rungs (in-regime + VMEM budget edge);
# headline is the 1M-lane bench workload.  All are unit box meshes
# (ntet = 6·cells³), matching bench.py's workload generator.
SPECS = {
    "smoke1": dict(cells=2, n_particles=256, n_groups=2),
    "smoke2": dict(cells=3, n_particles=512, n_groups=2),
    "ab12": dict(cells=12, n_particles=8192, n_groups=2),
    "ab14": dict(cells=14, n_particles=8192, n_groups=2),
    "headline": dict(cells=55, n_particles=1048576, n_groups=8),
}


def _fault():
    """Parse PUMI_TPU_TUNE_FAULT → ("kernel", "pallas", 128) etc."""
    spec = os.environ.get("PUMI_TPU_TUNE_FAULT", "")
    if not spec:
        return None
    parts = spec.split(":")
    if parts[0] == "kernel" and len(parts) == 3:
        return ("kernel", parts[1], int(parts[2]))
    if parts[0] == "megastep" and len(parts) == 2:
        return ("megastep", int(parts[1]))
    raise ValueError(
        f"PUMI_TPU_TUNE_FAULT must be kernel:<backend>:<lane_block> or "
        f"megastep:<K>: {spec!r}"
    )


def _corrupt(flux: np.ndarray) -> np.ndarray:
    """One-ULP perturbation of the first flux entry — the smallest
    possible silent corruption, which the bitwise gate must still
    catch."""
    out = flux.copy()
    flat = out.reshape(-1)
    flat[0] = np.nextafter(flat[0], np.inf)
    return out


def _bitwise_equal(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and a.tobytes() == b.tobytes()


# --------------------------------------------------------------------- #
# Workload construction (bench.py's box-mesh generator, seeded host RNG)
# --------------------------------------------------------------------- #
def build_workload(spec: dict, *, moves: int, seed: int) -> dict:
    import jax.numpy as jnp

    from .. import build_box
    from ..core.tally import make_flux

    dtype = jnp.dtype(spec.get("dtype", "float32"))
    cells = int(spec["cells"])
    n = int(spec["n_particles"])
    g = int(spec["n_groups"])
    mesh = build_box(1.0, 1.0, 1.0, cells, cells, cells, dtype=dtype)
    rng = np.random.default_rng(seed)
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem].astype(dtype)
    mean_path = float(spec.get("mean_path", 0.08))
    # Precomputed host destination chain: every candidate walks the
    # identical seeded trajectory, so outputs are comparable bitwise
    # and timing excludes host RNG.
    dests, prev = [], origin
    for _ in range(moves):
        d = rng.normal(0, 1, (n, 3))
        d /= np.linalg.norm(d, axis=1, keepdims=True)
        ln = rng.exponential(mean_path, (n, 1))
        prev = np.clip(prev + d * ln, 0.01, 0.99).astype(dtype)
        dests.append(prev)
    return dict(
        spec=spec,
        mesh=mesh,
        dtype=dtype,
        n_particles=n,
        n_groups=g,
        mean_path=mean_path,
        seed=seed,
        origin=jnp.asarray(origin, dtype),
        elem=jnp.asarray(elem),
        dests=[jnp.asarray(d, dtype) for d in dests],
        in_flight=jnp.ones(n, bool),
        weight=jnp.ones(n, dtype),
        group=jnp.asarray(rng.integers(0, g, n).astype(np.int32)),
        material=jnp.full(n, -1, jnp.int32),
        make_flux=lambda: make_flux(mesh.ntet, g, dtype, flat=True),
        packed=getattr(mesh, "geo20", None) is not None,
    )


def _trace_kwargs(w: dict, kernel: str, lane_block: int | None) -> dict:
    # The flat-loop regime: straggler compaction is an XLA scheduling
    # strategy the Mosaic kernel ignores, so the backends are only
    # bitwise-comparable (and fairly timeable) with it off.
    kw = dict(
        initial=False,
        max_crossings=w["mesh"].ntet + 64,
        tolerance=1e-6,
        unroll=8,
        n_groups=w["n_groups"],
        compact_after=None,
        compact_stages=None,
        kernel=kernel,
    )
    if kernel == "pallas" and lane_block is not None:
        kw["lane_block"] = lane_block
    return kw


def _run_chain(w: dict, kernel: str, lane_block: int | None):
    """Walk the full destination chain once from the seeded initial
    state; returns (final pos, elem, done, flux, total segments)."""
    from ..ops.walk import trace

    kw = _trace_kwargs(w, kernel, lane_block)
    cur, elem, flux = w["origin"], w["elem"], w["make_flux"]()
    nseg = 0
    r = None
    for dest in w["dests"]:
        r = trace(
            w["mesh"], cur, dest, elem, w["in_flight"], w["weight"],
            w["group"], w["material"], flux, **kw,
        )
        cur, elem, flux = r.position, r.elem, r.flux
        nseg += int(np.asarray(r.n_segments))
    return (
        np.asarray(cur), np.asarray(elem), np.asarray(r.done),
        np.asarray(flux), nseg,
    )


def _kernel_metrics(w: dict, kernel: str, lane_block: int | None) -> dict:
    """Compiled flop/byte signature of ONE move of this candidate (the
    PR 9 extraction over the real traced program)."""
    from ..analysis.costmodel import compile_metrics
    from ..ops import walk

    kw = _trace_kwargs(w, kernel, lane_block)
    traced = walk._trace_jit.trace(
        w["mesh"], w["origin"], w["dests"][0], w["elem"], w["in_flight"],
        w["weight"], w["group"], w["material"], w["make_flux"](), **kw,
    )
    return compile_metrics(traced)


def _median(vals) -> float:
    return float(np.median(np.asarray(vals)))


def kernel_candidates(w: dict) -> list[dict]:
    """The kernel-axis candidate grid: XLA first (today's default),
    then the Pallas lane_block ladder clamped to the batch and the
    VMEM budget."""
    from ..ops.walk_pallas import _budget_bytes, kernel_vmem_bytes

    cands = [dict(kind="kernel", kernel="xla", lane_block=None)]
    if not w["packed"]:
        return cands  # the Mosaic kernel needs the geo20 table
    budget = _budget_bytes()
    itemsize = np.dtype(w["dtype"]).itemsize
    seen = set()
    # Batch clamp stays power-of-two: a persisted winner re-enters
    # resolve_lane_block at every consuming facade, whose pow2
    # validation runs before its own batch clamp — a raw min(lb, n)
    # on a non-pow2 batch would commit a database that crashes its
    # consumers.  (The kernel itself clamps further to n at runtime.)
    pow2_cap = 1 << (max(int(w["n_particles"]), 1).bit_length() - 1)
    for lb in LANE_BLOCK_LADDER:
        eff = min(lb, pow2_cap)
        if eff in seen:
            continue
        seen.add(eff)
        need = kernel_vmem_bytes(
            w["mesh"].ntet, w["n_particles"], w["n_groups"], itemsize,
            lane_block=eff,
        )
        if need > budget:
            continue  # over the VMEM budget: not a candidate at all
        cands.append(dict(kind="kernel", kernel="pallas", lane_block=eff))
    return cands


def evaluate_kernel_axis(
    w: dict, *, reps: int, nominal: dict
) -> list[dict]:
    fault = _fault()
    moves = len(w["dests"])
    out = []
    reference = None
    for order, c in enumerate(kernel_candidates(w)):
        kern, lb = c["kernel"], c["lane_block"]
        # Warmup (compile) outside the clock, then median-of-N.
        outputs = _run_chain(w, kern, lb)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _run_chain(w, kern, lb)
            times.append((time.perf_counter() - t0) / moves)
        if (
            fault is not None
            and fault[0] == "kernel"
            and fault[1] == kern
            and (kern == "xla" or fault[2] == lb)
            and reference is not None  # the reference defines "correct"
        ):
            outputs = outputs[:3] + (_corrupt(outputs[3]),) + outputs[4:]
        if reference is None:
            reference = outputs  # the XLA walk (always candidate 0)
            parity = "bitwise"
        else:
            parity = (
                "bitwise"
                if all(
                    _bitwise_equal(a, b)
                    for a, b in zip(outputs[:4], reference[:4])
                )
                and outputs[4] == reference[4]
                else "failed"
            )
        metrics = _kernel_metrics(w, kern, lb)
        from ..analysis.costmodel import predict_seconds

        out.append(dict(
            kind="kernel",
            kernel=kern,
            lane_block=lb,
            order=order,
            parity=parity,
            median_s_per_move=round(_median(times), 6),
            times_s_per_move=[round(t, 6) for t in times],
            flops=metrics["flops"],
            bytes_accessed=metrics["bytes_accessed"],
            predicted_s_per_move=round(
                predict_seconds(metrics, nominal), 9
            ),
            segments=outputs[4],
        ))
    return out


def _mega_ladder(mega_moves: int) -> list[int]:
    # run_source_moves chunks at min(K, remaining): a K above the move
    # budget would silently run as a smaller remainder chunk, so the
    # ladder is clamped to the Ks the budget can actually exercise.
    return [k for k in MEGASTEP_LADDER if k <= mega_moves]


def _run_mega(w: dict, k: int, n_moves: int):
    """A fresh facade run of ``n_moves`` device-sourced moves fused at
    megastep K; returns (tally, flux bytes, physics totals)."""
    from ..api import PumiTally
    from ..ops.source import SourceParams
    from ..utils.config import TallyConfig

    cfg = TallyConfig(
        dtype=w["dtype"], n_groups=w["n_groups"], tolerance=1e-6,
        megastep=k,
    )
    t = PumiTally(w["mesh"], w["n_particles"], cfg)
    t.initialize_particle_location(
        np.asarray(w["origin"], np.float64).reshape(-1).copy()
    )
    src = SourceParams(
        default_sigma_t=1.0 / w["mean_path"], seed=w["seed"]
    )
    totals = t.run_source_moves(
        n_moves, src,
        weights=np.ones(w["n_particles"]),
        groups=np.zeros(w["n_particles"], np.int32),
        alive=np.ones(w["n_particles"], bool),
    )
    return t, np.asarray(t.flux), totals


def evaluate_megastep_axis(
    w: dict, *, reps: int, mega_moves: int, nominal: dict,
    xla_metrics: dict,
) -> list[dict]:
    """Time + parity-gate the megastep-K ladder through the real
    ``run_source_moves`` facade loop.  Parity: K fused moves are
    bitwise identical to the same moves at K=1 (the PR 6 invariant,
    re-verified here per candidate on this exact workload)."""
    from ..analysis.costmodel import predict_seconds

    fault = _fault()
    ladder = _mega_ladder(mega_moves)
    out = []
    reference = None
    for order, k in enumerate(ladder):
        # Parity run: a fresh facade, exactly mega_moves moves.
        _, flux, totals = _run_mega(w, k, mega_moves)
        if fault is not None and fault[0] == "megastep" and fault[1] == k \
                and reference is not None:
            flux = _corrupt(flux)
        if reference is None:
            reference = (flux, totals["segments"])  # K=1: the reference
            parity = "bitwise"
        else:
            parity = (
                "bitwise"
                if _bitwise_equal(flux, reference[0])
                and totals["segments"] == reference[1]
                else "failed"
            )
        # Timing run: warm (compile + first-chunk lane staging) once,
        # then median-of-N chunks on the same live tally continuing
        # from DEVICE state — production chunking (ResilientRunner)
        # re-stages weights/alive on the first chunk only, so passing
        # them per timed call would charge a full H2D re-stage to
        # every chunk and bias the per-move medians against small K.
        t, _, _ = _run_mega(w, k, k)  # construction + warm chunk
        from ..ops.source import SourceParams

        src = SourceParams(
            default_sigma_t=1.0 / w["mean_path"], seed=w["seed"]
        )
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            t.run_source_moves(k, src)
            times.append((time.perf_counter() - t0) / k)
        # Predicted per-move seconds: the XLA walk's per-move compute
        # signature plus the per-dispatch overhead amortized over K —
        # the model that makes dispatch amortization rankable without
        # hardware (rehearsal mode ranks on it).
        pred = predict_seconds(xla_metrics, nominal) + (
            nominal["dispatch_s"] / k
        )
        out.append(dict(
            kind="megastep",
            megastep=k,
            order=order,
            parity=parity,
            median_s_per_move=round(_median(times), 6),
            times_s_per_move=[round(x, 6) for x in times],
            predicted_s_per_move=round(pred, 9),
            segments=int(reference[1] if parity == "bitwise" else -1),
        ))
    return out


def pick_winner(cands: list[dict], mode: str) -> dict | None:
    eligible = [c for c in cands if c["parity"] == "bitwise"]
    if not eligible:
        return None
    if mode == "rehearsal":
        return min(
            eligible,
            key=lambda c: (c["predicted_s_per_move"], c["order"]),
        )
    best = min(c["median_s_per_move"] for c in eligible)
    tied = [
        c for c in eligible
        if c["median_s_per_move"] <= best * (1.0 + TIE_TOL)
    ]
    return min(tied, key=lambda c: c["order"])


def tune_shape_class(
    spec: dict,
    *,
    mode: str = "hardware",
    reps: int = 5,
    moves: int = 4,
    mega_moves: int = 64,
    seed: int = 0,
) -> tuple[str, dict]:
    """Search one shape class; returns ``(shape key, db entry)``."""
    from ..analysis.costmodel import NOMINAL_COEFFS, calibrate_points

    w = build_workload(spec, moves=moves, seed=seed)
    shape = classify(
        w["mesh"].ntet, w["n_particles"], w["n_groups"], w["dtype"],
        w["packed"],
    )
    kcands = evaluate_kernel_axis(w, reps=reps, nominal=NOMINAL_COEFFS)
    xla_metrics = {
        "flops": kcands[0]["flops"],
        "bytes_accessed": kcands[0]["bytes_accessed"],
    }
    mcands = evaluate_megastep_axis(
        w, reps=reps, mega_moves=mega_moves, nominal=NOMINAL_COEFFS,
        xla_metrics=xla_metrics,
    )
    kwin = pick_winner(kcands, mode)
    mwin = pick_winner(mcands, mode)
    points = [
        dict(
            flops=c["flops"],
            bytes_accessed=c["bytes_accessed"],
            seconds=c["median_s_per_move"],
        )
        for c in kcands
        if c["parity"] == "bitwise"
    ]
    entry = {
        "workload": {
            "cells": int(spec["cells"]),
            "ntet": int(w["mesh"].ntet),
            "n_particles": int(w["n_particles"]),
            "n_groups": int(w["n_groups"]),
            "dtype": np.dtype(w["dtype"]).name,
            "packed": bool(w["packed"]),
            "moves": moves,
            "mega_moves": mega_moves,
            "seed": seed,
        },
        "kernel": kwin["kernel"] if kwin else "xla",
        "lane_block": kwin.get("lane_block") if kwin else None,
        "megastep": int(mwin["megastep"]) if mwin else 1,
        "candidates": kcands + mcands,
        "calibration": calibrate_points(points),
    }
    return shape.key(), entry


def tune(
    specs: dict,
    *,
    mode: str = "hardware",
    reps: int = 5,
    moves: int = 4,
    mega_moves: int = 64,
    seed: int = 0,
    base: dict | None = None,
    progress=None,
) -> dict:
    """Tune every spec and merge the entries into (a copy of) ``base``
    under the current environment's section.  Entries for shape classes
    NOT in ``specs`` are preserved — a capture window can re-tune the
    headline classes without dropping the smoke rungs."""
    data = json.loads(json.dumps(base)) if base else empty_db()
    if data.get("schema") != TUNING_SCHEMA:
        raise ValueError(
            f"cannot merge into schema {data.get('schema')!r} database "
            f"(this tuner writes schema {TUNING_SCHEMA})"
        )
    env = environment()
    sec = data.setdefault("environments", {}).setdefault(
        env_key(env),
        {"environment": env, "mode": mode, "entries": {}},
    )
    if sec.get("environment") != env:
        raise ValueError(
            f"existing section {env_key(env)!r} pins environment "
            f"{sec.get('environment')}, current is {env}"
        )
    if sec.get("entries") and sec.get("mode") not in (None, mode):
        # A partial re-tune must not relabel entries measured under the
        # other mode (hardware medians tagged "rehearsal" or vice
        # versa) — re-tune every shape class or use a fresh database.
        raise ValueError(
            f"section {env_key(env)!r} was tuned in mode "
            f"{sec.get('mode')!r}; merging {mode!r} entries would "
            "mislabel the existing ones — re-tune all shapes in one "
            "mode or start a fresh database"
        )
    sec["mode"] = mode
    for name, spec in specs.items():
        if progress:
            progress(f"tuning {name}: {spec}")
        key, entry = tune_shape_class(
            spec, mode=mode, reps=reps, moves=moves,
            mega_moves=mega_moves, seed=seed,
        )
        entry["spec_name"] = name
        sec["entries"][key] = entry
        if progress:
            progress(
                f"  {key}: kernel={entry['kernel']}"
                f" lane_block={entry['lane_block']}"
                f" megastep={entry['megastep']}"
            )
    return data


def winners(data: dict, env: dict | None = None) -> dict:
    """{shape key: (kernel, lane_block, megastep)} of one environment
    section — the determinism/drift comparison surface."""
    env = env or environment()
    sec = data.get("environments", {}).get(env_key(env), {})
    return {
        k: (e.get("kernel"), e.get("lane_block"), e.get("megastep"))
        for k, e in sorted(sec.get("entries", {}).items())
    }
