"""The persisted tuning database (``TUNING.json``).

One committed JSON file holds the autotuner's winners per shape class
(tuning/shapes.py), grouped into *environment sections* exactly like
the contract captures: a decision measured on a TPU backend means
nothing on CPU, so every section is keyed by the pinned
``{backend, x64, n_devices}`` environment (analysis/contracts
``environment()``) and consumption REFUSES a database that has no
section for the current environment — the same cross-environment
refusal ``CONTRACTS.json`` / ``PERF_CONTRACTS.json`` enforce on their
diffs.  A schema-version mismatch is refused the same way (the file
outlives the code that wrote it).

Layout::

  {
    "schema": 1,
    "environments": {
      "cpu-x64off-d1": {
        "environment": {"backend": "cpu", "x64": false, "n_devices": 1},
        "mode": "rehearsal" | "hardware",
        "entries": {
          "<shape key>": {
            "kernel": "xla" | "pallas",
            "lane_block": 128 | null,
            "megastep": 16,
            "candidates": [... every measured candidate, parity verdicts
                           and median timings included ...],
            "calibration": {"flops_per_s": ..., "bytes_per_s": ..., ...}
          }
        }
      }
    }
  }

Consumption happens once, at facade construction
(``tuning.resolve_tuned``): a hit hands the construction-time resolves
(``resolve_config_kernel`` / ``select_backend`` /
``TallyConfig.resolve_megastep`` / ``resolve_lane_block``) the
database's winners; a miss — no entry for the shape class — falls back
to today's defaults, so behavior without a database is byte-identical
to a build without this module.  Explicit config knobs and env
overrides always beat the database (utils/config.py documents the full
precedence).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading

TUNING_SCHEMA = 1
TUNING_FILE = "TUNING.json"


def environment() -> dict:
    """The pinned consumption environment — same contract as the
    analysis layers' captures."""
    from ..analysis.contracts import environment as _env

    return _env()


def env_key(env: dict) -> str:
    """Canonical section key, e.g. ``cpu-x64off-d1`` / ``tpu-x64off-d4``."""
    return (
        f"{env['backend']}-x64{'on' if env['x64'] else 'off'}"
        f"-d{env['n_devices']}"
    )


def empty_db() -> dict:
    return {"schema": TUNING_SCHEMA, "environments": {}}


class TuningDB:
    """Parsed database + the section matching one environment."""

    def __init__(self, data: dict, path: str | None = None):
        if not isinstance(data, dict) or "schema" not in data:
            raise ValueError(
                f"tuning database {path or '<dict>'} has no schema "
                "field — not a TUNING.json capture"
            )
        if data["schema"] != TUNING_SCHEMA:
            raise ValueError(
                f"tuning database {path or '<dict>'} has schema "
                f"{data['schema']!r}, this build consumes schema "
                f"{TUNING_SCHEMA} — regenerate it with scripts/tune.py"
            )
        self.data = data
        self.path = path

    @property
    def environments(self) -> dict:
        return self.data.get("environments", {})

    def section(self, env: dict | None = None, *, strict: bool = True):
        """The section for ``env`` (default: the current environment).

        ``strict`` raises on a cross-environment database — a file that
        has sections but none for this environment; an EMPTY database
        (no sections at all) is not an error, it is all-miss."""
        env = env or environment()
        sec = self.environments.get(env_key(env))
        if sec is not None:
            if sec.get("environment") != env:
                raise ValueError(
                    f"tuning database {self.path or '<dict>'} section "
                    f"{env_key(env)!r} records environment "
                    f"{sec.get('environment')} but the current "
                    f"environment is {env} — the section key and its "
                    "pinned environment drifted; regenerate with "
                    "scripts/tune.py"
                )
            return sec
        if strict and self.environments:
            raise ValueError(
                f"tuning database {self.path or '<dict>'} has no "
                f"section for the current environment {env} "
                f"(sections: {sorted(self.environments)}) — tuning "
                "decisions do not transfer across backends; re-tune "
                "with scripts/tune.py or set PUMI_TPU_TUNING=off"
            )
        return None

    def lookup(self, shape, env: dict | None = None) -> dict | None:
        """The entry for one shape class (None = miss).  ``shape`` is a
        tuning.shapes.ShapeClass or its ``key()`` string."""
        sec = self.section(env)
        if sec is None:
            return None
        key = shape if isinstance(shape, str) else shape.key()
        return sec.get("entries", {}).get(key)


def load_tuning(path: str) -> TuningDB:
    with open(path) as fh:
        return TuningDB(json.load(fh), path=path)


def write_tuning(path: str, data: dict) -> None:
    from ..utils.checkpoint import atomic_write_json

    # The database outlives the tuner that wrote it and is consumed at
    # every facade construction — atomic write, so a crash mid-retune
    # leaves the previous committed database, never a torn one.
    atomic_write_json(path, data)


# Facades construct often (every test builds a tally); re-parsing the
# database each time would put file I/O on the construction path.  The
# cache is keyed by (path, mtime) so an in-place regeneration by
# scripts/tune.py is picked up.
_cache: dict = {}
_cache_lock = threading.Lock()


def cached_tuning(path: str) -> TuningDB:
    key = (os.path.abspath(path), os.stat(path).st_mtime_ns)
    with _cache_lock:
        db = _cache.get(key)
        if db is None:
            db = load_tuning(path)
            # One live generation per path: drop only stale mtimes of
            # THIS path, so two databases used alternately (a tuned db
            # and a smoke db in one test process) keep their entries.
            for stale in [k for k in _cache if k[0] == key[0]]:
                del _cache[stale]
            _cache[key] = db
        return db


@dataclasses.dataclass(frozen=True)
class TunedDecision:
    """What the database said for one concrete workload — all-None
    fields mean "no opinion, use the defaults"."""

    path: str | None = None  # database consulted (None: tuning off)
    key: str | None = None   # shape-class key looked up
    hit: bool = False
    kernel: str | None = None      # "xla" | "pallas"
    lane_block: int | None = None
    megastep: int | None = None


TUNING_OFF = TunedDecision()


def resolve_tuned(
    cfg,
    *,
    ntet: int,
    n_particles: int,
    n_groups: int,
    dtype,
    packed: bool,
) -> TunedDecision:
    """The ONE construction-time database consult shared by every
    facade: resolve the knob (``TallyConfig.resolve_tuning`` — env
    ``PUMI_TPU_TUNING`` beats the config field, "off"/unset means no
    database), load + schema/environment-check the file, classify the
    workload, and return the entry's winners (or an explicit miss).

    Raises on an unreadable/cross-schema/cross-environment database —
    pointing ``PUMI_TPU_TUNING`` at a file is an explicit request, and
    silently ignoring it would let a stale TPU database "work" on CPU.
    """
    path = cfg.resolve_tuning()
    if path is None:
        return TUNING_OFF
    return lookup_tuned(
        path,
        ntet=ntet,
        n_particles=n_particles,
        n_groups=n_groups,
        dtype=dtype,
        packed=packed,
    )


def lookup_tuned(
    path: str,
    *,
    ntet: int,
    n_particles: int,
    n_groups: int,
    dtype,
    packed: bool,
) -> TunedDecision:
    """``resolve_tuned`` with the database path already resolved
    (bench.py consults the same way without a TallyConfig)."""
    from .shapes import classify

    db = cached_tuning(path)
    shape = classify(ntet, n_particles, n_groups, dtype, packed)
    entry = db.lookup(shape)
    if entry is None:
        return TunedDecision(path=path, key=shape.key(), hit=False)
    lane = entry.get("lane_block")
    mega = entry.get("megastep")
    return TunedDecision(
        path=path,
        key=shape.key(),
        hit=True,
        kernel=entry.get("kernel"),
        lane_block=int(lane) if lane else None,
        megastep=int(mega) if mega else None,
    )
