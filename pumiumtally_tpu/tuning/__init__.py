"""Shape-class kernel autotuning (ROADMAP item 1).

The move loop's performance knobs — walk-kernel backend (xla/pallas),
the Pallas one-hot ``lane_block``, megastep K — are searched per padded
(ntet, n_particles, n_groups, dtype, packed) shape class by
``tuning/search.py`` (driven by ``scripts/tune.py``), parity-gated
bitwise against the reference XLA walk, and persisted into an
environment-keyed ``TUNING.json`` (``tuning/db.py``) that the facades
consult once at construction via :func:`resolve_tuned`.  Explicit
config knobs and env overrides always beat the database; a miss falls
back to today's defaults.
"""
from .db import (  # noqa: F401
    TUNING_FILE,
    TUNING_SCHEMA,
    TunedDecision,
    TuningDB,
    empty_db,
    env_key,
    environment,
    load_tuning,
    lookup_tuned,
    resolve_tuned,
    write_tuning,
)
from .shapes import PAD_FLOOR, ShapeClass, bucket, classify  # noqa: F401
