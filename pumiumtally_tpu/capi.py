"""Python side of the C ABI bridge (native/pumi_tally_c.cpp).

The embedded interpreter calls these functions with raw host pointers
wrapped as writable memoryviews; ``np.frombuffer`` turns them into
zero-copy NumPy views so the facade's out-param write-backs land directly
in the C caller's buffers — the same raw-pointer contract the reference's
pimpl facade gives OpenMC (pumipic_particle_data_structure.h:20-47),
without a staging copy on the host side.

Handles are integers into a registry (the C struct just carries the id).
"""
from __future__ import annotations

import itertools
import os

import numpy as np

if os.environ.get("PUMI_TPU_PLATFORM"):
    # Let embedded hosts pin the JAX platform ("cpu" for test rigs). The
    # plain JAX_PLATFORMS env var can be overridden by baked device
    # plugins; the config update always wins.
    import jax

    jax.config.update("jax_platforms", os.environ["PUMI_TPU_PLATFORM"])

from .api import PumiTally
from .utils.config import TallyConfig

_registry: dict[int, PumiTally] = {}
_ids = itertools.count(1)


def create(mesh_file: str, num_particles: int, n_groups: int) -> int:
    tally = PumiTally(
        mesh_file, num_particles, TallyConfig(n_groups=n_groups)
    )
    handle = next(_ids)
    _registry[handle] = tally
    return handle


def destroy(handle: int) -> None:
    _registry.pop(handle, None)


def _view(mv: memoryview, dtype, count: int) -> np.ndarray:
    arr = np.frombuffer(mv, dtype=dtype, count=count)
    if not arr.flags.writeable:
        raise ValueError("C buffer must be writable")
    return arr


def initialize_particle_location(handle: int, positions: memoryview,
                                 size: int) -> None:
    t = _registry[handle]
    pos = _view(positions, np.float64, size)
    t.initialize_particle_location(pos, size)


def move_to_next_location(
    handle: int,
    dests: memoryview,
    flying: memoryview,
    weights: memoryview,
    groups: memoryview,
    material_ids: memoryview,
    size: int,
) -> None:
    t = _registry[handle]
    n = t.num_particles
    t.move_to_next_location(
        _view(dests, np.float64, size),
        _view(flying, np.int8, n),
        _view(weights, np.float64, n),
        _view(groups, np.int32, n),
        _view(material_ids, np.int32, n),
        size,
    )


def write(handle: int, filename: str) -> None:
    _registry[handle].write_pumi_tally_mesh(filename)


def get_flux(handle: int, out: memoryview, capacity: int) -> int:
    t = _registry[handle]
    flux = np.asarray(t.raw_flux, np.float64).ravel()
    if flux.size > capacity:
        raise ValueError(
            f"flux has {flux.size} entries, buffer holds {capacity}"
        )
    _view(out, np.float64, flux.size)[:] = flux
    return int(flux.size)
