"""Bad-particle quarantine: mask poisonous inputs out of the walk.

The flux accumulator is additive — ONE NaN source particle scattered
into it poisons every later read of its bins, and the facades' only
defenses today are all-or-nothing: ``checkify_invariants`` raises
(killing a multi-hour run for one bad lane) or the garbage scores.
Production MC practice (PUMI-Tally, arXiv:2504.19048 §its degraded-mode
notes) wants the third option: park the bad lane, keep the run, report.

With ``TallyConfig(quarantine=True)`` both facades scan each call's
host inputs BEFORE anything reaches the device:

  * non-finite destination coordinates (``nonfinite_dest``),
  * non-finite statistical weights (``nonfinite_weight``),
  * destinations absurdly far outside the mesh — beyond the bounding
    box inflated by one diagonal (``out_of_mesh``; legitimate
    out-of-domain destinations that merely clip at the boundary pass).

Quarantined lanes are parked exactly like ``flying=0`` lanes: not
walked, not scored, position held, and the caller's out-params get the
held position back. Counts flow per-lane (``tally.quarantined_lanes``)
and per-reason into the obs registry (``pumi_quarantined_lanes_total``)
and ``telemetry()["quarantined"]``.

Host-side glue on the facade path — one vectorized isfinite/compare
pass over arrays the facade already touches; the device hot path pays
nothing.
"""
from __future__ import annotations

import dataclasses

import numpy as np

REASONS = ("nonfinite_dest", "nonfinite_weight", "out_of_mesh")


@dataclasses.dataclass
class QuarantineReport:
    """One call's quarantine verdicts.

    mask: [n] bool — True where the lane must be parked this move.
    reasons: reason name → lane count (a lane bad for several reasons
      counts once per reason; ``count`` deduplicates).
    """

    mask: np.ndarray
    reasons: dict

    @property
    def count(self) -> int:
        return int(self.mask.sum())


def inflated_bounds(coords) -> tuple[np.ndarray, np.ndarray]:
    """Mesh bounding box inflated by one diagonal on every side — the
    out-of-mesh threshold. Anything a caller legitimately sends (even
    destinations that overshoot the domain and clip at the boundary)
    lands well inside; only garbage coordinates land outside."""
    c = np.asarray(coords, np.float64)
    lo, hi = c.min(axis=0), c.max(axis=0)
    diag = float(np.linalg.norm(hi - lo)) or 1.0
    return lo - diag, hi + diag


def scan(
    dest3: np.ndarray,
    weights: np.ndarray | None,
    bounds: tuple[np.ndarray, np.ndarray],
) -> QuarantineReport | None:
    """Scan one call's inputs; returns None when everything is clean
    (the common case allocates nothing beyond the finite checks).
    ``weights`` is None on the initial location search (nothing is
    scored there, so only the coordinates can poison anything)."""
    lo, hi = bounds
    finite_dest = np.isfinite(dest3).all(axis=1)
    bad_dest = ~finite_dest
    bad_w = (
        ~np.isfinite(np.asarray(weights))
        if weights is not None
        else np.zeros(dest3.shape[0], bool)
    )
    oob = finite_dest & (
        (dest3 < lo) | (dest3 > hi)
    ).any(axis=1)
    mask = bad_dest | bad_w | oob
    if not mask.any():
        return None
    return QuarantineReport(
        mask=mask,
        reasons={
            "nonfinite_dest": int(bad_dest.sum()),
            "nonfinite_weight": int(bad_w.sum()),
            "out_of_mesh": int(oob.sum()),
        },
    )


def sanitize(
    report: QuarantineReport,
    dest3: np.ndarray,
    weights: np.ndarray | None,
) -> None:
    """Overwrite quarantined rows with inert finite values IN PLACE so
    nothing non-finite ever reaches a device array (NaNs on parked
    lanes are provably inert in the walk, but keeping device state
    finite makes checkpoints and ``checkify_invariants`` compose).
    Both arrays must be facade STAGING COPIES, never the caller's
    buffers — a supervisor retrying the move must re-see the original
    bad inputs, not the sanitized ones (resilience/runner.py)."""
    dest3[report.mask] = 0.0
    if weights is not None:
        weights[report.mask] = 0.0


def setup(tally, coords, num_particles: int) -> None:
    """Constructor hook shared by both facades
    (``TallyConfig.quarantine``): the out-of-mesh threshold and the
    per-lane count array live on the tally; the logic lives here once."""
    tally._qbounds = inflated_bounds(coords)
    tally._quarantined = np.zeros(int(num_particles), np.int64)


def lanes(tally) -> np.ndarray:
    """``quarantined_lanes()`` body shared by both facades: cumulative
    per-lane counts, host pid order."""
    if tally._quarantined is None:
        raise ValueError(
            "set TallyConfig(quarantine=True) to track quarantined "
            "lanes (off by default: parity runs fail loudly)"
        )
    return tally._quarantined.copy()


def apply(tally, dest3, weights, move):
    """The shared facade entry point (PumiTally and PartitionedTally
    delegate here so the quarantine semantics cannot drift): scan one
    call's inputs against ``tally._qbounds``; on a hit, sanitize a
    STAGING COPY of ``dest3`` (the caller's buffer keeps its original
    values until the facade's own copy-back), fold per-lane counts into
    ``tally._quarantined`` and the telemetry counters.

    ``weights`` must already be a facade copy (sanitized in place) or
    None. Returns ``(dest3_for_staging, mask_or_None)``.

    Counter semantics under the supervisor's transient retry: the
    per-lane ``_quarantined`` array is part of the resumable state and
    rolls back with it, while the registry counters are monotonic event
    counts (a retried scan records again — standard counter practice).
    """
    rep = scan(dest3, weights, tally._qbounds)
    if rep is None:
        return dest3, None
    dest3 = dest3.copy()
    sanitize(rep, dest3, weights)
    tally._quarantined += rep.mask
    tally._telemetry.record_quarantine(move, rep.count, rep.reasons)
    return dest3, rep.mask
