"""Fault-injection harness: prove each failure mode recovers.

A resilience subsystem that is only exercised by real preemptions is
untested code on the critical path. This module injects the failure
modes the ``ResilientRunner`` claims to survive, deterministically,
from one env knob::

    PUMI_TPU_FAULTS=nan_src:0.01,die_at_move:3,corrupt_ckpt

Grammar: comma-separated ``name[:value]`` clauses —

  ``nan_src:P``           each move, each lane's destination is NaN'd
                          with probability P (deterministic per
                          (seed, move) — replays reproduce the faults);
  ``die_at_move:K``       the K-th facade move (1-based over the run,
                          i.e. ``iter_count + 1 == K``) raises
                          ``InjectedKill`` BEFORE the walk runs — a
                          preemption mid-campaign. Fires once per
                          injector (the resumed process is a new one);
  ``transient_at_move:K`` the K-th move raises
                          ``InjectedTransientFault`` once — the
                          retry-with-backoff path must absorb it;
  ``corrupt_ckpt``        every checkpoint the supervisor writes is
                          bit-flipped right after the write — the
                          ``find_latest`` fallback must skip it;
  ``bitflip_flux:K``      after the K-th facade move, one flux entry
                          gets its sign flipped (or NaN'd when the
                          accumulator is still empty) — a single-bit
                          SDC the integrity layer's on-device flux
                          invariant must catch on the NEXT move
                          (integrity/invariants.py);
  ``sdc_walk:K``          at the K-th move's shadow audit, one sampled
                          lane's production track length is perturbed —
                          a mis-scored segment the float64 audit
                          re-walk must flag (integrity/audit.py);
  ``hang_at_move:K``      the K-th move's device dispatch sleeps
                          ``hang_seconds`` (a wedged dispatch) — the
                          watchdog deadline must surface it as a
                          retryable DispatchTimeoutError
                          (integrity/watchdog.py);
  ``hang_seconds:S``      how long the injected hang sleeps (default
                          5.0; tests use fractions of a second so the
                          abandoned watchdog thread dies quickly);
  ``seed:S``              rng seed for nan_src lane choice (default 0).

The PR 2 modes (nan_src/die/transient/corrupt_ckpt) are driven by the
``ResilientRunner``'s injector; the integrity modes (bitflip_flux/
sdc_walk/hang_at_move) are driven by the FACADE's own injector so the
detectors they target see the corruption regardless of whether a
supervisor wraps the run.

The injector is a no-op when the plan is empty, so production code can
call its hooks unconditionally.
"""
from __future__ import annotations

import dataclasses
import os

import numpy as np


class InjectedFault(RuntimeError):
    """Base class for injected failures."""


class InjectedKill(InjectedFault):
    """Simulated preemption: NOT retryable — the supervisor must let it
    propagate (the process is 'dead'); recovery is the next process's
    auto-resume."""


class InjectedTransientFault(InjectedFault):
    """Simulated transient device/runtime error: retryable — the
    supervisor's backoff path must absorb it."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    nan_src: float = 0.0
    die_at_move: int | None = None
    transient_at_move: int | None = None
    corrupt_ckpt: bool = False
    bitflip_flux: int | None = None
    sdc_walk: int | None = None
    hang_at_move: int | None = None
    hang_seconds: float = 5.0
    seed: int = 0

    def any(self) -> bool:
        return bool(
            self.nan_src
            or self.die_at_move is not None
            or self.transient_at_move is not None
            or self.corrupt_ckpt
            or self.bitflip_flux is not None
            or self.sdc_walk is not None
            or self.hang_at_move is not None
        )


def parse_faults(spec: str) -> FaultPlan:
    """Parse the ``PUMI_TPU_FAULTS`` grammar (module docstring). Raises
    ``ValueError`` on unknown clauses or malformed values — a typo'd
    fault spec silently injecting nothing would defeat the tests."""
    fields: dict = {}
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        name, _, value = clause.partition(":")
        if name == "nan_src":
            fields["nan_src"] = float(value)
            if not 0.0 <= fields["nan_src"] <= 1.0:
                raise ValueError(
                    f"nan_src must be a probability: {value!r}"
                )
        elif name == "die_at_move":
            fields["die_at_move"] = int(value)
        elif name == "transient_at_move":
            fields["transient_at_move"] = int(value)
        elif name == "corrupt_ckpt":
            if value:
                raise ValueError("corrupt_ckpt takes no value")
            fields["corrupt_ckpt"] = True
        elif name == "bitflip_flux":
            fields["bitflip_flux"] = int(value)
        elif name == "sdc_walk":
            fields["sdc_walk"] = int(value)
        elif name == "hang_at_move":
            fields["hang_at_move"] = int(value)
        elif name == "hang_seconds":
            fields["hang_seconds"] = float(value)
            if fields["hang_seconds"] <= 0:
                raise ValueError(
                    f"hang_seconds must be positive: {value!r}"
                )
        elif name == "seed":
            fields["seed"] = int(value)
        else:
            raise ValueError(
                f"unknown fault {name!r} in PUMI_TPU_FAULTS "
                f"(known: nan_src, die_at_move, transient_at_move, "
                f"corrupt_ckpt, bitflip_flux, sdc_walk, hang_at_move, "
                f"hang_seconds, seed)"
            )
    return FaultPlan(**fields)


def plan_from_env() -> FaultPlan:
    return parse_faults(os.environ.get("PUMI_TPU_FAULTS", ""))


class FaultInjector:
    """Stateful per-process injector over a FaultPlan.

    ``die_at_move`` / ``transient_at_move`` fire at most once per
    injector instance — the model is one failure per process life, and
    a resumed run constructs a fresh injector (usually with a fresh
    env)."""

    def __init__(self, plan: FaultPlan | None = None):
        self.plan = plan if plan is not None else plan_from_env()
        self._died = False
        self._transient_fired = False
        self._bitflip_fired = False
        self._sdc_fired = False
        self._hang_fired = False

    # ------------------------------------------------------------------ #
    def maybe_die(self, move: int) -> None:
        if (
            self.plan.die_at_move is not None
            and move == self.plan.die_at_move
            and not self._died
        ):
            self._died = True
            raise InjectedKill(
                f"injected preemption at move {move} "
                f"(PUMI_TPU_FAULTS die_at_move)"
            )

    def maybe_transient(self, move: int) -> None:
        if (
            self.plan.transient_at_move is not None
            and move == self.plan.transient_at_move
            and not self._transient_fired
        ):
            self._transient_fired = True
            raise InjectedTransientFault(
                f"injected transient device error at move {move} "
                f"(PUMI_TPU_FAULTS transient_at_move)"
            )

    def bitflip_at(self, move: int) -> bool:
        """``bitflip_flux``: True exactly once, after the matching move
        — the facade then flips one accumulator entry so the NEXT
        move's on-device flux invariant must catch it."""
        if (
            self.plan.bitflip_flux is not None
            and move == self.plan.bitflip_flux
            and not self._bitflip_fired
        ):
            self._bitflip_fired = True
            return True
        return False

    def sdc_at(self, move: int) -> bool:
        """``sdc_walk``: True exactly once, at the matching move's
        shadow audit — the audit then perturbs one sampled lane's
        production result so the float64 re-walk must flag it."""
        if (
            self.plan.sdc_walk is not None
            and move == self.plan.sdc_walk
            and not self._sdc_fired
        ):
            self._sdc_fired = True
            return True
        return False

    def maybe_hang(self, move: int) -> bool:
        """``hang_at_move``: sleep ``hang_seconds`` inside the dispatch
        closure at the matching move (once) — a wedged device dispatch
        the watchdog deadline must convert into a retryable timeout.
        Returns True when the hang fired (for fault accounting)."""
        if (
            self.plan.hang_at_move is not None
            and move == self.plan.hang_at_move
            and not self._hang_fired
        ):
            self._hang_fired = True
            import time

            time.sleep(self.plan.hang_seconds)
            return True
        return False

    def corrupt_destinations(self, dest, move: int) -> int:
        """NaN destination lanes IN PLACE with probability ``nan_src``,
        deterministically per (seed, move). ``dest`` must be the
        caller's float64 destination buffer (an out-param — the facade
        overwrites it at copy-back). Returns the lane count hit."""
        p = self.plan.nan_src
        if not p:
            return 0
        d = np.asarray(dest)
        if d.dtype != np.float64:
            # asarray would silently copy, NaN the copy, and report
            # lanes the caller's buffer never saw — refuse instead.
            raise TypeError(
                "nan_src needs the float64 destination out-param "
                f"buffer (in-place injection); got dtype {d.dtype}"
            )
        d = d.reshape(-1, 3)
        rng = np.random.default_rng([self.plan.seed, int(move)])
        bad = rng.random(d.shape[0]) < p
        d[bad] = np.nan
        return int(bad.sum())

    def corrupt_file(self, path: str) -> bool:
        """``corrupt_ckpt``: flip bytes in the middle of the file (past
        the zip header, inside a compressed member) so the container
        still opens but the payload fails its digest/CRC."""
        if not self.plan.corrupt_ckpt:
            return False
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(16)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        return True
