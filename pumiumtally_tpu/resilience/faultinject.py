"""Fault-injection harness: prove each failure mode recovers.

A resilience subsystem that is only exercised by real preemptions is
untested code on the critical path. This module injects the failure
modes the ``ResilientRunner`` claims to survive, deterministically,
from one env knob::

    PUMI_TPU_FAULTS=nan_src:0.01,die_at_move:3,corrupt_ckpt

Grammar: comma-separated ``name[:value]`` clauses —

  ``nan_src:P``           each move, each lane's destination is NaN'd
                          with probability P (deterministic per
                          (seed, move) — replays reproduce the faults);
  ``die_at_move:K``       the K-th facade move (1-based over the run,
                          i.e. ``iter_count + 1 == K``) raises
                          ``InjectedKill`` BEFORE the walk runs — a
                          preemption mid-campaign. Fires once per
                          injector (the resumed process is a new one);
  ``transient_at_move:K`` the K-th move raises
                          ``InjectedTransientFault`` once — the
                          retry-with-backoff path must absorb it;
  ``corrupt_ckpt``        every checkpoint the supervisor writes is
                          bit-flipped right after the write — the
                          ``find_latest`` fallback must skip it;
  ``bitflip_flux:K``      after the K-th facade move, one flux entry
                          gets its sign flipped (or NaN'd when the
                          accumulator is still empty) — a single-bit
                          SDC the integrity layer's on-device flux
                          invariant must catch on the NEXT move
                          (integrity/invariants.py);
  ``sdc_walk:K``          at the K-th move's shadow audit, one sampled
                          lane's production track length is perturbed —
                          a mis-scored segment the float64 audit
                          re-walk must flag (integrity/audit.py);
  ``hang_at_move:K``      the K-th move's device dispatch sleeps
                          ``hang_seconds`` (a wedged dispatch) — the
                          watchdog deadline must surface it as a
                          retryable DispatchTimeoutError
                          (integrity/watchdog.py);
  ``hang_seconds:S``      how long the injected hang sleeps (default
                          5.0; tests use fractions of a second so the
                          abandoned watchdog thread dies quickly);
  ``chip_down_at_move:K`` the K-th move raises ``ChipLostError`` once,
                          and the chip stays DOWN for every subsequent
                          health probe (``downed``) — the coordinator
                          must classify it chip-lost and the elastic
                          layer must re-partition onto the survivors
                          (resilience/coordinator.py, elastic.py);
  ``chip:C``              which chip ``chip_down_at_move`` kills
                          (default -1 = the last chip of the mesh);
  ``preempt_at_move:K``   the K-th move raises ``InjectedPreemption``
                          MID-MOVE (inside the supervised dispatch) —
                          the runner must flush the LAST-GOOD
                          generation, never the in-flight state, then
                          let it propagate like a real SIGTERM;
  ``torn_shard:G``        the G-th checkpoint generation the
                          supervisor writes is TORN right after the
                          commit: one shard file is truncated
                          mid-payload (single-file generations get the
                          corrupt_ckpt byte-flip), so its manifest
                          digest fails and find_latest must reject the
                          WHOLE generation atomically;
  ``poison_job:K``        the job with submission index K is POISON:
                          every scheduling quantum it dispatches
                          raises ``InjectedPoisonFault`` — a
                          persistent per-job failure the serving
                          scheduler must isolate (finish the job
                          ``poisoned``, free its slot) while every
                          other job continues bitwise
                          (serving/scheduler.py);
  ``transient_quantum:K`` job K's next scheduling quantum raises
                          ``InjectedTransientFault`` once — the
                          scheduler's bounded per-job retry must
                          replay the quantum bitwise from the job's
                          own snapshot;
  ``kill_server_at_quantum:Q`` the Q-th scheduling quantum the server
                          executes (1-based, counted across all jobs)
                          raises ``InjectedKill`` BEFORE the dispatch
                          — a server crash mid-run. Fires once per
                          injector (the restarted process is a new
                          one); recovery is the JOBS.json journal's
                          ``TallyScheduler.recover`` path;
  ``wedge_member:M``      fleet member M stops answering health probes
                          but HOLDS its jobs (no raise, no progress) —
                          the silent-wedge failure mode only the
                          supervisor's missed-heartbeat detection can
                          see (serving/supervisor.py). Persists until
                          the injector is swapped out;
  ``slow_member:M:F``     fleet member M's scheduling quanta run F×
                          their natural wall time (host-side injected
                          latency; device results are untouched, so
                          the job stays bitwise) — a brownout the
                          supervisor's latency SLO must flag without
                          false-positively evicting;
  ``disk_full_at:N``      the N-th durable write this injector gates
                          (journal flush, flux persist, quantum
                          checkpoint) — and every one after it, the
                          disk stays full — raises an ENOSPC OSError;
                          the journal must degrade instead of crash
                          (serving/journal.py);
  ``seed:S``              rng seed for nan_src lane choice (default 0).

The PR 2 modes (nan_src/die/transient/corrupt_ckpt) are driven by the
``ResilientRunner``'s injector; the integrity modes (bitflip_flux/
sdc_walk/hang_at_move) are driven by the FACADE's own injector so the
detectors they target see the corruption regardless of whether a
supervisor wraps the run.

The injector is a no-op when the plan is empty, so production code can
call its hooks unconditionally.
"""
from __future__ import annotations

import dataclasses
import errno
import os

import numpy as np


class InjectedFault(RuntimeError):
    """Base class for injected failures."""


class InjectedKill(InjectedFault):
    """Simulated preemption: NOT retryable — the supervisor must let it
    propagate (the process is 'dead'); recovery is the next process's
    auto-resume."""


class InjectedTransientFault(InjectedFault):
    """Simulated transient device/runtime error: retryable — the
    supervisor's backoff path must absorb it."""


class InjectedPreemption(InjectedKill):
    """Simulated preemption notice landing MID-MOVE: the supervisor
    flushes the last-GOOD generation (never the in-flight state) and
    then lets it propagate — the process is being evicted; recovery is
    the next process's auto-resume."""


class InjectedPoisonFault(InjectedFault):
    """Simulated persistent per-job failure (a poison job): NOT
    retryable — replaying the same request hits the same failure every
    time. The serving scheduler must isolate it (job finished
    ``poisoned``, device slot freed) instead of retrying forever or
    taking the server down with it."""


class ChipLostError(RuntimeError):
    """A device dropped out of the mesh. Raised by the injector
    (``chip_down_at_move``) and by the coordinator when a health probe
    finds a dead chip behind a runtime error. NOT plain-retryable: an
    in-place replay would re-dispatch onto the dead chip — recovery is
    the coordinated rollback + elastic mesh-shrink path
    (resilience/coordinator.py, elastic.py)."""

    def __init__(self, message: str, chip: int = -1):
        super().__init__(message)
        self.chip = int(chip)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    nan_src: float = 0.0
    die_at_move: int | None = None
    transient_at_move: int | None = None
    corrupt_ckpt: bool = False
    bitflip_flux: int | None = None
    sdc_walk: int | None = None
    hang_at_move: int | None = None
    hang_seconds: float = 5.0
    chip_down_at_move: int | None = None
    chip: int = -1
    preempt_at_move: int | None = None
    torn_shard: int | None = None
    poison_job: int | None = None
    transient_quantum: int | None = None
    kill_server_at_quantum: int | None = None
    wedge_member: int | None = None
    slow_member: int | None = None
    slow_factor: float = 1.0
    disk_full_at: int | None = None
    seed: int = 0

    def any(self) -> bool:
        return bool(
            self.nan_src
            or self.die_at_move is not None
            or self.transient_at_move is not None
            or self.corrupt_ckpt
            or self.bitflip_flux is not None
            or self.sdc_walk is not None
            or self.hang_at_move is not None
            or self.chip_down_at_move is not None
            or self.preempt_at_move is not None
            or self.torn_shard is not None
            or self.poison_job is not None
            or self.transient_quantum is not None
            or self.kill_server_at_quantum is not None
            or self.wedge_member is not None
            or self.slow_member is not None
            or self.disk_full_at is not None
        )


def parse_faults(spec: str) -> FaultPlan:
    """Parse the ``PUMI_TPU_FAULTS`` grammar (module docstring). Raises
    ``ValueError`` on unknown clauses or malformed values — a typo'd
    fault spec silently injecting nothing would defeat the tests."""
    fields: dict = {}
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        name, _, value = clause.partition(":")
        if name == "nan_src":
            fields["nan_src"] = float(value)
            if not 0.0 <= fields["nan_src"] <= 1.0:
                raise ValueError(
                    f"nan_src must be a probability: {value!r}"
                )
        elif name == "die_at_move":
            fields["die_at_move"] = int(value)
        elif name == "transient_at_move":
            fields["transient_at_move"] = int(value)
        elif name == "corrupt_ckpt":
            if value:
                raise ValueError("corrupt_ckpt takes no value")
            fields["corrupt_ckpt"] = True
        elif name == "bitflip_flux":
            fields["bitflip_flux"] = int(value)
        elif name == "sdc_walk":
            fields["sdc_walk"] = int(value)
        elif name == "hang_at_move":
            fields["hang_at_move"] = int(value)
        elif name == "hang_seconds":
            fields["hang_seconds"] = float(value)
            if fields["hang_seconds"] <= 0:
                raise ValueError(
                    f"hang_seconds must be positive: {value!r}"
                )
        elif name == "chip_down_at_move":
            fields["chip_down_at_move"] = int(value)
        elif name == "chip":
            fields["chip"] = int(value)
        elif name == "preempt_at_move":
            fields["preempt_at_move"] = int(value)
        elif name == "torn_shard":
            fields["torn_shard"] = int(value)
            if fields["torn_shard"] < 1:
                raise ValueError(
                    f"torn_shard counts generations from 1: {value!r}"
                )
        elif name == "poison_job":
            fields["poison_job"] = int(value)
        elif name == "transient_quantum":
            fields["transient_quantum"] = int(value)
        elif name == "kill_server_at_quantum":
            fields["kill_server_at_quantum"] = int(value)
            if fields["kill_server_at_quantum"] < 1:
                raise ValueError(
                    "kill_server_at_quantum counts quanta from 1: "
                    f"{value!r}"
                )
        elif name == "wedge_member":
            fields["wedge_member"] = int(value)
        elif name == "slow_member":
            member, _, factor = value.partition(":")
            fields["slow_member"] = int(member)
            fields["slow_factor"] = float(factor) if factor else 4.0
            if fields["slow_factor"] < 1.0:
                raise ValueError(
                    f"slow_member factor must be >= 1: {value!r}"
                )
        elif name == "disk_full_at":
            fields["disk_full_at"] = int(value)
            if fields["disk_full_at"] < 1:
                raise ValueError(
                    f"disk_full_at counts durable writes from 1: "
                    f"{value!r}"
                )
        elif name == "seed":
            fields["seed"] = int(value)
        else:
            raise ValueError(
                f"unknown fault {name!r} in PUMI_TPU_FAULTS "
                f"(known: nan_src, die_at_move, transient_at_move, "
                f"corrupt_ckpt, bitflip_flux, sdc_walk, hang_at_move, "
                f"hang_seconds, chip_down_at_move, chip, "
                f"preempt_at_move, torn_shard, poison_job, "
                f"transient_quantum, kill_server_at_quantum, "
                f"wedge_member, slow_member, disk_full_at, seed)"
            )
    return FaultPlan(**fields)


def plan_from_env() -> FaultPlan:
    return parse_faults(os.environ.get("PUMI_TPU_FAULTS", ""))


class FaultInjector:
    """Stateful per-process injector over a FaultPlan.

    ``die_at_move`` / ``transient_at_move`` fire at most once per
    injector instance — the model is one failure per process life, and
    a resumed run constructs a fresh injector (usually with a fresh
    env)."""

    def __init__(self, plan: FaultPlan | None = None):
        self.plan = plan if plan is not None else plan_from_env()
        self._died = False
        self._transient_fired = False
        self._bitflip_fired = False
        self._sdc_fired = False
        self._hang_fired = False
        self._preempt_fired = False
        #: Chip indices this injector has killed (the once-only guard;
        #: the runner forwards each raise to
        #: ``ResilienceCoordinator.note_down``, which pins the DEVICE
        #: so later probes keep it dead across reshards — the CPU test
        #: mesh has no way to actually lose a device).
        self.downed: set[int] = set()
        self._ckpt_writes = 0
        self._torn_fired = False
        self._quantum_transient_fired = False
        self._server_killed = False
        self._durable_writes = 0

    # ------------------------------------------------------------------ #
    def maybe_die(self, move: int) -> None:
        if (
            self.plan.die_at_move is not None
            and move == self.plan.die_at_move
            and not self._died
        ):
            self._died = True
            raise InjectedKill(
                f"injected preemption at move {move} "
                f"(PUMI_TPU_FAULTS die_at_move)"
            )

    def maybe_transient(self, move: int) -> None:
        if (
            self.plan.transient_at_move is not None
            and move == self.plan.transient_at_move
            and not self._transient_fired
        ):
            self._transient_fired = True
            raise InjectedTransientFault(
                f"injected transient device error at move {move} "
                f"(PUMI_TPU_FAULTS transient_at_move)"
            )

    def maybe_chip_down(self, move: int) -> None:
        """``chip_down_at_move``: lose a chip at the matching move —
        raises ``ChipLostError`` once and marks the chip permanently
        down for the health probe."""
        if (
            self.plan.chip_down_at_move is not None
            and move == self.plan.chip_down_at_move
            and self.plan.chip not in self.downed
        ):
            self.downed.add(self.plan.chip)
            raise ChipLostError(
                f"injected chip loss at move {move} "
                f"(PUMI_TPU_FAULTS chip_down_at_move, chip "
                f"{self.plan.chip})",
                chip=self.plan.chip,
            )

    def maybe_preempt(self, move: int) -> None:
        """``preempt_at_move``: a preemption notice landing mid-move
        (inside the supervised dispatch), once."""
        if (
            self.plan.preempt_at_move is not None
            and move == self.plan.preempt_at_move
            and not self._preempt_fired
        ):
            self._preempt_fired = True
            raise InjectedPreemption(
                f"injected preemption at move {move} "
                f"(PUMI_TPU_FAULTS preempt_at_move)"
            )

    # -- serving-scheduler hooks (per-JOB fault targeting) ------------- #
    def maybe_poison_job(self, job_index: int) -> None:
        """``poison_job:K``: job K's quantum dispatches raise a
        PERSISTENT fault — every time, not once; a poison request does
        not get better on replay. The scheduler must classify it
        persistent and isolate the job."""
        if (
            self.plan.poison_job is not None
            and job_index == self.plan.poison_job
        ):
            raise InjectedPoisonFault(
                f"injected poison job at index {job_index} "
                f"(PUMI_TPU_FAULTS poison_job)"
            )

    def maybe_transient_quantum(self, job_index: int) -> None:
        """``transient_quantum:K``: job K's next quantum raises a
        transient once — the scheduler's bounded retry must absorb it
        with a bitwise replay from the job's own snapshot."""
        if (
            self.plan.transient_quantum is not None
            and job_index == self.plan.transient_quantum
            and not self._quantum_transient_fired
        ):
            self._quantum_transient_fired = True
            raise InjectedTransientFault(
                f"injected transient quantum for job {job_index} "
                f"(PUMI_TPU_FAULTS transient_quantum)"
            )

    def maybe_kill_server(self, quantum: int) -> None:
        """``kill_server_at_quantum:Q``: the server 'crashes' before
        dispatching its Q-th scheduling quantum (1-based, across all
        jobs), once per injector. The write-ahead journal must make
        the next process's ``recover`` resume every job."""
        if (
            self.plan.kill_server_at_quantum is not None
            and quantum == self.plan.kill_server_at_quantum
            and not self._server_killed
        ):
            self._server_killed = True
            raise InjectedKill(
                f"injected server kill at quantum {quantum} "
                f"(PUMI_TPU_FAULTS kill_server_at_quantum)"
            )

    # -- fleet-supervisor hooks (per-MEMBER fault targeting) ----------- #
    def member_wedged(self, member_index: int | None) -> bool:
        """``wedge_member:M``: True while member M is wedged — it
        answers no health probe and makes no progress, but holds its
        jobs and device state. Not once-only: a wedge persists until
        the member's injector is replaced (chaos harnesses model
        un-wedging by swapping in a clean injector)."""
        return (
            self.plan.wedge_member is not None
            and member_index == self.plan.wedge_member
        )

    def slow_quantum_extra(
        self, member_index: int | None, base_s: float
    ) -> float:
        """``slow_member:M:F``: extra host-side seconds to sleep after
        member M's quantum so the quantum's wall time is ~F× its
        natural duration. Device results are untouched — the brownout
        is pure latency, and the job stays bitwise."""
        if (
            self.plan.slow_member is None
            or member_index != self.plan.slow_member
        ):
            return 0.0
        return max(0.0, (self.plan.slow_factor - 1.0) * float(base_s))

    def maybe_disk_full(self) -> None:
        """``disk_full_at:N``: the N-th durable write this injector
        gates — and every write after it; an injected full disk stays
        full — raises an ENOSPC ``OSError``. The journal layer must
        convert it into degraded mode, never a crash."""
        if self.plan.disk_full_at is None:
            return
        self._durable_writes += 1
        if self._durable_writes >= self.plan.disk_full_at:
            raise OSError(
                errno.ENOSPC,
                f"injected disk full at durable write "
                f"{self._durable_writes} (PUMI_TPU_FAULTS disk_full_at)",
            )

    def bitflip_at(self, move: int) -> bool:
        """``bitflip_flux``: True exactly once, after the matching move
        — the facade then flips one accumulator entry so the NEXT
        move's on-device flux invariant must catch it."""
        if (
            self.plan.bitflip_flux is not None
            and move == self.plan.bitflip_flux
            and not self._bitflip_fired
        ):
            self._bitflip_fired = True
            return True
        return False

    def sdc_at(self, move: int) -> bool:
        """``sdc_walk``: True exactly once, at the matching move's
        shadow audit — the audit then perturbs one sampled lane's
        production result so the float64 re-walk must flag it."""
        if (
            self.plan.sdc_walk is not None
            and move == self.plan.sdc_walk
            and not self._sdc_fired
        ):
            self._sdc_fired = True
            return True
        return False

    def maybe_hang(self, move: int) -> bool:
        """``hang_at_move``: sleep ``hang_seconds`` inside the dispatch
        closure at the matching move (once) — a wedged device dispatch
        the watchdog deadline must convert into a retryable timeout.
        Returns True when the hang fired (for fault accounting)."""
        if (
            self.plan.hang_at_move is not None
            and move == self.plan.hang_at_move
            and not self._hang_fired
        ):
            self._hang_fired = True
            import time

            time.sleep(self.plan.hang_seconds)
            return True
        return False

    def corrupt_destinations(self, dest, move: int) -> int:
        """NaN destination lanes IN PLACE with probability ``nan_src``,
        deterministically per (seed, move). ``dest`` must be the
        caller's float64 destination buffer (an out-param — the facade
        overwrites it at copy-back). Returns the lane count hit."""
        p = self.plan.nan_src
        if not p:
            return 0
        d = np.asarray(dest)
        if d.dtype != np.float64:
            # asarray would silently copy, NaN the copy, and report
            # lanes the caller's buffer never saw — refuse instead.
            raise TypeError(
                "nan_src needs the float64 destination out-param "
                f"buffer (in-place injection); got dtype {d.dtype}"
            )
        d = d.reshape(-1, 3)
        rng = np.random.default_rng([self.plan.seed, int(move)])
        bad = rng.random(d.shape[0]) < p
        d[bad] = np.nan
        return int(bad.sum())

    @staticmethod
    def _flip_bytes(path: str) -> None:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(16)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))

    @staticmethod
    def _shard_files(dirname: str) -> list[str]:
        return sorted(
            os.path.join(dirname, n)
            for n in os.listdir(dirname)
            if n.startswith("shard-") and n.endswith(".npz")
        )

    def corrupt_file(self, path: str) -> bool:
        """``corrupt_ckpt``: flip bytes in the middle of the file (past
        the zip header, inside a compressed member) so the container
        still opens but the payload fails its digest/CRC. Sharded
        generations (directories) get one shard flipped — the manifest
        digest check must then reject the whole generation."""
        if not self.plan.corrupt_ckpt:
            return False
        if os.path.isdir(path):
            path = self._shard_files(path)[0]
        self._flip_bytes(path)
        return True

    def maybe_tear(self, path: str) -> bool:
        """``torn_shard:G``: tear the G-th generation this injector
        sees written — truncate one shard file mid-payload (a torn
        concurrent multi-shard write surfacing AFTER the manifest
        commit), or byte-flip a single-file generation. The store's
        digest checks must reject the whole generation atomically."""
        if self.plan.torn_shard is None:
            return False
        self._ckpt_writes += 1
        if self._ckpt_writes != self.plan.torn_shard or self._torn_fired:
            return False
        self._torn_fired = True
        if os.path.isdir(path):
            target = self._shard_files(path)[-1]
            with open(target, "r+b") as f:
                f.truncate(os.path.getsize(target) // 2)
        else:
            self._flip_bytes(path)
        return True


# --------------------------------------------------------------------- #
# Chaos campaigns: a randomized-but-seeded multi-fault schedule
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """A concrete multi-fault schedule drawn deterministically from a
    seed (``chaos_plan``) — the campaign driver's unit
    (scripts/chaos.py, scripts/soak_walk.py --chaos)."""

    transient_moves: tuple = ()
    chip_down_move: int | None = None
    chip: int = -1
    preempt_move: int | None = None
    torn_generation: int | None = None
    poison_job: int | None = None
    transient_quantum: int | None = None
    kill_server_at_quantum: int | None = None
    wedge_member: int | None = None
    slow_member: int | None = None
    slow_factor: float = 1.0
    disk_full_at: int | None = None
    seed: int = 0

    def describe(self) -> str:
        bits = [f"seed:{self.seed}"]
        if self.transient_moves:
            bits.append(
                "transients@" + ",".join(map(str, self.transient_moves))
            )
        if self.chip_down_move is not None:
            bits.append(f"chip_down@{self.chip_down_move}(chip {self.chip})")
        if self.preempt_move is not None:
            bits.append(f"preempt@{self.preempt_move}")
        if self.torn_generation is not None:
            bits.append(f"torn_shard@gen{self.torn_generation}")
        if self.poison_job is not None:
            bits.append(f"poison_job@{self.poison_job}")
        if self.transient_quantum is not None:
            bits.append(f"transient_quantum@job{self.transient_quantum}")
        if self.kill_server_at_quantum is not None:
            bits.append(f"kill_server@q{self.kill_server_at_quantum}")
        if self.wedge_member is not None:
            bits.append(f"wedge_member@{self.wedge_member}")
        if self.slow_member is not None:
            bits.append(
                f"slow_member@{self.slow_member}x{self.slow_factor:g}"
            )
        if self.disk_full_at is not None:
            bits.append(f"disk_full@write{self.disk_full_at}")
        return " ".join(bits)


def chaos_plan(spec: str, n_moves: int) -> ChaosPlan:
    """Draw a concrete schedule from a chaos spec. Grammar
    (comma-separated ``name[:value]``):

      ``transients:N``  N transient device errors at distinct random
                        moves;
      ``chip_down:1``   one chip loss at a random move (value 0 = off);
      ``chip:C``        which chip it kills (default -1 = last);
      ``preempt:1``     one mid-move preemption at a random move AFTER
                        every other fault (so recovery is exercised
                        before the eviction);
      ``torn:G``        tear the G-th checkpoint generation written;
      ``poison_job:K``  job index K is poison (serving campaigns);
      ``transient_quantum:K``  one transient on job K's next quantum;
      ``kill_server:Q`` the server dies before its Q-th quantum;
      ``wedge_member:M``  fleet member M silently wedges;
      ``slow_member:M:F`` fleet member M runs F× slower (default 4×);
      ``disk_full:N``   member-local disk fills at durable write N;
      ``seed:S``        the schedule seed (default 0).

    Same spec + seed + n_moves → the same schedule, so a chaos soak
    failure reproduces exactly."""
    counts = {"transients": 0, "chip_down": 0, "preempt": 0}
    chip, torn, seed = -1, None, 0
    poison_job = transient_quantum = kill_server = None
    wedge_member = slow_member = disk_full = None
    slow_factor = 1.0
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        name, _, value = clause.partition(":")
        if name in counts:
            counts[name] = int(value or "1")
        elif name == "chip":
            chip = int(value)
        elif name == "torn":
            torn = int(value)
        elif name == "poison_job":
            poison_job = int(value)
        elif name == "transient_quantum":
            transient_quantum = int(value)
        elif name == "kill_server":
            kill_server = int(value)
        elif name == "wedge_member":
            wedge_member = int(value)
        elif name == "slow_member":
            member, _, factor = value.partition(":")
            slow_member = int(member)
            slow_factor = float(factor) if factor else 4.0
        elif name == "disk_full":
            disk_full = int(value)
        elif name == "seed":
            seed = int(value)
        else:
            raise ValueError(
                f"unknown chaos clause {name!r} (known: transients, "
                "chip_down, chip, preempt, torn, poison_job, "
                "transient_quantum, kill_server, wedge_member, "
                "slow_member, disk_full, seed)"
            )
    rng = np.random.default_rng([987654321, seed])
    # Faults land in [2, n_moves-1]: move 1 establishes a good state
    # first and the final move proves post-recovery steady state.
    lo, hi = 2, max(2, int(n_moves) - 1)
    candidates = np.arange(lo, hi + 1)
    n_t = min(counts["transients"], candidates.size)
    transients = tuple(
        sorted(
            int(m)
            for m in rng.choice(candidates, size=n_t, replace=False)
        )
    )
    chip_down = (
        int(rng.choice(candidates)) if counts["chip_down"] else None
    )
    preempt = None
    if counts["preempt"]:
        floor = max([lo, *transients, chip_down or lo])
        preempt = int(rng.integers(floor, hi + 1))
    return ChaosPlan(
        transient_moves=transients,
        chip_down_move=chip_down,
        chip=chip,
        preempt_move=preempt,
        torn_generation=torn,
        poison_job=poison_job,
        transient_quantum=transient_quantum,
        kill_server_at_quantum=kill_server,
        wedge_member=wedge_member,
        slow_member=slow_member,
        slow_factor=slow_factor,
        disk_full_at=disk_full,
        seed=seed,
    )


class ChaosInjector(FaultInjector):
    """A FaultInjector driven by a ChaosPlan schedule: transients can
    fire at SEVERAL moves (fault storms), a chip loss and a preemption
    can ride the same run (fault-during-recovery compositions), and a
    generation tear composes with all of them. Each scheduled fault
    fires once. The serving-side faults (poison job / transient
    quantum / server kill) ride the inherited FaultPlan hooks, so one
    chaos schedule can compose per-move and per-job failures."""

    def __init__(self, plan: ChaosPlan):
        super().__init__(FaultPlan(
            torn_shard=plan.torn_generation,
            poison_job=plan.poison_job,
            transient_quantum=plan.transient_quantum,
            kill_server_at_quantum=plan.kill_server_at_quantum,
            wedge_member=plan.wedge_member,
            slow_member=plan.slow_member,
            slow_factor=plan.slow_factor,
            disk_full_at=plan.disk_full_at,
        ))
        self.chaos = plan
        self._fired_transients: set[int] = set()

    def maybe_transient(self, move: int) -> None:
        if (
            move in self.chaos.transient_moves
            and move not in self._fired_transients
        ):
            self._fired_transients.add(move)
            raise InjectedTransientFault(
                f"chaos transient at move {move} "
                f"({self.chaos.describe()})"
            )

    def maybe_chip_down(self, move: int) -> None:
        if (
            self.chaos.chip_down_move is not None
            and move == self.chaos.chip_down_move
            and self.chaos.chip not in self.downed
        ):
            self.downed.add(self.chaos.chip)
            raise ChipLostError(
                f"chaos chip loss at move {move} "
                f"({self.chaos.describe()})",
                chip=self.chaos.chip,
            )

    def maybe_preempt(self, move: int) -> None:
        if (
            self.chaos.preempt_move is not None
            and move == self.chaos.preempt_move
            and not self._preempt_fired
        ):
            self._preempt_fired = True
            raise InjectedPreemption(
                f"chaos preemption at move {move} "
                f"({self.chaos.describe()})"
            )
