"""ResilienceCoordinator: failure taxonomy + per-chip health probing.

The ``ResilientRunner`` sees one exception per failed dispatch; what it
should DO depends on what actually happened on the fleet. This module
owns that verdict — the failure taxonomy the elastic recovery layer
dispatches on:

  * ``"transient"`` — a one-shot device/runtime error (injected
    transients, retryable JAX runtime errors, a watchdog timeout with
    every chip still answering its probe). Recovery: roll every part
    back to the last good state and replay BITWISE (same layout).
  * ``"chip-lost"`` — a device dropped out of the mesh (injected
    ``chip_down_at_move``, or a runtime error/timeout behind which the
    health probe finds a dead chip). An in-place replay would
    re-dispatch onto the dead chip; recovery is coordinated rollback
    of EVERY part to the same generation plus an elastic mesh-shrink
    re-partition onto the survivors (resilience/elastic.py).
  * ``"preempted"`` — an eviction notice (``InjectedPreemption``, or a
    real SIGTERM/SIGINT through the runner's handlers). Recovery: one
    final flush of the LAST-GOOD generation, then die; the next
    process auto-resumes.
  * ``"persistent"`` — a failure replay cannot fix (a fatal integrity
    violation, an injected poison job): retrying burns the bounded
    budget on a deterministic failure. The runner surfaces these
    before classification (its halt path); the serving scheduler
    dispatches on the verdict — the job is POISONED (finished
    ``outcome="poisoned"``, slot freed) and every other job continues
    bitwise (serving/scheduler.py).

The health probe stages a tiny round-trip computation on every chip of
the tally's mesh (a dead TPU fails the put or returns garbage) and
also checks the ``downed_devices`` set the runner feeds via
``note_down`` on every ``ChipLostError`` — by device identity, never
by index, since an elastic shrink re-indexes the mesh. On the
single-process CPU test mesh, where devices cannot actually die,
injected chip losses flow through exactly that path, so the chaos
suite exercises the production classify→probe→shrink pipeline. Results
are exported per chip through the ``pumi_chip_health`` gauge on the
tally's registry (the PR 5 Prometheus endpoint serves it), alongside
``pumi_rollbacks_total{cause=...}`` and
``pumi_elastic_reshards_total`` which the runner feeds as it acts on
the verdicts.
"""
from __future__ import annotations

import numpy as np

from ..integrity.policy import FatalIntegrityViolation
from ..integrity.watchdog import DispatchTimeoutError
from .faultinject import (
    ChipLostError,
    FaultInjector,
    InjectedPoisonFault,
    InjectedPreemption,
    InjectedTransientFault,
)

try:  # pragma: no cover - depends on installed jax
    from jax.errors import JaxRuntimeError as _JaxRuntimeError
except ImportError:  # pragma: no cover
    class _JaxRuntimeError(Exception):
        """Placeholder when jax.errors lacks JaxRuntimeError."""


#: The classifier's verdicts, in escalation order.
VERDICTS = ("transient", "chip-lost", "preempted", "persistent")


class ResilienceCoordinator:
    def __init__(self, tally, faults: FaultInjector | None = None,
                 tracer=None):
        self.tally = tally
        self.faults = faults if faults is not None else FaultInjector()
        # Span tracer (obs/trace.py): the serving scheduler passes its
        # own so classify/probe spans land in the failing job's trace
        # via the ambient binding; standalone use gets a private
        # (ring-only) tracer.
        if tracer is None:
            from ..obs import SpanTracer

            tracer = SpanTracer()
        self.tracer = tracer
        r = tally.metrics
        self.c_rollbacks = r.counter(
            "pumi_rollbacks_total",
            "coordinated rollbacks to the last good generation "
            "(labeled by cause: transient, chip-lost, preempted, "
            "integrity)",
        )
        self.c_reshards = r.counter(
            "pumi_elastic_reshards_total",
            "elastic mesh-shrink recoveries (re-partition onto the "
            "surviving device set)",
        )
        self._g_health = r.gauge(
            "pumi_chip_health",
            "per-chip health probe result (1 = answering, 0 = lost)",
        )
        # Dead chips by DEVICE IDENTITY, not index: after an elastic
        # shrink the mesh re-indexes, so a stored index would point at
        # a healthy survivor (note_down resolves index -> device at
        # failure time, while the failing mesh is still current).
        self.downed_devices: set = set()
        self._last_probe: dict[int, bool] | None = None

    def rebind(self, tally) -> None:
        """Point at the post-reshard tally (the registry travels with
        the telemetry transplant, so the counters keep counting)."""
        self.tally = tally

    def note_rollback(self, cause: str) -> None:
        """Count one coordinated rollback and mark it in the current
        trace (the runner calls this as it restores the last good
        generation)."""
        self.c_rollbacks.inc(cause=cause)
        self.tracer.event("rollback", cause=cause)

    # ------------------------------------------------------------------ #
    def devices(self) -> list:
        """The tally's device set, mesh order: the partitioned facade's
        device mesh, or the single device the plain facade's arrays
        live on."""
        dm = getattr(self.tally, "device_mesh", None)
        if dm is not None:
            return list(dm.devices.flat)
        import jax

        return [jax.devices()[0]]

    def note_down(self, chip_index: int) -> None:
        """Record a failed chip by DEVICE while the mesh it indexed is
        still current (the runner calls this on every
        ``ChipLostError``, before any reshard re-indexes the fleet)."""
        devs = self.devices()
        self.downed_devices.add(devs[chip_index % len(devs)])

    def consume_last_probe(self) -> dict[int, bool] | None:
        """Hand the recovery path the probe ``classify`` already ran
        for this failure (None when the verdict needed no probe) —
        probing a dead chip blocks until its own timeout, so one
        incident should pay for it once."""
        probe, self._last_probe = self._last_probe, None
        return probe

    def probe_chips(self) -> dict[int, bool]:
        """Per-chip liveness: stage a tiny array onto each chip and
        read it back (mutation-free — no tally state is touched).
        Known-dead devices (``note_down``; on the CPU test mesh the
        stand-in for a chip that stopped answering) report dead
        without a dispatch. Updates the ``pumi_chip_health`` gauge per
        chip."""
        import jax

        health: dict[int, bool] = {}
        with self.tracer.span("probe") as sp:
            for i, dev in enumerate(self.devices()):
                if dev in self.downed_devices:
                    ok = False
                else:
                    try:
                        probe = jax.device_put(
                            np.ones(2, np.float32), dev
                        )
                        ok = float(np.asarray(probe).sum()) == 2.0
                    except Exception:
                        ok = False
                health[i] = ok
                self._g_health.set(1.0 if ok else 0.0, chip=str(i))
            sp["chips"] = len(health)
            sp["dead"] = sum(1 for ok in health.values() if not ok)
        return health

    # ------------------------------------------------------------------ #
    def classify(self, exc: BaseException) -> str:
        """Name the failure (module docstring taxonomy). Ambiguous
        runtime errors — a hung dispatch, a JAX runtime error — are
        resolved by PROBING: a dead chip behind them upgrades the
        verdict to chip-lost; all chips answering means transient."""
        with self.tracer.span(
            "classify", exc=type(exc).__name__,
        ) as sp:
            verdict = self._classify(exc)
            sp["verdict"] = verdict
        return verdict

    def _classify(self, exc: BaseException) -> str:
        # A probe is retained ONLY for a chip-lost verdict it just
        # produced (consumed by the recovery that follows); anything
        # older is stale — a later failure must probe afresh, or a
        # bygone all-healthy map would make the recovery skip the
        # shrink and re-dispatch onto the dead chip.
        self._last_probe = None
        if isinstance(exc, (FatalIntegrityViolation, InjectedPoisonFault)):
            # Deterministic failures: replaying the same inputs hits
            # them again — no probe can soften the verdict. (The
            # runner's halt path intercepts FatalIntegrityViolation
            # before classifying; the serving scheduler dispatches on
            # this verdict to poison exactly one job.)
            return "persistent"
        if isinstance(exc, InjectedPreemption):
            return "preempted"
        if isinstance(exc, ChipLostError):
            return "chip-lost"
        if isinstance(exc, (DispatchTimeoutError, _JaxRuntimeError)):
            health = self.probe_chips()
            if not all(health.values()):
                self._last_probe = health
                return "chip-lost"
            return "transient"
        if isinstance(exc, InjectedTransientFault):
            return "transient"
        return "transient"
