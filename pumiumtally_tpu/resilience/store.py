"""Rotating generations of durable checkpoints.

One directory holds the run's checkpoint history as
``ckpt-<iteration>.npz`` files (atomic writes + per-array sha256, see
utils/checkpoint.py). The store keeps the newest ``keep`` generations,
and ``find_latest``/``restore_latest`` walk newest→oldest SKIPPING
corrupt files — a torn write or bit-rot in the newest generation falls
back to the previous one instead of killing the resume. A genuinely
mismatched checkpoint (wrong mesh/config) still raises: that is a
caller bug, not corruption, and silently skipping it would resume the
wrong run.
"""
from __future__ import annotations

import os
import re

from ..utils.checkpoint import (
    CheckpointIntegrityError,
    fsync_dir,
    verify_checkpoint,
)
from ..utils.log import log_info, log_warn

_NAME_RE = re.compile(r"^(?P<prefix>.+)-(?P<it>\d+)\.npz$")


class CheckpointStore:
    def __init__(self, directory: str, keep: int = 3,
                 prefix: str = "ckpt"):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.keep = int(keep)
        self.prefix = prefix
        os.makedirs(directory, exist_ok=True)
        self._sweep_orphaned_tmp()

    def _sweep_orphaned_tmp(self) -> None:
        """A SIGKILL/power-loss mid-write leaves atomic_savez's temp
        file behind (in-process cleanup never ran); rotation ignores
        non-generation names, so sweep them here or they accumulate
        forever across preemption cycles."""
        for name in os.listdir(self.directory):
            if name.startswith(f"{self.prefix}-") and ".tmp-" in name:
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass

    # ------------------------------------------------------------------ #
    def path_for(self, iteration: int) -> str:
        return os.path.join(
            self.directory, f"{self.prefix}-{int(iteration):08d}.npz"
        )

    def entries(self) -> list[tuple[int, str]]:
        """(iteration, path) pairs sorted oldest→newest."""
        out = []
        for name in os.listdir(self.directory):
            m = _NAME_RE.match(name)
            if m and m.group("prefix") == self.prefix:
                out.append(
                    (int(m.group("it")),
                     os.path.join(self.directory, name))
                )
        return sorted(out)

    # ------------------------------------------------------------------ #
    def save(self, tally) -> str:
        """Write the tally's checkpoint as the next generation
        (``ckpt-<iter_count>.npz``) and rotate old generations out."""
        path = self.path_for(tally.iter_count)
        tally.save_checkpoint(path)
        self._rotate()
        return path

    def _rotate(self) -> None:
        removed = False
        for _, path in self.entries()[: -self.keep]:
            try:
                os.unlink(path)
                removed = True
            except OSError as e:
                log_warn(
                    f"checkpoint rotation could not remove {path}: {e}"
                )
        if removed:
            # Make the unlinks durable: without the directory fsync a
            # power cut can resurrect a rotated-out generation while
            # losing the newest rename — find_latest would then resume
            # an OLDER state than the rotation promised survives
            # (utils/checkpoint.fsync_dir).
            fsync_dir(self.directory)

    # ------------------------------------------------------------------ #
    def find_latest(self) -> tuple[int, str] | None:
        """Newest generation that passes the integrity check; corrupt
        files are skipped with a warning (the fallback contract). The
        same mismatch-vs-corruption rule as ``restore_latest``: an
        INTACT file of another format/shape raises instead of being
        skipped, so the two lookups always agree on a directory."""
        for it, path in reversed(self.entries()):
            try:
                verify_checkpoint(path)
                return it, path
            except CheckpointIntegrityError as e:
                log_warn(f"skipping corrupt checkpoint {path}: {e}")
            except ValueError:
                raise
            except Exception as e:
                log_warn(f"skipping unreadable checkpoint {path}: {e}")
        return None

    def restore_latest(self, tally) -> int | None:
        """Restore the newest VALID generation into ``tally``; returns
        its iteration, or None when no restorable generation exists.
        Corruption (bad container, failed digest) falls back to the
        previous generation; a clean-but-mismatched checkpoint raises —
        see the module docstring for why the two differ."""
        for it, path in reversed(self.entries()):
            try:
                tally.restore_checkpoint(path)
                log_info(
                    f"resumed from checkpoint {path}", iteration=it
                )
                return it
            except CheckpointIntegrityError as e:
                log_warn(f"skipping corrupt checkpoint {path}: {e}")
            except ValueError:
                # Intact but incompatible (mesh/dtype/shape): caller bug.
                raise
            except Exception as e:
                # Unreadable container (truncated zip, zlib error, OS
                # error): corruption by another name — fall back.
                log_warn(f"skipping unreadable checkpoint {path}: {e}")
        return None
