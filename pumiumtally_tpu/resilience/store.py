"""Rotating generations of durable checkpoints.

One directory holds the run's checkpoint history, one generation per
entry, in either on-disk layout (utils/checkpoint.py):

  * ``ckpt-<iteration>.npz``    — single atomic file (per-array sha256);
  * ``ckpt-<iteration>.shards`` — a DIRECTORY of per-mesh-part shard
    npz files plus a ``MANIFEST.json`` committed last (two-phase
    commit; the partitioned facade's default through
    ``ResilientRunner``).

The store keeps the newest ``keep`` generations, and
``find_latest``/``restore_latest`` walk newest→oldest SKIPPING corrupt
generations — a torn write or bit-rot in the newest generation falls
back to the previous one instead of killing the resume. For sharded
generations "corrupt" is atomic over the WHOLE generation: a missing
manifest, a missing shard, or any shard digest mismatch rejects every
shard of that generation together (no Frankenstein restore mixing
shard vintages). A genuinely mismatched checkpoint (wrong mesh/config)
still raises: that is a caller bug, not corruption, and silently
skipping it would resume the wrong run.
"""
from __future__ import annotations

import os
import re
import shutil

from ..utils.checkpoint import (
    MANIFEST_NAME,
    SHARD_SUFFIX,
    CheckpointIntegrityError,
    fsync_dir,
    verify_checkpoint,
)
from ..utils.log import log_info, log_warn

_NAME_RE = re.compile(r"^(?P<prefix>.+)-(?P<it>\d+)\.npz$")
_SHARD_RE = re.compile(r"^(?P<prefix>.+)-(?P<it>\d+)\.shards$")


class CheckpointStore:
    def __init__(self, directory: str, keep: int = 3,
                 prefix: str = "ckpt",
                 shards: int | str | None = "auto"):
        """``shards`` picks the on-disk generation layout: "auto"
        (default) writes one shard per mesh part for partitioned
        tallies and the single-file layout for everything else; an int
        forces that shard count; None/0 forces single-file (the pre-
        sharding behavior, byte-identical)."""
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.keep = int(keep)
        self.prefix = prefix
        self.shards = shards
        #: Shard count of the last ``save`` (0 for single-file) — the
        #: supervisor's pumi_checkpoint_shards_written_total feed.
        self.last_shards = 0
        os.makedirs(directory, exist_ok=True)
        self._sweep_orphaned_tmp()

    def _sweep_orphaned_tmp(self) -> None:
        """A SIGKILL/power-loss mid-write leaves atomic temp files
        behind (in-process cleanup never ran), and a crash between the
        two commit phases leaves an UNCOMMITTED (manifest-less) shard
        directory; rotation ignores non-generation names, so sweep
        both here or they accumulate forever across preemption
        cycles. (No writer can be live at construction time.)"""
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.startswith(f"{self.prefix}-") and ".tmp-" in name:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            elif _SHARD_RE.match(name) and os.path.isdir(path):
                # Temp litter INSIDE a shard dir is always sweepable;
                # the dir itself only when it was never committed.
                for inner in os.listdir(path):
                    if ".tmp-" in inner:
                        try:
                            os.unlink(os.path.join(path, inner))
                        except OSError:
                            pass
                if not os.path.exists(
                    os.path.join(path, MANIFEST_NAME)
                ):
                    shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------------ #
    def path_for(self, iteration: int) -> str:
        return os.path.join(
            self.directory, f"{self.prefix}-{int(iteration):08d}.npz"
        )

    def shard_dir_for(self, iteration: int) -> str:
        return os.path.join(
            self.directory,
            f"{self.prefix}-{int(iteration):08d}{SHARD_SUFFIX}",
        )

    def valid_path_for(self, iteration: int) -> str | None:
        """An existing generation of this iteration that passes its
        integrity check, else None. The runner consults this before
        re-flushing a rollback target: rewriting a committed sharded
        generation in place would UN-COMMIT it first (manifest removed
        before the shards are rewritten), opening a crash window on
        the very generation the flush exists to preserve — and within
        one supervised run the iteration uniquely keys the trajectory,
        so a valid existing generation already holds the state."""
        for path in (
            self.shard_dir_for(iteration), self.path_for(iteration)
        ):
            if os.path.exists(path):
                try:
                    verify_checkpoint(path)
                    return path
                except Exception:
                    continue
        return None

    def entries(self) -> list[tuple[int, str]]:
        """(iteration, path) pairs sorted oldest→newest; sharded
        directory generations and single-file generations interleave
        by iteration (backward compatibility: a run can switch layouts
        mid-history, e.g. across an elastic reshard)."""
        out = []
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            m = _NAME_RE.match(name)
            if m and m.group("prefix") == self.prefix:
                out.append((int(m.group("it")), path))
                continue
            m = _SHARD_RE.match(name)
            if (
                m
                and m.group("prefix") == self.prefix
                and os.path.isdir(path)
            ):
                out.append((int(m.group("it")), path))
        return sorted(out)

    # ------------------------------------------------------------------ #
    def _shards_for(self, tally) -> int:
        if self.shards in (None, 0):
            return 0
        if self.shards == "auto":
            return int(getattr(tally, "n_parts", 0) or 0)
        return int(self.shards)

    def save(self, tally) -> str:
        """Write the tally's checkpoint as the next generation and
        rotate old generations out. Partitioned tallies (under the
        default ``shards="auto"``) get the sharded two-phase layout —
        one npz per mesh part, manifest committed last."""
        n = self._shards_for(tally)
        if n:
            path = self.shard_dir_for(tally.iter_count)
            tally.save_checkpoint(path, n_shards=n)
            self.last_shards = n
        else:
            path = self.path_for(tally.iter_count)
            tally.save_checkpoint(path)
            self.last_shards = 0
        self._rotate()
        return path

    def _rotate(self) -> None:
        removed = False
        for _, path in self.entries()[: -self.keep]:
            try:
                if os.path.isdir(path):
                    shutil.rmtree(path)
                else:
                    os.unlink(path)
                removed = True
            except OSError as e:
                log_warn(
                    f"checkpoint rotation could not remove {path}: {e}"
                )
        if removed:
            # Make the unlinks durable: without the directory fsync a
            # power cut can resurrect a rotated-out generation while
            # losing the newest rename — find_latest would then resume
            # an OLDER state than the rotation promised survives
            # (utils/checkpoint.fsync_dir).
            fsync_dir(self.directory)

    # ------------------------------------------------------------------ #
    def find_latest(self) -> tuple[int, str] | None:
        """Newest generation that passes the integrity check; corrupt
        generations are skipped with a warning (the fallback contract
        — for sharded generations a missing manifest or any bad shard
        digest rejects the whole generation atomically). The same
        mismatch-vs-corruption rule as ``restore_latest``: an INTACT
        file of another format/shape raises instead of being skipped,
        so the two lookups always agree on a directory."""
        for it, path in reversed(self.entries()):
            try:
                verify_checkpoint(path)
                return it, path
            except CheckpointIntegrityError as e:
                log_warn(f"skipping corrupt checkpoint {path}: {e}")
            except ValueError:
                raise
            except Exception as e:
                log_warn(f"skipping unreadable checkpoint {path}: {e}")
        return None

    def restore_latest(self, tally) -> int | None:
        """Restore the newest VALID generation into ``tally``; returns
        its iteration, or None when no restorable generation exists.
        Corruption (bad container, failed digest, torn shard set)
        falls back to the previous generation; a clean-but-mismatched
        checkpoint raises — see the module docstring for why the two
        differ."""
        for it, path in reversed(self.entries()):
            try:
                tally.restore_checkpoint(path)
                log_info(
                    f"resumed from checkpoint {path}", iteration=it
                )
                return it
            except CheckpointIntegrityError as e:
                log_warn(f"skipping corrupt checkpoint {path}: {e}")
            except ValueError:
                # Intact but incompatible (mesh/dtype/shape): caller bug.
                raise
            except Exception as e:
                # Unreadable container (truncated zip, zlib error, OS
                # error): corruption by another name — fall back.
                log_warn(f"skipping unreadable checkpoint {path}: {e}")
        return None
