"""Elastic mesh-shrink recovery: continue the run on the survivors.

The reference stack (PUMI-Tally / Omega_h over MPI) loses the whole job
when one rank dies. Here a lost chip costs one rollback: the
partitioned checkpoint payload is LAYOUT-INDEPENDENT (the flux is
stored assembled in global element order and the particle state in pid
order — PR 2 pinned resume across part counts), and
``parallel/mesh_partition.partition_mesh`` accepts any part count, so
the coordinated-rollback state restores cleanly onto a FRESH
``PartitionedTally`` built over the surviving device set. The
rebuilt facade recompiles its step for the new layout (with fresh
watchdog compile amnesty — the first dispatch per kind is always
un-deadlined) and the run continues: physics-equal to an uninterrupted
run at the shrunk part count (the layout-independence oracle;
same-layout rollback stays bitwise).

This module is pure construction glue — the verdicts come from
``resilience/coordinator.py``, the orchestration (when to shrink, what
generation to roll to) lives in ``ResilientRunner``.
"""
from __future__ import annotations


def surviving_devices(tally, health: dict[int, bool]) -> list:
    """The subset of the tally's mesh devices a probe found alive,
    mesh order preserved."""
    devs = list(tally.device_mesh.devices.flat)
    return [d for i, d in enumerate(devs) if health.get(i, True)]


def rebuild_on_devices(tally, devices: list):
    """Construct a fresh ``PartitionedTally`` over ``devices`` with the
    source tally's mesh, config, halo depth, per-chip capacity and
    migration bounds — re-partitioning the SAME global mesh onto the
    new part count. Telemetry (registry + flight recorder) transplants
    from the source so counters, the scrape endpoint's registry and
    the supervisor's metrics keep one continuous history across the
    shrink. The caller restores state into the result
    (``utils.checkpoint.restore_state`` handles the cross-layout
    re-slab; megastep slot state re-distributes on the next dispatch).
    """
    if not devices:
        raise ValueError(
            "elastic recovery needs at least one surviving device"
        )
    from ..parallel.particle_sharding import mesh_from_devices
    from ..parallel.partitioned_api import PartitionedTally

    return PartitionedTally(
        tally.mesh,
        tally.num_particles,
        tally.config,
        device_mesh=mesh_from_devices(devices),
        halo_layers=tally.partition.halo_layers,
        cap=tally.cap,
        exchange_size=tally._step_kwargs["exchange_size"],
        max_rounds=tally._step_kwargs["max_rounds"],
        telemetry=tally._telemetry,
    )
