"""ResilientRunner: the auto-checkpointing run supervisor.

Wraps a ``PumiTally`` or ``PartitionedTally`` behind the same
``initialize_particle_location`` / ``move_to_next_location`` surface and
adds the fault-tolerance loop production preemptible fleets need
(ROADMAP north star; the reference library has none, SURVEY.md §5):

  * **auto-checkpoint** every ``every_moves`` moves or
    ``every_seconds`` seconds into a rotating ``CheckpointStore``
    (atomic writes, per-array sha256, keep-N);
  * **auto-resume**: construction restores the newest VALID generation
    (corrupt ones are skipped) and the driver replays from
    ``tally.iter_count`` — a replayed run is bitwise identical to an
    uninterrupted one because checkpoint round-trips are exact;
  * **preemption flush**: SIGTERM/SIGINT trigger one final checkpoint
    before the process dies, so at most the in-flight move is lost;
  * **transient retry**: a retryable error from a move (injected
    transients, JAX runtime errors) rolls the tally back to the last
    good in-memory snapshot and retries with exponential backoff,
    bounded by ``max_retries``;
  * **coordinated rollback + elastic mesh-shrink** (the failure
    taxonomy lives in ``resilience/coordinator.py``): every failure is
    CLASSIFIED — ``transient`` replays bitwise on the same layout;
    ``chip-lost`` (a health probe finds a dead chip) rolls EVERY part
    back to the same last-good generation, re-partitions the mesh onto
    the surviving devices (``resilience/elastic.py``), re-arms the
    compiled step with fresh watchdog compile amnesty, and continues —
    physics-equal to an uninterrupted run at the shrunk part count;
    ``preempted`` flushes the last-GOOD generation (never in-flight
    state) and propagates;
  * **sharded generations**: partitioned tallies checkpoint as one npz
    per mesh part plus a manifest committed last (two-phase commit,
    ``CheckpointStore(shards="auto")``) — a torn multi-shard write can
    never resume as a Frankenstein mix of vintages;
  * **fault injection**: every hook of ``faultinject.py`` threads
    through here, so the tests can prove each failure mode recovers.

Driver shape (the resume-aware loop)::

    t = PumiTally(mesh, n, TallyConfig(quarantine=True))
    with ResilientRunner(t, "ckpts/", every_moves=25) as run:
        run.initialize_particle_location(pos)   # no-op after a resume
        for i in range(1, n_moves + 1):
            if t.iter_count >= i:
                continue                         # already replayed
            run.move_to_next_location(*inputs(i))
"""
from __future__ import annotations

import time

import numpy as np

from ..integrity.policy import (
    FatalIntegrityViolation,
    TransientIntegrityViolation,
)
from ..integrity.watchdog import DispatchTimeoutError
from ..utils.checkpoint import restore_state, snapshot_state
from ..utils.log import log_info, log_warn
from ..utils.signals import (
    install_preemption_handlers,
    resume_previous_handler,
    uninstall_preemption_handlers,
)
from .coordinator import ResilienceCoordinator
from .faultinject import (
    ChipLostError,
    FaultInjector,
    InjectedPreemption,
    InjectedTransientFault,
)
from .store import CheckpointStore

try:  # pragma: no cover - depends on installed jax
    from jax.errors import JaxRuntimeError as _JaxRuntimeError
except ImportError:  # pragma: no cover
    class _JaxRuntimeError(Exception):
        """Placeholder when jax.errors lacks JaxRuntimeError."""


#: Error types a move retry can plausibly fix: injected transients, JAX
#: runtime errors (preempted device, RESOURCE_EXHAUSTED, collective
#: timeouts), watchdog dispatch timeouts (integrity/watchdog.py — a
#: hung dispatch re-arms and replays instead of wedging), and
#: integrity="retry" violations (a one-shot SDC does not recur on
#: replay; a deterministic kernel bug exhausts the bounded retries and
#: propagates). Anything else — including InjectedKill and
#: integrity="halt" violations — propagates. ``ChipLostError`` is NOT
#: here: an in-place replay would re-dispatch onto the dead chip; the
#: coordinator routes it to the elastic mesh-shrink path instead (and
#: the members listed here can still be UPGRADED to chip-lost when the
#: health probe finds a dead chip behind them).
RETRYABLE = (
    InjectedTransientFault,
    DispatchTimeoutError,
    TransientIntegrityViolation,
    _JaxRuntimeError,
)


class ResilientRunner:
    def __init__(
        self,
        tally,
        store: CheckpointStore | str,
        *,
        every_moves: int | None = 25,
        every_seconds: float | None = None,
        keep: int = 3,
        max_retries: int = 3,
        backoff_base: float = 0.25,
        backoff_max: float = 8.0,
        resume: bool = True,
        handle_signals: bool = True,
        retry_snapshots: bool = True,
        elastic: bool = True,
        faults: FaultInjector | None = None,
        sleep=time.sleep,
    ):
        self.tally = tally
        self.store = (
            store if isinstance(store, CheckpointStore)
            else CheckpointStore(store, keep=keep)
        )
        self.every_moves = every_moves
        self.every_seconds = every_seconds
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        # The retry anchor costs one full host readback of the flux (+
        # global assembly on the partitioned facade) per move. That is
        # the price of exact transient-rollback; production runs that
        # would rather lose the window since the last on-disk
        # generation can turn it off — transient errors then propagate
        # like any other (the next process auto-resumes).
        self.retry_snapshots = bool(retry_snapshots)
        # Elastic mesh-shrink recovery for partitioned tallies: a
        # chip-lost verdict re-partitions onto the survivors instead
        # of propagating. Off → chip loss flushes last-good and raises
        # (declared graceful degradation).
        self.elastic = bool(elastic)
        self.faults = faults if faults is not None else FaultInjector()
        self._sleep = sleep
        self._prev_handlers: dict = {}
        self._in_move = False
        self._pending_signal: int | None = None
        # True while a dispatch may have half-mutated tally state (set
        # around every supervised body() call): the preemption flush
        # consults it so a signal surfacing on an ERROR path writes the
        # LAST-GOOD generation, never the in-flight rolled-back state.
        self._dirty = False
        #: MTTR accounting for bench.py's fault-mode axes: rollbacks /
        #: reshards performed, moves lost to rollback rewinds, and
        #: wall-clock seconds spent inside recovery (classify + probe +
        #: rollback + re-partition + backoff).
        self.recovery_stats = {
            "rollbacks": 0,
            "reshards": 0,
            "lost_moves": 0,
            "recovery_seconds": 0.0,
        }
        # Failure taxonomy + per-chip health probe; registers the
        # pumi_rollbacks_total / pumi_elastic_reshards_total /
        # pumi_chip_health families on the tally's registry.
        self.coordinator = ResilienceCoordinator(
            tally, faults=self.faults
        )
        r = tally.metrics
        self._c_ckpt = r.counter(
            "pumi_checkpoints_total",
            "checkpoint generations written by the supervisor",
        )
        self._c_retry = r.counter(
            "pumi_move_retries_total",
            "transient move failures retried by the supervisor",
        )
        self._c_resume = r.counter(
            "pumi_resumes_total",
            "startup auto-resumes from a checkpoint generation",
        )
        self._c_fault = r.counter(
            "pumi_injected_faults_total",
            "faults injected through PUMI_TPU_FAULTS (labeled by kind)",
        )
        self._c_shards = r.counter(
            "pumi_checkpoint_shards_written_total",
            "shard files written by sharded (two-phase manifest) "
            "checkpoint generations",
        )

        # Live scrape endpoint (obs/exporter.py): the facades start one
        # when PUMI_TPU_PROM_PORT is set; pick up the duty for wrapped
        # tallies that did not (e.g. constructed before the env was
        # set), so a supervised soak is always scrapable. Owned (and
        # stopped on close) only when started HERE.
        self._exporter = None
        if getattr(tally, "_exporter", None) is None:
            from ..obs import maybe_start_exporter

            self._exporter = maybe_start_exporter(r)

        self.resumed_from: int | None = None
        if resume:
            it = self.store.restore_latest(tally)
            if it is not None:
                self.resumed_from = it
                self._c_resume.inc()
        # Last good state: the transient-retry anchor. Taken whenever
        # the tally holds a consistent post-move (or restored) state.
        self._good = (
            snapshot_state(tally) if self._want_snapshot() else None
        )
        self._last_ckpt_iter = tally.iter_count
        self._last_ckpt_time = time.monotonic()
        if handle_signals:
            self._install_signal_handlers()

    # ------------------------------------------------------------------ #
    # Facade surface
    # ------------------------------------------------------------------ #
    def initialize_particle_location(self, positions, size=None) -> None:
        """Delegates the initial parent-element search; after a resume
        this is a NO-OP (the restored state already holds located
        particles — re-searching would clobber it), so drivers can call
        it unconditionally."""
        if self.resumed_from is not None and self.tally._initialized:
            log_info(
                "initialize_particle_location skipped: resumed from "
                f"iteration {self.resumed_from}"
            )
            return
        self.tally.initialize_particle_location(positions, size)
        if self._want_snapshot():
            self._good = snapshot_state(self.tally)
        # Generation 0: guarantees auto-resume has a base to fall back
        # to even if the run dies before the first cadence checkpoint.
        self.checkpoint()

    def move_to_next_location(
        self, particle_destinations, flying, weights, groups,
        material_ids, size=None,
    ) -> None:
        move = self.tally.iter_count + 1
        self.faults.maybe_die(move)
        n_nan = self.faults.corrupt_destinations(
            particle_destinations, move
        )
        if n_nan:
            self._c_fault.inc(n_nan, kind="nan_src")
        self._in_move = True
        try:
            self._move_with_retry(
                move, particle_destinations, flying, weights, groups,
                material_ids, size,
            )
            if self._want_snapshot():
                self._good = snapshot_state(self.tally)
            self._maybe_checkpoint()
        finally:
            self._in_move = False
            if self._pending_signal is not None:
                # A preemption signal landed mid-move: flush and die at
                # the move boundary — whether the move completed (a
                # consistent post-move state) or raised (the last good
                # generation still stands). Swallowing the signal on
                # the error path would leave a process that ignores
                # SIGTERM forever.
                sig, self._pending_signal = self._pending_signal, None
                self._on_signal(sig, None)

    def run_source_moves(self, n_moves, source=None, **kwargs) -> dict:
        """Supervised device-sourced move loop: the tally's
        ``run_source_moves`` under the same transient-retry /
        last-good-rollback / cadence-checkpoint contract as
        ``move_to_next_location``, at MEGASTEP granularity — the call
        is chunked into megastep-K dispatches with the snapshot +
        cadence-checkpoint step BETWEEN dispatches, so a long call
        (n_moves ≫ K) still bounds the retry-replay window and the
        preemption loss window to one megastep. A transient failure
        rolls the in-flight megastep back to the last good snapshot
        and replays it (bitwise identical: the RNG stream is keyed by
        the persisted move counter). There are no out-params to re-arm
        — the megastep's inputs are device-resident state the rollback
        rebuilds. ``weights``/``groups``/``alive`` re-stage on the
        FIRST chunk only; later chunks continue from device state,
        exactly like the facade's own internal chunking."""
        # The same tuned K the facade will use (the tally consulted the
        # tuning database at construction) — keeps the supervisor's
        # checkpoint-between-dispatches chunking aligned with the
        # facade's own fused-dispatch size.
        k = self.tally.config.resolve_megastep(
            tuned=getattr(self.tally, "_tuned", None)
        )
        totals = {
            "moves": 0, "segments": 0, "collisions": 0, "escaped": 0,
            "rouletted": 0, "absorbed_weight": 0.0, "alive": 0,
            "truncated": 0,
        }
        done = 0
        first = True
        self._in_move = True
        try:
            while done < int(n_moves):
                chunk = min(k, int(n_moves) - done)
                move = self.tally.iter_count + 1
                self.faults.maybe_die(move)
                out = self._source_chunk_with_retry(
                    move, chunk, source, kwargs if first else {}
                )
                first = False
                done += chunk
                for f in ("moves", "segments", "collisions", "escaped",
                          "rouletted", "truncated"):
                    totals[f] += out[f]
                totals["absorbed_weight"] += out["absorbed_weight"]
                totals["alive"] = out["alive"]
                if self._want_snapshot():
                    self._good = snapshot_state(self.tally)
                self._maybe_checkpoint()
                if out["alive"] == 0 or self._pending_signal is not None:
                    break
            return totals
        finally:
            self._in_move = False
            if self._pending_signal is not None:
                sig, self._pending_signal = self._pending_signal, None
                self._on_signal(sig, None)

    def _retry_loop(self, what: str, body, rearm=None):
        """Shared escalation skeleton for one supervised dispatch. A
        fatal integrity halt and a preemption notice flush the last
        GOOD generation before propagating; every other failure is
        CLASSIFIED by the coordinator: ``transient`` rolls back to the
        last good snapshot and replays with bounded exponential
        backoff (single-state rearm), ``chip-lost`` rolls EVERY part
        back to the same generation and re-partitions onto the
        surviving devices (fleet rearm, ``_recover_chip_loss``).
        ``rearm`` re-seeds caller-owned inputs the dispatch may have
        mutated before failing. The per-move and megastep paths share
        this so the two resilience contracts cannot drift apart."""
        attempt = 0
        while True:
            self._dirty = True
            try:
                out = body()
                self._dirty = False
                return out
            except FatalIntegrityViolation:
                # integrity="halt": flush the last GOOD generation —
                # never the suspect post-violation state — so the
                # campaign can be resumed from verified data, then let
                # the halt propagate.
                self._flush_last_good("integrity", what)
                raise
            except InjectedPreemption:
                # A preemption notice mid-move: same flush discipline
                # as a real SIGTERM on an error path — the generation
                # on disk must be the last GOOD state, never the
                # in-flight one.
                self._flush_last_good("preempted", what)
                raise
            except (ChipLostError,) + RETRYABLE as e:
                attempt += 1
                if isinstance(e, InjectedTransientFault):
                    self._c_fault.inc(kind="transient")
                if isinstance(e, ChipLostError):
                    self._c_fault.inc(kind="chip_down")
                    # Pin the dead DEVICE while the mesh it indexed is
                    # still current (a reshard re-indexes the fleet).
                    self.coordinator.note_down(e.chip)
                verdict = self.coordinator.classify(e)
                if verdict == "chip-lost" and not self._can_reshard():
                    # Nothing to shrink onto (single-chip facade, a
                    # 1-part mesh, elastic off, or no anchor):
                    # declared graceful degradation — flush the last
                    # good generation and propagate.
                    self._flush_last_good("chip-lost", what)
                    raise
                if attempt > self.max_retries or self._good is None:
                    # No anchor to roll back to (retry_snapshots off,
                    # or nothing completed yet): an in-place retry
                    # could silently run on a donated/half-updated
                    # accumulator — propagate instead; the next
                    # process's auto-resume is the recovery path.
                    raise
                self._c_retry.inc()
                t0 = time.monotonic()
                iter_before = self.tally.iter_count
                if verdict == "chip-lost":
                    self._recover_chip_loss(e, what)
                    if rearm is not None:
                        rearm()
                else:
                    delay = min(
                        self.backoff_base * 2 ** (attempt - 1),
                        self.backoff_max,
                    )
                    log_warn(
                        f"{what} failed transiently ({e}); restoring "
                        f"last good state and retrying in {delay:.2f}s "
                        f"(attempt {attempt}/{self.max_retries})"
                    )
                    restore_state(self.tally, self._good)
                    self._dirty = False
                    self.coordinator.note_rollback("transient")
                    self.recovery_stats["rollbacks"] += 1
                    if rearm is not None:
                        rearm()
                    self._sleep(delay)
                self.recovery_stats["lost_moves"] += max(
                    0, iter_before - self.tally.iter_count
                )
                self.recovery_stats["recovery_seconds"] += (
                    time.monotonic() - t0
                )

    def _flush_last_good(self, cause: str, what: str) -> None:
        """Roll back to the last good snapshot (when the in-flight
        state may be inconsistent) and flush one generation, so the
        failure about to propagate leaves verified data on disk."""
        if self._good is None:
            return
        restore_state(self.tally, self._good)
        self._dirty = False
        self.coordinator.note_rollback(cause)
        self.recovery_stats["rollbacks"] += 1
        try:
            path = self.checkpoint()
            log_warn(
                f"{cause} in {what}: flushed last-good checkpoint "
                f"{path} before raising"
            )
        except Exception as e:  # pragma: no cover - flush best-effort
            log_warn(f"{cause} flush failed: {e}")

    def _can_reshard(self) -> bool:
        return (
            self.elastic
            and self._good is not None
            and hasattr(self.tally, "flux_slabs")
            and getattr(self.tally, "n_parts", 1) > 1
        )

    def _recover_chip_loss(self, exc, what: str) -> None:
        """Fleet rearm: probe the mesh, roll EVERY part back to the
        same last-good generation, and — when chips are actually gone
        — rebuild the partitioned facade on the survivors
        (resilience/elastic.py) with the layout-independent state
        re-slabbed onto the new partition. The rebuilt facade
        recompiles its step for the new layout with fresh watchdog
        compile amnesty; a fresh generation is flushed immediately so
        the next resume sees the shrunken fleet's layout."""
        from .elastic import rebuild_on_devices, surviving_devices

        old = self.tally
        # Reuse the probe classify() just ran for this failure (an
        # injected ChipLostError needed none — probe once here).
        health = self.coordinator.consume_last_probe()
        if health is None:
            health = self.coordinator.probe_chips()
        survivors = surviving_devices(old, health)
        if not survivors:
            # Fleet-wide loss: same declared degradation as the
            # unshrinkable cases — leave verified last-good data on
            # disk before propagating. Best-effort: with every chip
            # gone even the rollback's device staging can fail, and
            # that must not mask the original loss.
            try:
                self._flush_last_good("chip-lost", what)
            except Exception as e:  # pragma: no cover - best-effort
                log_warn(f"fleet-loss flush failed: {e}")
            raise exc
        if len(survivors) == old.n_parts:
            # The probe found the fleet whole (a mis-attributed
            # timeout): same-layout coordinated rollback — the replay
            # is bitwise.
            restore_state(old, self._good)
            self._dirty = False
            self.coordinator.note_rollback("chip-lost")
            self.recovery_stats["rollbacks"] += 1
            return
        log_warn(
            f"chip loss in {what} ({exc}); rolling every part back to "
            f"the last good generation and re-partitioning "
            f"{old.n_parts} -> {len(survivors)} parts"
        )
        old.close()
        new = rebuild_on_devices(old, survivors)
        restore_state(new, self._good)
        self.tally = new
        self._dirty = False
        self.coordinator.rebind(new)
        self.coordinator.note_rollback("chip-lost")
        self.coordinator.c_reshards.inc()
        self.recovery_stats["rollbacks"] += 1
        self.recovery_stats["reshards"] += 1
        self._good = snapshot_state(new)
        # Flush now so a crash right after the shrink still resumes
        # (a no-op when this iteration's generation already exists —
        # its layout-independent payload restores onto any fleet).
        self.checkpoint()

    def _source_chunk_with_retry(
        self, move, chunk, source, kwargs
    ) -> dict:
        def body():
            self.faults.maybe_transient(move)
            self.faults.maybe_chip_down(move)
            self.faults.maybe_preempt(move)
            return self.tally.run_source_moves(chunk, source, **kwargs)

        # No out-params to re-arm: the megastep's inputs are
        # device-resident state the rollback rebuilds.
        return self._retry_loop(f"megastep at move {move}", body)

    def _move_with_retry(
        self, move, particle_destinations, flying, weights, groups,
        material_ids, size,
    ) -> None:
        # The facade mutates the caller's out-params (copy-back writes
        # dest/material_ids, zeroes flying) BEFORE its last device
        # fetches can fail — a retry must re-see the ORIGINAL inputs or
        # it would walk zero particles and silently drop the move.
        saved = (
            tuple(
                np.array(a, copy=True)
                for a in (particle_destinations, flying, material_ids)
            )
            if self._good is not None
            else None
        )

        def body():
            self.faults.maybe_transient(move)
            self.faults.maybe_chip_down(move)
            self.faults.maybe_preempt(move)
            self.tally.move_to_next_location(
                particle_destinations, flying, weights, groups,
                material_ids, size,
            )

        def rearm():
            for dst, src in zip(
                (particle_destinations, flying, material_ids),
                saved, strict=True,
            ):
                np.copyto(np.asarray(dst), src)

        self._retry_loop(f"move {move}", body, rearm)

    def _want_snapshot(self) -> bool:
        return (
            self.retry_snapshots
            and self.max_retries > 0
            and self.tally._initialized
        )

    # ------------------------------------------------------------------ #
    # Checkpointing
    # ------------------------------------------------------------------ #
    def checkpoint(self) -> str:
        """Write one generation now (cadence-independent). Partitioned
        tallies write the sharded two-phase layout (store default);
        the shard count feeds pumi_checkpoint_shards_written_total.
        Re-flushing an iteration that already has a VALID generation
        (a rollback flush landing on a cadence write's iteration) is
        a no-op: the runner is its store's single writer and the
        iteration keys the trajectory, so the bytes are already safe
        — and rewriting a sharded generation in place would un-commit
        it first, risking the one copy a crash must preserve."""
        existing = self.store.valid_path_for(self.tally.iter_count)
        if existing is not None:
            self._last_ckpt_iter = self.tally.iter_count
            self._last_ckpt_time = time.monotonic()
            return existing
        path = self.store.save(self.tally)
        if self.faults.corrupt_file(path):
            self._c_fault.inc(kind="corrupt_ckpt")
        if self.faults.maybe_tear(path):
            self._c_fault.inc(kind="torn_shard")
        if self.store.last_shards:
            self._c_shards.inc(self.store.last_shards)
        self._c_ckpt.inc()
        self._last_ckpt_iter = self.tally.iter_count
        self._last_ckpt_time = time.monotonic()
        return path

    def _maybe_checkpoint(self) -> None:
        due = (
            self.every_moves is not None
            and self.tally.iter_count - self._last_ckpt_iter
            >= self.every_moves
        ) or (
            self.every_seconds is not None
            and time.monotonic() - self._last_ckpt_time
            >= self.every_seconds
        )
        if due:
            self.checkpoint()

    # ------------------------------------------------------------------ #
    # Preemption handling
    # ------------------------------------------------------------------ #
    def _install_signal_handlers(self) -> None:
        self._prev_handlers = install_preemption_handlers(
            self._on_signal, "ResilientRunner"
        )

    def _uninstall_signal_handlers(self) -> None:
        uninstall_preemption_handlers(
            self._prev_handlers, mine=self._on_signal
        )
        self._prev_handlers = {}

    def _on_signal(self, signum, frame) -> None:
        """Preemption flush: one final checkpoint, then die the way the
        process would have died without us. Mid-move delivery defers to
        the move boundary so the flushed generation is consistent; if
        that boundary was reached by an ERROR path (retries exhausted
        mid-flight — the dirty flag is still up), the tally is first
        rolled back to the last good snapshot so the flush writes the
        last-GOOD generation, never the in-flight state."""
        if self._in_move:
            self._pending_signal = signum
            return
        if self._dirty and self._good is not None:
            try:
                restore_state(self.tally, self._good)
                self._dirty = False
                self.coordinator.note_rollback("preempted")
                self.recovery_stats["rollbacks"] += 1
            except Exception as e:  # pragma: no cover - best-effort
                log_warn(f"preemption rollback failed: {e}")
        try:
            path = self.checkpoint()
            log_info(
                f"preemption flush: checkpoint {path} written on "
                f"signal {signum}"
            )
        except Exception as e:  # pragma: no cover - flush best-effort
            log_warn(f"preemption flush failed: {e}")
        prev = self._prev_handlers.get(signum)
        self._uninstall_signal_handlers()
        resume_previous_handler(prev, signum, frame)

    # ------------------------------------------------------------------ #
    def close(self, final_checkpoint: bool = True) -> None:
        """Flush a final generation (when anything advanced since the
        last one) and release the signal handlers."""
        if final_checkpoint and self.tally._initialized and (
            self.tally.iter_count != self._last_ckpt_iter
            or not self.store.entries()
        ):
            self.checkpoint()
        self._uninstall_signal_handlers()
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # On an exception the tally state may be mid-move; the cadence
        # checkpoints are the trustworthy generations — flush only on
        # clean exit.
        self.close(final_checkpoint=exc_type is None)
        return False

    # ------------------------------------------------------------------ #
    def __getattr__(self, name):
        """Everything else (telemetry, write_pumi_tally_mesh, raw_flux,
        ...) passes through to the wrapped tally."""
        if name == "tally":  # guard pre-__init__ access recursion
            raise AttributeError(name)
        return getattr(self.tally, name)
