"""Resilience subsystem: survive preemption, bad particles, and bit-rot.

Production-scale Monte Carlo campaigns on preemptible TPU fleets need
what the reference library lacks entirely (SURVEY.md §5 "Checkpoint /
resume. Absent."; PUMI-Tally arXiv:2504.19048 treats checkpoint/restart
as first-class):

  * ``CheckpointStore`` — rotating generations of durable checkpoints
    (atomic tmp+fsync+rename writes, per-array sha256 verified on
    load, keep-N rotation, corrupt-generation fallback);
  * ``ResilientRunner`` — the run supervisor: auto-checkpoint every K
    moves / T seconds, SIGTERM/SIGINT preemption flush, startup
    auto-resume, bounded exponential-backoff retry of transient move
    failures;
  * ``quarantine`` — bad-particle masking (``TallyConfig(quarantine=
    True)``): non-finite / out-of-mesh inputs are parked and counted
    instead of raising or poisoning the additive flux;
  * ``faultinject`` — the ``PUMI_TPU_FAULTS`` harness that proves each
    failure mode recovers (NaN sources, kill-at-move, transient device
    errors, checkpoint corruption, chip loss, mid-move preemption,
    torn shard generations) plus the seeded ``ChaosPlan``/
    ``ChaosInjector`` multi-fault scheduler driving the chaos
    campaigns (scripts/chaos.py, scripts/soak_walk.py --chaos);
  * ``coordinator`` — ``ResilienceCoordinator``: the failure taxonomy
    ({transient, chip-lost, preempted, persistent}) and the per-chip
    health probe behind the ``pumi_chip_health`` gauge — shared by the
    run supervisor and the serving scheduler's per-job isolation
    (serving/scheduler.py);
  * ``elastic`` — mesh-shrink recovery: rebuild the partitioned facade
    on the surviving device set from the layout-independent
    checkpoint state and continue the run.

Truncated-walk escalation (re-walk only the truncated lanes with a
doubled crossing budget before declaring them lost) lives with the
kernels — ``ops/walk.py rewalk_truncated`` — and is switched by
``TallyConfig(truncation_retries=N)``.
"""
from .coordinator import VERDICTS, ResilienceCoordinator
from .faultinject import (
    ChaosInjector,
    ChaosPlan,
    ChipLostError,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    InjectedKill,
    InjectedPoisonFault,
    InjectedPreemption,
    InjectedTransientFault,
    chaos_plan,
    parse_faults,
    plan_from_env,
)
from .quarantine import (
    REASONS as QUARANTINE_REASONS,
    QuarantineReport,
    inflated_bounds,
)
from .runner import RETRYABLE, ResilientRunner
from .store import CheckpointStore

__all__ = [
    "CheckpointStore",
    "ResilientRunner",
    "ResilienceCoordinator",
    "RETRYABLE",
    "VERDICTS",
    "ChaosInjector",
    "ChaosPlan",
    "chaos_plan",
    "ChipLostError",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "InjectedKill",
    "InjectedPoisonFault",
    "InjectedPreemption",
    "InjectedTransientFault",
    "parse_faults",
    "plan_from_env",
    "QuarantineReport",
    "QUARANTINE_REASONS",
    "inflated_bounds",
]
