"""Shape-bucketed multi-tenant scheduler (tally-as-a-service).

A production server multiplexes many concurrent tally jobs over one
device.  This scheduler makes that a first-class loop:

  * Requests are PADDED onto the tuning shape ladder
    (``tuning/shapes.py`` — the same power-of-two ``bucket`` the
    autotuner and the AOT bank key on) and bucketed by shape class, so
    every job of a class dispatches the SAME compiled programs: one
    bank entry pair (packed init search + megastep) serves every job
    in the bucket, however many distinct request sizes arrive.
  * Up to ``max_resident`` jobs are RESIDENT at once (live device
    state: particle lanes + flux accumulator).  Admission is
    round-robin ACROSS shape classes, so one hot bucket cannot starve
    the others.
  * The device is time-sliced at MEGASTEP-K granularity: each
    scheduling round gives every resident job exactly one quantum (one
    ``run_source_moves`` call of up to ``quantum_moves`` fused moves —
    one H2D + one D2H per quantum, PR 6's contract), which is both the
    fairness grain and the natural preemption boundary.
  * Jobs finish by exhaustion (all requested moves), by DRAINING
    (every particle terminated), or by CONVERGENCE — with
    ``TallyConfig(convergence=True)`` the PR 5 ``converged()``
    statistic evicts a job early the moment its requested precision is
    reached, freeing the slot for queued work.
  * PREEMPTION reuses the PR 2 checkpoint subsystem: when queued jobs
    wait and a resident job has held its slot for ``preempt_after``
    quanta, the job is checkpointed to disk, its device state dropped,
    and it re-queues; on re-admission it restores and continues
    BITWISE-identically (megastep RNG is keyed by the persistent move
    counter, so replay equals the uninterrupted run —
    tests/test_serving.py pins it).

Serving under failure (the fault-isolation layer)
-------------------------------------------------
A multi-tenant server must contain one job's failure to that job:

  * Every quantum dispatch is CLASSIFIED through the PR 11 failure
    taxonomy (``resilience/coordinator.py``).  A ``transient`` verdict
    (injected transients, retryable JAX runtime errors, a watchdog
    timeout with the chip still answering its probe) replays the
    quantum BITWISE from the job's own pre-quantum snapshot — the same
    ``snapshot_state`` payload the checkpoint subsystem persists —
    with bounded exponential backoff, counted in
    ``pumi_job_retries_total{cause}``.  A ``persistent`` verdict (a
    fatal integrity violation, an injected poison job) or an exhausted
    retry budget POISONS the job: finished ``outcome="poisoned"``,
    device slot freed, and every other resident and queued job
    continues bitwise-identical to a fault-free run (jobs are
    facade-isolated; scheduling order never enters their RNG streams).
  * ADMISSION CONTROL: ``max_queued`` bounds the wait queue — an
    over-limit submission is finished ``outcome="rejected"`` (named
    backpressure) instead of growing the queue without bound.
  * A per-quantum DEADLINE (``quantum_deadline_s``) arms the PR 4
    dispatch watchdog inside every job facade, so one wedged dispatch
    surfaces as a classified ``DispatchTimeoutError`` instead of
    stalling the round-robin loop forever (first dispatch per program
    kind keeps the compile amnesty).
  * The CRASH-SAFE JOURNAL (``journal_dir``, serving/journal.py): the
    whole job table rides a ``JOBS.json`` write-ahead log — request
    params, shape key, moves_done, checkpoint, outcome — flushed
    atomically after every state transition, with each resident job
    checkpointed at its quantum boundary BEFORE the flush that
    references it, a SIGTERM/SIGINT flush, and a
    ``TallyScheduler.recover(journal_dir)`` startup path that
    re-queues interrupted jobs from their checkpoints and resumes
    bitwise (over a warm program bank the restarted process compiles
    nothing).  Finished fluxes persist beside the journal, so a
    restart loses zero jobs — not even completed ones.

Observability rides the PR 1/PR 5 machinery: ``pumi_jobs_total
{outcome}``, ``pumi_queue_depth``, ``pumi_preemptions_total``,
``pumi_job_retries_total{cause}``, the ``pumi_job_queue_seconds``
wait histogram, the bank's ``pumi_aot_hits_total`` /
``pumi_aot_misses_total`` / ``pumi_compile_seconds_total`` (one shared
registry), per-job and per-quantum flight records plus
journal/recovery records, and the live Prometheus endpoint via
``PUMI_TPU_PROM_PORT``.

Per-job distributed tracing (obs/trace.py) threads a causal spine
through all of it: every job carries a ``trace_id`` from submission to
its terminal ``job`` root span — ``submit`` → ``queued`` → ``admit`` →
one ``quantum`` span per scheduling quantum (with ``retry`` events
parented on the failing quantum) → ``preempted``/``recovered``/
terminal — and the ambient binding the loop sets around each phase
pulls the bank's resolve/deserialize/compile spans and the
coordinator's classify/probe spans into the SAME trace.  The journal
persists each job's trace_id (schema 2), so a recovered job CONTINUES
its trace across a server crash; spans stream to
``<journal_dir>/TRACE.jsonl``.  Device-time attribution: the
wall-clock around each blocked dispatch accumulates into
``pumi_job_device_seconds{member=}`` (per-job attribution stays on
``Job.device_seconds`` and the /jobs rows); SLO
histograms ``pumi_job_e2e_seconds`` and
``pumi_job_time_to_first_quantum_seconds`` time the full job arc and
the admission latency.  The crash black box dumps the tracer's ring
(atomic JSON, PUMI008) on poison and from the signal flush/close
paths, and the exporter gains ``/jobs`` + ``/trace`` endpoints —
``scripts/teleview.py --job`` renders either surface.  Tracing is
zero-cost to physics: spans wrap HOST control flow only, so served
fluxes are bitwise identical with ``PUMI_TPU_TRACE=off``.
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import time
import types

import numpy as np

from ..integrity.watchdog import DispatchTimeoutError
from ..obs import (
    FLIGHT_SCHEMA,
    FlightRecorder,
    MetricsRegistry,
    SpanTracer,
    maybe_start_exporter,
)
from ..resilience.coordinator import ResilienceCoordinator
from ..resilience.faultinject import FaultInjector, InjectedKill
from ..tuning.shapes import bucket, classify
from ..utils.checkpoint import (
    restore_state,
    snapshot_state,
    verify_checkpoint,
)
from ..utils.config import TallyConfig
from ..utils.log import log_info, log_warn
from ..utils.signals import (
    install_preemption_handlers,
    resume_previous_handler,
    uninstall_preemption_handlers,
)
from .bank import ProgramBank
from .journal import (
    DISK_FULL_ERRNOS,
    SchedulerJournal,
    check_job_id,
    request_from_json,
    request_to_json,
)

# Job lifecycle: queued -> resident -> (preempted -> queued ->)* -> done
QUEUED, RESIDENT, PREEMPTED, DONE = (
    "queued", "resident", "preempted", "done",
)

# /jobs scrape cap: rows returned by the exporter's job table unless
# the scrape overrides with ?limit= (newest rows first).
JOBS_JSON_LIMIT = 500


def _jobs_limit(query: dict | None) -> int:
    """Resolve ``?limit=`` from a parsed query dict; malformed values
    fall back to the default rather than 500-ing a scrape."""
    try:
        limit = int((query or {}).get("limit", JOBS_JSON_LIMIT))
    except (TypeError, ValueError):
        return JOBS_JSON_LIMIT
    return max(0, limit)


@dataclasses.dataclass
class JobRequest:
    """One tally job: walk ``n_moves`` device-sourced moves for the
    given source particles and return the raw flux.  ``origins`` is
    [n, 3] float64 (host order); ``weights``/``groups`` default to
    ones/zeros.  ``source`` is an ``ops.source.SourceParams`` (its
    ``seed`` keys the job's RNG stream)."""

    origins: np.ndarray
    n_moves: int
    source: object | None = None
    weights: np.ndarray | None = None
    groups: np.ndarray | None = None
    job_id: str | None = None
    #: Caller-supplied trace identity (the gateway's ``traceparent``
    #: header lands here): the job JOINS this trace instead of minting
    #: a root, so an external client can follow its job end-to-end.
    trace_id: str | None = None


class Job:
    """Scheduler-internal job state."""

    def __init__(self, job_id: str, request: JobRequest, n: int,
                 padded_n: int, shape_key: str, index: int = 0):
        self.id = job_id
        self.index = index         # submission ordinal (fault targeting)
        self.request = request
        self.n = n
        self.padded_n = padded_n
        self.shape_key = shape_key
        self.state = QUEUED
        self.outcome: str | None = None
        self.error: str | None = None
        self.tally = None
        self.moves_done = 0
        self.quanta = 0            # quanta run since last admission
        self.preemptions = 0
        self.retries = 0           # transient quanta replayed
        self.recovery_seconds = 0.0
        self.needs_stage = True    # first quantum stages the lanes
        self.checkpoint: str | None = None
        self.result: np.ndarray | None = None
        self.flux_name: str | None = None   # journal-relative, if any
        self.request_json: dict | None = None  # serialized-once cache
        self.totals: dict = collections.defaultdict(float)
        self.submitted_s = time.perf_counter()
        self.enqueued_s = self.submitted_s
        self.finished_s: float | None = None
        # Distributed-trace identity + device-time attribution
        # (obs/trace.py; persisted in the schema-2 journal so both
        # survive a server crash).  A caller-supplied request trace id
        # (gateway ``traceparent``) is joined, not re-minted.
        self.trace_id: str = request.trace_id or SpanTracer.new_trace()
        self.device_seconds = 0.0  # wall around blocked dispatches
        self.first_dispatch_s: float | None = None

    @property
    def terminal(self) -> bool:
        return self.state == DONE


@contextlib.contextmanager
def _quiet_exporter():
    """Suppress the per-tally Prometheus endpoint while the scheduler
    constructs job facades — the SCHEDULER's registry owns the scrape
    port; dozens of short-lived job tallies racing to bind it would
    only warn-spam."""
    prev = os.environ.pop("PUMI_TPU_PROM_PORT", None)
    try:
        yield
    finally:
        if prev is not None:
            os.environ["PUMI_TPU_PROM_PORT"] = prev


class TallyScheduler:
    """Multi-tenant megastep-quantum scheduler over one mesh.

    Args:
      mesh: the served TetMesh (device-resident, shared by every job).
      config: per-job TallyConfig template.  ``megastep`` is overridden
        by the resolved quantum so facade chunking and scheduler
        quanta coincide (a preemption boundary is always a megastep
        boundary).
      bank: a ProgramBank, a bank root path (constructed with the
        scheduler's registry), or None (jit path — every fresh process
        pays compile cost; the bench's aot=off baseline).
      max_resident: resident-job cap (device memory bound: each
        resident job holds padded lanes + one flux accumulator).
      quantum_moves: fused moves per scheduling quantum (default: the
        config/env/tuning-resolved megastep K).
      preempt_after: quanta a resident job may hold its slot while
        other jobs queue before it is checkpoint-preempted (None: run
        to completion).
      checkpoint_dir: where preemption checkpoints live (required when
        ``preempt_after`` is set and no journal_dir is given — a
        journaled scheduler preempts into its journal directory).
      max_queued: admission backpressure — a submission arriving with
        this many jobs already waiting is finished
        ``outcome="rejected"`` instead of queued (None: unbounded).
      job_retries: bounded per-quantum replay budget for transient
        failures (0 disables snapshots and retries — any dispatch
        failure poisons the job).
      quantum_deadline_s: per-quantum dispatch watchdog deadline
        (integrity/watchdog.py via the job configs' move_deadline_s);
        a timeout is classified like any transient.
      journal_dir: the JOBS.json write-ahead journal directory
        (serving/journal.py); enables ``recover`` and the
        SIGTERM/SIGINT flush.
      blackbox_dir: where crash-postmortem black boxes land
        (``<tag>.blackbox.json`` — the tracer ring dumped atomically
        on poison, on the signal flush, and at close).  Defaults to
        the journal directory; None without a journal disables dumps.
      faults: the scheduler-level FaultInjector driving the per-job
        fault hooks (poison_job / transient_quantum /
        kill_server_at_quantum) and the per-member hooks
        (wedge_member / slow_member / disk_full_at); default: one
        built from PUMI_TPU_FAULTS.
      member_index: this scheduler's fleet-member index (set by
        FleetRouter) — the identity the per-member fault hooks and
        the fleet supervisor's health probes key on; None for a
        standalone scheduler.
    """

    def __init__(
        self,
        mesh,
        config: TallyConfig | None = None,
        *,
        bank: ProgramBank | str | None = None,
        max_resident: int = 2,
        quantum_moves: int | None = None,
        preempt_after: int | None = None,
        checkpoint_dir: str | None = None,
        max_queued: int | None = None,
        job_retries: int = 2,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        quantum_deadline_s: float | None = None,
        journal_dir: str | None = None,
        blackbox_dir: str | None = None,
        faults: FaultInjector | None = None,
        member_index: int | None = None,
        handle_signals: bool = True,
        registry: MetricsRegistry | None = None,
        tracer: SpanTracer | None = None,
        recorder: FlightRecorder | None = None,
        sleep=time.sleep,
    ):
        self.mesh = mesh
        base = config or TallyConfig()
        self.quantum = int(
            quantum_moves
            if quantum_moves is not None
            else base.resolve_megastep()
        )
        if self.quantum < 1:
            raise ValueError(f"quantum_moves must be >= 1: {self.quantum}")
        # Facade chunking == scheduler quantum: run_source_moves(k)
        # with megastep=quantum runs one fused dispatch per quantum,
        # and a job interleaved with others chains bitwise-identically
        # to the same chunks run back to back.
        self.config = dataclasses.replace(base, megastep=self.quantum)
        if quantum_deadline_s is not None:
            self.config = dataclasses.replace(
                self.config, move_deadline_s=float(quantum_deadline_s)
            )
        self.max_resident = int(max_resident)
        if self.max_resident < 1:
            raise ValueError(
                f"max_resident must be >= 1: {self.max_resident}"
            )
        self.max_queued = None if max_queued is None else int(max_queued)
        if self.max_queued is not None and self.max_queued < 1:
            raise ValueError(
                f"max_queued must be >= 1: {self.max_queued}"
            )
        self.job_retries = int(job_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._sleep = sleep
        self.faults = faults if faults is not None else FaultInjector()
        self.member_index = (
            None if member_index is None else int(member_index)
        )
        self.journal = (
            SchedulerJournal(journal_dir)
            if journal_dir is not None else None
        )
        # Per-quantum wall seconds (successful quanta only), the
        # sliding window the fleet supervisor's brownout SLO compares
        # against the fleet median (serving/supervisor.py).
        self.recent_quantum_seconds: collections.deque = (
            collections.deque(maxlen=64)
        )
        self.preempt_after = preempt_after
        self.checkpoint_dir = checkpoint_dir
        if (
            preempt_after is not None
            and checkpoint_dir is None
            and self.journal is None
        ):
            raise ValueError(
                "preempt_after needs checkpoint_dir or journal_dir "
                "(preemption persists job state through the "
                "checkpoint subsystem)"
            )
        if checkpoint_dir is not None:
            # Fail at construction, not at the first mid-run
            # preemption (the atomic checkpoint writer mkstemps into
            # this directory and does not create it).
            os.makedirs(checkpoint_dir, exist_ok=True)
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.recorder = (
            recorder if recorder is not None
            else FlightRecorder(schema=FLIGHT_SCHEMA)
        )
        # One tracer for the whole serving path (scheduler + bank +
        # coordinator share it via the ambient binding); journaled
        # schedulers stream spans to <journal_dir>/TRACE.jsonl so both
        # process lifetimes of a crashed server append to one stream.
        # A fleet (serving/fleet.py) passes one shared tracer/recorder
        # so every member streams into the SAME fleet-level spine.
        self.tracer = tracer if tracer is not None else SpanTracer(
            sink=(
                self.journal.trace_path()
                if self.journal is not None else None
            ),
        )
        self.blackbox_dir = (
            blackbox_dir if blackbox_dir is not None
            else (self.journal.dir if self.journal is not None else None)
        )
        if self.blackbox_dir is not None:
            os.makedirs(self.blackbox_dir, exist_ok=True)
        if isinstance(bank, str):
            bank = ProgramBank(
                bank, registry=self.registry, recorder=self.recorder,
                tracer=self.tracer,
            )
        self.bank = bank
        r = self.registry
        self._jobs_total = r.counter(
            "pumi_jobs_total",
            "served tally jobs by outcome (completed: move budget "
            "exhausted or all particles terminated; converged: "
            "evicted early at the requested precision; poisoned: "
            "isolated after a persistent per-job failure or an "
            "exhausted retry budget; rejected: admission "
            "backpressure at max_queued; cancelled: terminated by "
            "an explicit cancel request)",
        )
        self._queue_depth = r.gauge(
            "pumi_queue_depth",
            "jobs waiting for a resident slot (preempted jobs "
            "re-queue and count)",
        )
        self._preempt_total = r.counter(
            "pumi_preemptions_total",
            "resident jobs checkpoint-preempted to admit queued work",
        )
        self._quanta_total = r.counter(
            "pumi_quanta_total",
            "scheduling quanta executed (one megastep-K dispatch "
            "window per resident job per round)",
        )
        self._job_seconds = r.histogram(
            "pumi_job_seconds",
            "wall seconds from job submission to completion",
        )
        self._retries_total = r.counter(
            "pumi_job_retries_total",
            "per-job quantum replays after a transient-classified "
            "dispatch failure (labeled by cause: transient, timeout)",
        )
        self._queue_seconds = r.histogram(
            "pumi_job_queue_seconds",
            "wall seconds a job waited in the admission queue before "
            "each (re)admission to a device slot",
        )
        self._recovered_total = r.counter(
            "pumi_jobs_recovered_total",
            "jobs re-queued from the JOBS.json journal at recovery "
            "(labeled by source: checkpoint = resumed mid-run, "
            "scratch = request replayed from move 0, migrated = "
            "adopted from another fleet member's journal, evicted = "
            "adopted from a member the supervisor drained)",
        )
        self._device_seconds = r.counter(
            "pumi_job_device_seconds",
            "wall seconds spent inside blocked quantum dispatches "
            "(labeled by fleet member — per-JOB attribution lives on "
            "Job.device_seconds and the /jobs rows; a per-job-id "
            "label here would grow the family without bound)",
        )
        self._quantum_wall_seconds = r.counter(
            "pumi_quantum_wall_seconds_total",
            "cumulative wall seconds inside scheduling quanta "
            "(device dispatch + host overhead + retries + injected "
            "latency), labeled by fleet member — the fleet profiler's "
            "dispatch-wait breakdown reads device vs quantum wall",
        )
        self._e2e_seconds = r.histogram(
            "pumi_job_e2e_seconds",
            "SLO: wall seconds from submission to terminal state "
            "(completed/converged/poisoned/rejected)",
        )
        self._ttfq_seconds = r.histogram(
            "pumi_job_time_to_first_quantum_seconds",
            "SLO: wall seconds from submission to the first quantum "
            "dispatch (queue wait + admission + staging)",
        )
        self._journal_degraded_gauge = r.gauge(
            "pumi_journal_degraded",
            "1 while this scheduler's journal is in disk-pressure "
            "degraded mode (ENOSPC-class durable-write failure — "
            "flushes frozen, residents parked; serving/journal.py "
            "'Degraded mode'), labeled by fleet member",
        )
        self._journal_degraded_gauge.set(
            0.0, member=self._member_label()
        )
        if self.journal is not None:
            # Resolve the injector at gate time (a chaos harness swaps
            # ``self.faults`` mid-run) and surface the degraded
            # transition through this scheduler's metrics/recorder.
            self.journal.faults = lambda: self.faults
            self.journal.on_degraded = self._on_journal_degraded
        # The PR 11 failure taxonomy, shared with ResilientRunner: one
        # coordinator on the SCHEDULER registry, rebound to the failing
        # job's facade at classification time (the probe needs the
        # job's device set; the counters belong to the server).
        self._coordinator = ResilienceCoordinator(
            types.SimpleNamespace(metrics=r), faults=self.faults,
            tracer=self.tracer,
        )
        # Per-class FIFO queues + a rotation pointer: admission takes
        # one job per class in turn, so a burst in one shape bucket
        # cannot starve the others.
        self._queues: dict[str, collections.deque] = {}
        self._class_order: list[str] = []
        self._next_class = 0
        self._resident: list[Job] = []
        self._jobs: dict[str, Job] = {}
        self._n_submitted = 0
        self._n_quanta = 0          # lifetime quanta (fault targeting)
        self._n_recovered = 0
        self._in_step = False
        self._pending_signal: int | None = None
        self._prev_handlers: dict = {}
        if self.journal is not None and handle_signals:
            self._install_signal_handlers()
        self._exporter = maybe_start_exporter(
            self.registry,
            endpoints={
                "/jobs": self._jobs_json,
                "/trace": self.tracer.chrome,
            },
        )

    def _member_label(self) -> str:
        return (
            "solo" if self.member_index is None
            else f"m{self.member_index}"
        )

    def _on_journal_degraded(self, op: str, exc: OSError) -> None:
        """Journal's degraded-mode transition callback: hang the gauge
        and a flight record off the first ENOSPC-class failure."""
        self._journal_degraded_gauge.set(
            1.0, member=self._member_label()
        )
        self.recorder.record(
            "journal_degraded", member=self._member_label(),
            op=op, error=str(exc)[:200],
        )

    # -- fleet-supervisor probes (serving/supervisor.py) --------------- #
    @property
    def wedged(self) -> bool:
        """True while the ``wedge_member`` fault holds this member: it
        answers no probe and makes no progress, but keeps its jobs and
        device state (the silent-wedge failure mode)."""
        return self.faults.member_wedged(self.member_index)

    def heartbeat(self) -> bool:
        """One liveness probe: False when this member is wedged, else
        the per-chip health probe verdict (every device of the served
        mesh answers a device_put round-trip —
        resilience/coordinator.py)."""
        if self.wedged:
            return False
        return all(self._coordinator.probe_chips().values())

    # ------------------------------------------------------------------ #
    # Submission
    # ------------------------------------------------------------------ #
    def submit(self, request: JobRequest) -> str:
        """Enqueue one job; returns its id.  The job is padded onto the
        shape ladder here — its bucket decides which queue it joins
        and which bank entries will serve it."""
        origins = np.asarray(request.origins, np.float64).reshape(-1, 3)
        n = origins.shape[0]
        if n < 1:
            raise ValueError("a job needs at least one particle")
        if request.n_moves < 1:
            raise ValueError(f"n_moves must be >= 1: {request.n_moves}")
        for name, arr in (
            ("weights", request.weights), ("groups", request.groups),
        ):
            if arr is not None and np.asarray(arr).reshape(-1).size != n:
                # A silent [:n] truncation would scale the flux by the
                # wrong source weights — reject the mismatch up front.
                raise ValueError(
                    f"{name} has {np.asarray(arr).reshape(-1).size} "
                    f"entries for {n} particles — per-lane arrays must "
                    "match the request's UNPADDED particle count"
                )
        padded_n = bucket(n)
        cfg = self.config
        shape = classify(
            self.mesh.ntet, padded_n, cfg.n_groups, cfg.dtype,
            getattr(self.mesh, "geo20", None) is not None,
        )
        job_id = request.job_id or f"job-{self._n_submitted:05d}"
        if job_id in self._jobs:
            raise ValueError(f"duplicate job id {job_id!r}")
        # The id becomes filenames (journal sidefiles AND the
        # preemption checkpoint path) — refuse path tricks up front,
        # journaled or not.
        check_job_id(job_id)
        # Serialize the (immutable) request ONCE; every journal flush
        # reuses the dict instead of re-walking the float64 payload.
        request_json = (
            request_to_json(request) if self.journal is not None
            else None
        )
        job = Job(
            job_id, request, n, padded_n, shape.key(),
            index=self._n_submitted,
        )
        job.request_json = request_json
        self._n_submitted += 1
        self._jobs[job_id] = job
        # The trace starts at submission for EVERY outcome — a
        # rejected job's (short) trace still reads submit → job.
        self.tracer.event(
            "submit", trace_id=job.trace_id,
            parent=SpanTracer.root_id(job.trace_id), job_id=job_id,
            shape_key=job.shape_key, n=n, padded_n=padded_n,
            n_moves=int(request.n_moves),
        )
        if (
            self.max_queued is not None
            and self.queue_depth >= self.max_queued
        ):
            # Named backpressure: the job is terminal on arrival — the
            # caller sees outcome="rejected" instead of an unbounded
            # queue absorbing work the server cannot promise to run.
            job.state = DONE
            job.outcome = "rejected"
            job.finished_s = time.perf_counter()
            self._jobs_total.inc(outcome="rejected")
            self._job_seconds.observe(job.finished_s - job.submitted_s)
            self.recorder.record(
                "job_rejected", job=job_id, job_id=job_id,
                shape_key=job.shape_key,
                queue_depth=self.queue_depth,
                max_queued=self.max_queued,
            )
            self._trace_terminal(
                job, "rejected", queue_depth=self.queue_depth
            )
            self._flush_journal()
            return job_id
        self._enqueue(job)
        self.recorder.record(
            "job_submitted", job=job_id, job_id=job_id,
            shape_key=job.shape_key,
            n=n, padded_n=padded_n, n_moves=int(request.n_moves),
        )
        self._flush_journal()
        return job_id

    def _enqueue(self, job: Job) -> None:
        q = self._queues.get(job.shape_key)
        if q is None:
            q = self._queues[job.shape_key] = collections.deque()
            self._class_order.append(job.shape_key)
        q.append(job)
        job.state = QUEUED if job.checkpoint is None else PREEMPTED
        job.enqueued_s = time.perf_counter()
        self._queue_depth.set(self.queue_depth)

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def resident_count(self) -> int:
        return len(self._resident)

    def _pop_next(self) -> Job | None:
        """Round-robin across shape-class queues."""
        if not self._class_order:
            return None
        for _ in range(len(self._class_order)):
            key = self._class_order[
                self._next_class % len(self._class_order)
            ]
            self._next_class += 1
            q = self._queues[key]
            if q:
                return q.popleft()
        return None

    # ------------------------------------------------------------------ #
    # Crash-safe journal + recovery
    # ------------------------------------------------------------------ #
    def _journal_entry(self, job: Job) -> dict:
        done = job.state == DONE
        if job.request_json is None:
            job.request_json = request_to_json(job.request)
        return {
            "id": job.id,
            "index": job.index,
            "state": "done" if done else "pending",
            "outcome": job.outcome,
            "error": job.error,
            "shape_key": job.shape_key,
            "n": job.n,
            "padded_n": job.padded_n,
            "moves_done": job.moves_done,
            "preemptions": job.preemptions,
            "retries": job.retries,
            # Terminal records never reference a checkpoint: the side
            # file is deleted AFTER the flush that marks the job done
            # (write-ahead order — a crash between the two must not
            # leave a record pointing at a removed file).
            "checkpoint": (
                os.path.basename(job.checkpoint)
                if job.checkpoint is not None and not done else None
            ),
            "flux": job.flux_name,
            # Schema-2 trace fields: the id lets the NEXT process
            # continue this job's distributed trace after a crash.
            "trace_id": job.trace_id,
            "device_seconds": round(job.device_seconds, 6),
            "request": job.request_json,
        }

    def _flush_journal(self) -> None:
        if self.journal is None:
            return
        self.journal.flush(
            [
                self._journal_entry(j)
                for j in sorted(
                    self._jobs.values(), key=lambda j: j.index
                )
            ],
            quantum_moves=self.quantum,
        )

    def _journal_checkpoint(self, job: Job) -> None:
        """Quantum-boundary checkpoint into the journal dir (written
        BEFORE the journal flush that references it — the write-ahead
        discipline serving/journal.py documents).  An ENOSPC-class
        failure degrades the journal instead of crashing the serving
        loop: the job keeps its previous checkpoint (if any), whose
        own move counter makes a later resume bitwise."""
        if self.journal is None or job.tally is None:
            return
        if self.journal.degraded:
            return
        path = self.journal.checkpoint_path(job.id)
        try:
            self.journal._gate_durable()
            job.tally.save_checkpoint(path)
        except OSError as exc:
            if exc.errno not in DISK_FULL_ERRNOS:
                raise
            self.journal.note_disk_failure("quantum checkpoint", exc)
            return
        job.checkpoint = path

    @classmethod
    def recover(cls, journal_dir: str, mesh,
                config: TallyConfig | None = None, **kwargs):
        """Build a scheduler over an existing journal and re-queue
        every interrupted job: terminal jobs come back with their
        outcome (and their persisted flux, so results survive the
        process that computed them); pending jobs resume from their
        quantum-boundary checkpoint when it verifies — BITWISE, since
        the megastep RNG is keyed by the restored move counter — or
        replay from move 0 when it does not (also bitwise: the whole
        trajectory re-runs).  Over a warm program bank the recovered
        process compiles no program family."""
        sched = cls(mesh, config, journal_dir=journal_dir, **kwargs)
        try:
            doc = sched.journal.load()
            if not doc:
                return sched
            for entry in sorted(
                doc.get("jobs", {}).values(), key=lambda e: e["index"]
            ):
                sched._recover_job(entry)
            sched._n_submitted = max(
                (j.index + 1 for j in sched._jobs.values()),
                default=sched._n_submitted,
            )
            sched.recorder.record(
                "journal_recovery", jobs=len(sched._jobs),
                recovered=sched._n_recovered,
                quantum_moves=doc.get("quantum_moves"),
            )
            log_info(
                f"scheduler recovery: {len(sched._jobs)} journaled "
                f"jobs, {sched._n_recovered} re-queued from "
                f"{journal_dir}"
            )
            sched._flush_journal()
        except BaseException:
            # Construction already installed the preemption handlers;
            # a failed recovery (unreadable journal, bad entry) must
            # not leak them — a stale handler would route the NEXT
            # signal into this dead half-recovered scheduler.  abandon
            # (not close): the journal on disk stays exactly as the
            # crashed process committed it, never rewritten with a
            # half-recovered table.
            sched.abandon()
            raise
        return sched

    def _recover_job(self, entry: dict) -> None:
        self._import_entry(entry, src_dir=None, link="recovered")

    def _copy_sidefile(self, src: str, dst: str) -> bool:
        """Copy one journal side file (checkpoint/flux) from another
        member's journal directory into this one — atomically, so a
        crash mid-migration never leaves a torn file under the real
        name.  Returns False when the source is missing."""
        if not os.path.exists(src):
            return False
        with open(src, "rb") as fh:
            data = fh.read()
        from ..utils.checkpoint import atomic_write_bytes

        atomic_write_bytes(dst, data)
        return True

    def _import_entry(self, entry: dict, *, src_dir: str | None,
                      link: str) -> Job:
        """Rebuild one journaled job in this scheduler.  ``link`` names
        the cross-lifetime trace event: ``recovered`` (same journal,
        new process), ``migrated`` (another member's journal — side
        files are copied in from ``src_dir`` first), or ``evicted``
        (same copy-in, but the hop was forced by the supervisor
        draining an unhealthy member)."""
        request = request_from_json(entry["request"])
        origins = np.asarray(request.origins, np.float64).reshape(-1, 3)
        n = origins.shape[0]
        padded_n = bucket(n)
        cfg = self.config
        shape_key = classify(
            self.mesh.ntet, padded_n, cfg.n_groups, cfg.dtype,
            getattr(self.mesh, "geo20", None) is not None,
        ).key()
        if entry["id"] in self._jobs:
            raise ValueError(
                f"duplicate job id {entry['id']!r} (already owned by "
                "this scheduler)"
            )
        job = Job(
            entry["id"], request, n, padded_n, shape_key,
            index=int(entry["index"]),
        )
        job.request_json = entry["request"]
        job.preemptions = int(entry.get("preemptions", 0))
        job.retries = int(entry.get("retries", 0))
        job.error = entry.get("error")
        # Continue the crashed process's trace: same trace_id, new
        # spans (schema-1 journals predate tracing — those jobs start
        # a fresh trace here).  Device-time attribution accumulates
        # across lifetimes.
        if entry.get("trace_id"):
            job.trace_id = str(entry["trace_id"])
        job.device_seconds = float(entry.get("device_seconds", 0.0))
        self._jobs[job.id] = job
        if entry["state"] == "done":
            job.state = DONE
            job.outcome = entry.get("outcome")
            job.moves_done = int(entry.get("moves_done", 0))
            job.finished_s = job.submitted_s
            if entry.get("flux"):
                if src_dir is not None:
                    self._copy_sidefile(
                        os.path.join(src_dir, entry["flux"]),
                        self.journal.flux_path(job.id),
                    )
                job.result = self.journal.load_flux(job.id)
                job.flux_name = entry["flux"]
            return job
        source = "scratch"
        if entry.get("checkpoint"):
            ck = self.journal.checkpoint_path(job.id)
            if src_dir is not None:
                self._copy_sidefile(
                    os.path.join(src_dir, entry["checkpoint"]), ck
                )
            try:
                verify_checkpoint(ck)
                job.checkpoint = ck
                job.moves_done = int(entry.get("moves_done", 0))
                source = "checkpoint"
            except Exception as e:
                # Torn/corrupt/missing checkpoint: the request is
                # still intact in the journal — replay from move 0
                # (bitwise: the whole stream re-runs on the same
                # counter keys) instead of losing the job.
                log_warn(
                    f"scheduler recovery: checkpoint for {job.id} "
                    f"unusable ({e}); replaying from move 0"
                )
        self._enqueue(job)
        self._n_recovered += 1
        self._recovered_total.inc(
            source=link if link in ("migrated", "evicted") else source
        )
        # The explicit cross-lifetime link: this span's pid (or, for a
        # migration, member) differs from the spans the previous owner
        # emitted, and both parent onto the same deterministic root id.
        self.tracer.event(
            link, trace_id=job.trace_id,
            parent=SpanTracer.root_id(job.trace_id), job_id=job.id,
            source=source, moves_done=job.moves_done,
        )
        self.recorder.record(
            "journal_recovered", job=job.id, job_id=job.id,
            shape_key=job.shape_key, link=link,
            source=source, moves_done=job.moves_done,
        )
        return job

    # ------------------------------------------------------------------ #
    # Cross-member migration primitives (serving/fleet.py)
    # ------------------------------------------------------------------ #
    def preempt_job(self, job_id: str) -> None:
        """Checkpoint-preempt one RESIDENT job at its megastep boundary
        (no-op for queued/preempted/terminal jobs) — the export half of
        a cross-chip migration."""
        job = self._jobs[job_id]
        if job.state == RESIDENT:
            self._preempt(job)

    def park_job(self, job_id: str) -> None:
        """Degraded-safe preempt of one RESIDENT job (no-op
        otherwise): checkpoint-preempt when the disk allows; under
        disk pressure, release the device slot WITHOUT a durable
        checkpoint.  The job then resumes from its previous
        quantum-boundary checkpoint if one exists on disk (its own
        move counter makes that bitwise), else replays from move 0
        (also bitwise — the whole stream re-runs).  The supervisor's
        disk-pressure drain and the scheduler's own degraded parking
        both route through here."""
        job = self._jobs[job_id]
        if job.state != RESIDENT:
            return
        if self.journal is None or not self.journal.degraded:
            try:
                self._preempt(job)
                return
            except OSError as exc:
                if exc.errno not in DISK_FULL_ERRNOS:
                    raise
                if self.journal is not None:
                    self.journal.note_disk_failure(
                        "preempt checkpoint", exc
                    )
        # Disk-pressure fallback: free the slot, keep (at most) the
        # last durable checkpoint as the resume point.
        if job.tally is not None:
            try:
                job.tally.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            job.tally = None
        if job in self._resident:
            self._resident.remove(job)
        job.preemptions += 1
        if job.checkpoint is None or not os.path.exists(job.checkpoint):
            job.checkpoint = None
            job.moves_done = 0
            job.needs_stage = True
        self._preempt_total.inc()
        self.recorder.record(
            "job_parked", job=job.id, job_id=job.id,
            shape_key=job.shape_key, moves=job.moves_done,
            degraded=True,
        )
        self._enqueue(job)
        self._flush_journal()

    def _park_degraded(self) -> None:
        """Degraded-mode quantum boundary (satellite contract): park
        every resident so device memory is released and all state is
        journaled-or-replayable, then hold admission until a
        supervisor drains this member or an operator intervenes."""
        for job in list(self._resident):
            self.park_job(job.id)

    def export_entry(self, job_id: str) -> dict:
        """This job's journal entry — exactly what recovery would read;
        ``adopt_job`` on another member rebuilds the job from it."""
        return self._journal_entry(self._jobs[job_id])

    def adopt_job(self, entry: dict, *, src_dir: str | None = None,
                  link: str = "migrated") -> Job:
        """Adopt one job journaled by ANOTHER fleet member (cross-chip
        migration / dead-member re-placement / supervisor eviction):
        side files are copied from ``src_dir`` into this journal, a
        pending job re-queues from its checkpoint (bitwise — the move
        counter keys the RNG), a done job lands terminal with its
        persisted flux, and the trace continues across the hop with a
        ``migrated`` (or ``evicted``) link.  The adopted job is
        journaled here BEFORE the caller drops it from the source
        member (write-ahead: two journals briefly know the job; the
        fleet's assignment record names the owner)."""
        if self.journal is None:
            raise ValueError(
                "adopt_job needs a journaled scheduler (fleet members "
                "always journal)"
            )
        if link not in ("migrated", "evicted"):
            raise ValueError(
                f"adopt_job link must be 'migrated' or 'evicted': "
                f"{link!r}"
            )
        entry = dict(entry, index=self._n_submitted)
        job = self._import_entry(entry, src_dir=src_dir, link=link)
        self._n_submitted += 1
        self._flush_journal()
        return job

    def drop_job(self, job_id: str) -> None:
        """Forget one job after another member adopted it: remove it
        from the queue and the journal document, then its side files
        (record first, delete after — the same write-ahead edge as
        every terminal transition).  Resident jobs must be
        checkpoint-preempted (``preempt_job``) first."""
        job = self._jobs[job_id]
        if job.state == RESIDENT:
            raise ValueError(
                f"job {job_id} is resident — preempt_job() before "
                "drop_job()"
            )
        q = self._queues.get(job.shape_key)
        if q is not None and job in q:
            q.remove(job)
        del self._jobs[job_id]
        self._queue_depth.set(self.queue_depth)
        self._flush_journal()
        if self.journal is not None:
            self.journal.remove_sidefiles(job_id, flux=True)

    def cancel(self, job_id: str) -> bool:
        """Terminate one non-terminal job (outcome="cancelled"): free
        its slot or queue position and journal the terminal record
        before its checkpoint is removed.  Returns False when the job
        is already terminal (cancel is idempotent, never un-finishes
        work)."""
        job = self._jobs[job_id]
        if job.terminal:
            return False
        if job.tally is not None:
            try:
                job.tally.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            job.tally = None
        if job in self._resident:
            self._resident.remove(job)
        q = self._queues.get(job.shape_key)
        if q is not None and job in q:
            q.remove(job)
        job.state = DONE
        job.outcome = "cancelled"
        job.finished_s = time.perf_counter()
        self._jobs_total.inc(outcome="cancelled")
        self._job_seconds.observe(job.finished_s - job.submitted_s)
        self._queue_depth.set(self.queue_depth)
        self._trace_terminal(job, "cancelled")
        self.recorder.record(
            "job_cancelled", job=job_id, job_id=job_id,
            shape_key=job.shape_key, moves=job.moves_done,
        )
        self._flush_journal()
        self._remove_checkpoint(job)
        return True

    # ------------------------------------------------------------------ #
    # Preemption-signal flush (journaled schedulers only)
    # ------------------------------------------------------------------ #
    def _install_signal_handlers(self) -> None:
        self._prev_handlers = install_preemption_handlers(
            self._on_signal, "TallyScheduler"
        )

    def _uninstall_signal_handlers(self) -> None:
        uninstall_preemption_handlers(
            self._prev_handlers, mine=self._on_signal
        )
        self._prev_handlers = {}

    def _on_signal(self, signum, frame) -> None:
        if self._in_step:
            # Mid-quantum: defer to the quantum boundary so the
            # flushed checkpoints are consistent post-dispatch states.
            self._pending_signal = signum
            return
        self._signal_flush(signum, frame)

    def _signal_flush(self, signum, frame) -> None:
        """One final checkpoint of every resident job + a journal
        flush, then die the way the process would have without us —
        the next process's ``recover`` resumes every job."""
        for job in list(self._resident):
            try:
                self._journal_checkpoint(job)
            except Exception as e:  # pragma: no cover - best-effort
                log_warn(f"preemption checkpoint of {job.id} failed: {e}")
        try:
            self._flush_journal()
            log_info(
                f"scheduler preemption flush: journal written on "
                f"signal {signum}"
            )
        except Exception as e:  # pragma: no cover - flush best-effort
            log_warn(f"scheduler preemption flush failed: {e}")
        # Black box last (the journal is the recovery-critical write):
        # the tracer ring dumped atomically, lock-free — this path is
        # signal-handler-reachable (PUMI009), and the dump must not
        # block on a lock an interrupted appender still holds.
        self._blackbox("shutdown", reason=f"signal-{signum}")
        prev = self._prev_handlers.get(signum)
        self._uninstall_signal_handlers()
        resume_previous_handler(prev, signum, frame)

    # ------------------------------------------------------------------ #
    # Padding helpers
    # ------------------------------------------------------------------ #
    def _padded_inputs(self, job: Job):
        """Host arrays padded to the shape bucket: pad lanes sit at the
        first request position with zero weight and alive=False — they
        are initialized (parent-element search needs a valid position)
        but never walk, never score, and never sample."""
        req, n, N = job.request, job.n, job.padded_n
        origins = np.asarray(req.origins, np.float64).reshape(-1, 3)
        pad = np.broadcast_to(origins[0], (N - n, 3))
        origins_p = np.concatenate([origins, pad], axis=0)
        w = (
            np.ones(n) if req.weights is None
            else np.asarray(req.weights, np.float64).reshape(-1)[:n]
        )
        g = (
            np.zeros(n, np.int32) if req.groups is None
            else np.asarray(req.groups, np.int32).reshape(-1)[:n]
        )
        weights_p = np.concatenate([w, np.zeros(N - n)])
        groups_p = np.concatenate([g, np.zeros(N - n, np.int32)])
        alive_p = np.concatenate(
            [np.ones(n, bool), np.zeros(N - n, bool)]
        )
        return origins_p, weights_p, groups_p, alive_p

    # ------------------------------------------------------------------ #
    # Residency
    # ------------------------------------------------------------------ #
    def _admit(self, job: Job) -> bool:
        from ..api import PumiTally

        root = SpanTracer.root_id(job.trace_id)
        wait = time.perf_counter() - job.enqueued_s
        self._queue_seconds.observe(wait)
        # The queue wait as a closed span (it just ended), then the
        # admission itself with a PRE-allocated span id: the ambient
        # binding parents everything emitted during admission — the
        # bank's resolve/deserialize/compile spans, the coordinator's
        # classify on failure — onto the admit span.
        self.tracer.span_record(
            "queued", wait, trace_id=job.trace_id, parent=root,
            job_id=job.id, preempted=job.checkpoint is not None,
        )
        aid = self.tracer.next_id()
        a0 = time.perf_counter()
        tally = None
        attrs: dict = {}
        try:
            with self.tracer.bind(job.trace_id, job.id, aid):
                try:
                    with _quiet_exporter():
                        tally = PumiTally(
                            self.mesh, job.padded_n, self.config,
                            program_bank=self.bank,
                        )
                    restored = False
                    if job.checkpoint is not None:
                        # Preempted/recovered job: restore the exact
                        # megastep boundary it was parked at — the move
                        # counter keys the RNG stream, so the
                        # continuation is bitwise the uninterrupted
                        # run.  An unusable checkpoint falls back to a
                        # from-scratch replay (also bitwise) instead of
                        # failing the job.
                        try:
                            tally.restore_checkpoint(job.checkpoint)
                            restored = True
                        except Exception as e:
                            log_warn(
                                f"checkpoint restore for {job.id} failed "
                                f"({e}); replaying from move 0"
                            )
                            job.checkpoint = None
                            job.moves_done = 0
                    if restored:
                        # The checkpoint's own counter is the truth — a
                        # journal written just before a crash may lag
                        # it by one quantum.
                        job.moves_done = int(tally.iter_count)
                        job.needs_stage = False
                    else:
                        origins_p, _, _, _ = self._padded_inputs(job)
                        tally.initialize_particle_location(
                            origins_p.reshape(-1).copy()
                        )
                        job.needs_stage = True
                except InjectedKill:
                    raise
                except Exception as e:
                    if tally is not None:
                        # Constructed but never handed to the job:
                        # release its device buffers before deciding
                        # the job's fate.
                        try:
                            tally.close()
                        except Exception:  # pragma: no cover - best-effort
                            pass
                    # Admission failures go through the SAME taxonomy
                    # as quantum failures: a transient verdict
                    # (retryable runtime error, timeout with healthy
                    # chips) re-queues the job against its bounded
                    # retry budget instead of permanently poisoning
                    # work one replay would have saved.
                    attrs["error"] = f"{type(e).__name__}: {e}"[:200]
                    self._coordinator.rebind(types.SimpleNamespace())
                    verdict = self._coordinator.classify(e)
                    if (
                        verdict == "transient"
                        and job.retries < self.job_retries
                    ):
                        job.retries += 1
                        cause = (
                            "timeout"
                            if isinstance(e, DispatchTimeoutError)
                            else "transient"
                        )
                        self._retries_total.inc(cause=cause)
                        log_warn(
                            f"admission of {job.id} failed transiently "
                            f"({e}); re-queueing (attempt "
                            f"{job.retries}/{self.job_retries})"
                        )
                        self.tracer.event(
                            "retry", cause=cause, attempt=job.retries,
                            at="admission",
                        )
                        self.recorder.record(
                            "job_retry", job=job.id, job_id=job.id,
                            shape_key=job.shape_key,
                            cause=cause, attempt=job.retries,
                            at="admission", error=str(e)[:200],
                        )
                        self._sleep(min(
                            self.backoff_base * 2 ** (job.retries - 1),
                            self.backoff_max,
                        ))
                        self._enqueue(job)
                    else:
                        self._poison(
                            job, e,
                            cause=(
                                "retries-exhausted"
                                if verdict == "transient" else verdict
                            ),
                        )
                    return False
                job.tally = tally
                job.quanta = 0
                job.state = RESIDENT
                self._resident.append(job)
                attrs["restored"] = not job.needs_stage
                self.recorder.record(
                    "job_admitted", job=job.id, job_id=job.id,
                    shape_key=job.shape_key,
                    restored=job.checkpoint is not None,
                )
                return True
        finally:
            self.tracer.span_record(
                "admit", time.perf_counter() - a0,
                trace_id=job.trace_id, parent=root, job_id=job.id,
                span_id=aid, **attrs,
            )

    def _quantum(self, job: Job) -> None:
        """One scheduling quantum: up to ``quantum_moves`` fused moves
        for one resident job, then the completion checks.  The
        dispatch runs under the per-job failure containment loop
        (module docstring): transient-classified failures replay the
        quantum bitwise from the job's pre-quantum snapshot with
        bounded backoff; everything else poisons THIS job only."""
        remaining = job.request.n_moves - job.moves_done
        if remaining <= 0:
            # A recovered checkpoint already at the move budget (the
            # crash landed between the final checkpoint and the finish
            # record): nothing to dispatch — the restored accumulator
            # IS the result.
            self._finish(job, "completed")
            return
        k = min(self.quantum, remaining)
        kw = {}
        if job.needs_stage:
            _, w, g, alive = self._padded_inputs(job)
            kw = dict(weights=w, groups=g, alive=alive)
        self._n_quanta += 1
        # Crash model: the injected server kill propagates raw — no
        # flush, no cleanup.  The write-ahead journal must already
        # hold everything recovery needs (that is the contract the
        # chaos campaign proves).
        self.faults.maybe_kill_server(self._n_quanta)
        snap = (
            snapshot_state(job.tally)
            if self.job_retries > 0 else None
        )
        # Pre-allocated quantum span id: retry events and the
        # coordinator's classify spans emitted mid-quantum parent onto
        # the quantum span via the ambient binding (the span itself is
        # emitted when the quantum closes — including by poison).
        qid = self.tracer.next_id()
        qattrs: dict = {"k": k, "move_start": job.moves_done}
        t0 = time.perf_counter()
        fail_t0 = None
        attempt = 0
        disp_s = 0.0  # wall inside blocked dispatches (device time)
        poison: tuple | None = None
        try:
            with self.tracer.bind(
                job.trace_id, job.id, qid
            ):
                while True:
                    d0 = time.perf_counter()
                    try:
                        self.faults.maybe_poison_job(job.index)
                        self.faults.maybe_transient_quantum(job.index)
                        totals = job.tally.run_source_moves(
                            k, job.request.source, **kw
                        )
                        disp_s += time.perf_counter() - d0
                        qattrs["moves"] = int(totals["moves"])
                        qattrs["alive"] = int(totals["alive"])
                        break
                    except InjectedKill:
                        raise
                    except Exception as e:
                        # A failed attempt still held the device — its
                        # wall time stays attributed to this job.
                        disp_s += time.perf_counter() - d0
                        if fail_t0 is None:
                            fail_t0 = time.perf_counter()
                        self._coordinator.rebind(job.tally)
                        verdict = self._coordinator.classify(e)
                        if (
                            verdict != "transient"
                            or attempt >= self.job_retries
                            or snap is None
                        ):
                            cause = (
                                "retries-exhausted"
                                if verdict == "transient" else verdict
                            )
                            qattrs["error"] = (
                                f"{type(e).__name__}: {e}"[:200]
                            )
                            # Deferred past the finally so the failing
                            # quantum's span is in the ring BEFORE the
                            # poison black box snapshots it.
                            poison = (e, cause)
                            break
                        attempt += 1
                        job.retries += 1
                        cause = (
                            "timeout"
                            if isinstance(e, DispatchTimeoutError)
                            else "transient"
                        )
                        self._retries_total.inc(cause=cause)
                        log_warn(
                            f"job {job.id} quantum failed transiently "
                            f"({e}); replaying from its snapshot "
                            f"(attempt {attempt}/{self.job_retries})"
                        )
                        # Bitwise replay anchor: the snapshot is the
                        # same payload the checkpoint subsystem
                        # persists, and the restore rebuilds every
                        # donated buffer from host copies — a
                        # half-consumed dispatch leaves nothing behind.
                        restore_state(job.tally, snap)
                        self.tracer.event(
                            "retry", cause=cause, attempt=attempt,
                            error=str(e)[:200],
                        )
                        self.recorder.record(
                            "job_retry", job=job.id, job_id=job.id,
                            shape_key=job.shape_key,
                            cause=cause, attempt=attempt,
                            error=str(e)[:200],
                        )
                        self._sleep(min(
                            self.backoff_base * 2 ** (attempt - 1),
                            self.backoff_max,
                        ))
                # Injected brownout (slow_member:M:F): stretch this
                # quantum's WALL time to ~F× its dispatch time.  Pure
                # host-side latency — device results are untouched, so
                # the job stays bitwise; only the supervisor's latency
                # SLO sees it.
                if poison is None:
                    extra = self.faults.slow_quantum_extra(
                        self.member_index, disp_s
                    )
                    if extra > 0.0:
                        self._sleep(extra)
        finally:
            # Device-time attribution survives every exit path
            # (success, poison return, injected kill unwinding).
            job.device_seconds += disp_s
            if disp_s > 0:
                self._device_seconds.inc(
                    disp_s, member=self._member_label()
                )
            self._quantum_wall_seconds.inc(
                time.perf_counter() - t0, member=self._member_label()
            )
            if job.first_dispatch_s is None and disp_s > 0:
                job.first_dispatch_s = time.perf_counter()
                self._ttfq_seconds.observe(
                    job.first_dispatch_s - job.submitted_s
                )
            self.tracer.span_record(
                "quantum", time.perf_counter() - t0,
                trace_id=job.trace_id,
                parent=SpanTracer.root_id(job.trace_id),
                job_id=job.id, span_id=qid, retries=attempt,
                device_seconds=round(disp_s, 6), **qattrs,
            )
        if poison is not None:
            self._poison(job, poison[0], cause=poison[1])
            return
        if fail_t0 is not None:
            job.recovery_seconds += time.perf_counter() - fail_t0
        job.needs_stage = False
        job.moves_done += totals["moves"]
        job.quanta += 1
        for key, v in totals.items():
            job.totals[key] += v
        job.totals["alive"] = totals["alive"]
        self._quanta_total.inc()
        # Successful quanta feed the supervisor's brownout window
        # (wall time, injected latency included).
        self.recent_quantum_seconds.append(time.perf_counter() - t0)
        self.recorder.record(
            "quantum", job=job.id, job_id=job.id,
            shape_key=job.shape_key,
            moves=int(totals["moves"]), move_total=job.moves_done,
            alive=int(totals["alive"]), retries=attempt,
            device_seconds=round(disp_s, 6),
            seconds=round(time.perf_counter() - t0, 6),
        )
        if totals["alive"] == 0 or job.moves_done >= job.request.n_moves:
            self._finish(job, "completed")
        elif self.config.convergence and job.tally.converged():
            self._finish(job, "converged")
        elif self.journal is not None:
            # Write-ahead: checkpoint the quantum boundary, THEN the
            # journal record that references it.
            self._journal_checkpoint(job)
            self._flush_journal()

    def _trace_terminal(self, job: Job, outcome: str, **attrs) -> None:
        """Emit the trace's ROOT span (deterministic id — spans from
        every process lifetime already parent onto it) and observe the
        end-to-end SLO histogram.  ``parent=NO_PARENT`` because this
        is usually emitted inside a bind whose parent the root must
        not inherit."""
        from ..obs import NO_PARENT

        e2e = max(0.0, (job.finished_s or time.perf_counter())
                  - job.submitted_s)
        self._e2e_seconds.observe(e2e)
        self.tracer.span_record(
            "job", e2e, trace_id=job.trace_id, parent=NO_PARENT,
            job_id=job.id, span_id=SpanTracer.root_id(job.trace_id),
            outcome=outcome, moves=job.moves_done,
            device_seconds=round(job.device_seconds, 6),
            preemptions=job.preemptions, retries=job.retries,
            **attrs,
        )

    def _blackbox(self, tag: str, *, reason: str,
                  meta: dict | None = None) -> str | None:
        """Dump the tracer ring as a postmortem black box (atomic
        write).  Best-effort by design — a failed dump must never take
        the serving loop (or the signal path) down with it."""
        if self.blackbox_dir is None:
            return None
        path = os.path.join(self.blackbox_dir, f"{tag}.blackbox.json")
        try:
            self.tracer.dump(path, reason=reason, meta=meta)
        except Exception as e:  # pragma: no cover - dump best-effort
            log_warn(f"black-box dump {path} failed: {e}")
            return None
        return path

    def _finish(self, job: Job, outcome: str) -> None:
        job.result = job.tally.raw_flux.copy()
        job.tally.close()
        job.tally = None
        if job in self._resident:
            self._resident.remove(job)
        job.state = DONE
        job.outcome = outcome
        job.finished_s = time.perf_counter()
        self._jobs_total.inc(outcome=outcome)
        self._job_seconds.observe(job.finished_s - job.submitted_s)
        self._trace_terminal(job, outcome)
        if self.journal is not None:
            # Results survive the process: flux first, then the journal
            # record that references it.
            job.flux_name = self.journal.write_flux(job.id, job.result)
        self.recorder.record(
            "job_done", job=job.id, job_id=job.id,
            shape_key=job.shape_key,
            outcome=outcome, moves=job.moves_done,
            preemptions=job.preemptions, retries=job.retries,
            device_seconds=round(job.device_seconds, 6),
            seconds=round(job.finished_s - job.submitted_s, 6),
        )
        # Write-ahead order: commit the terminal record (with its
        # flux) BEFORE deleting the checkpoint — a crash between the
        # two must cost a redundant file, never the finished work.
        self._flush_journal()
        self._remove_checkpoint(job)

    def _remove_checkpoint(self, job: Job) -> None:
        if job.checkpoint is not None:
            try:
                os.remove(job.checkpoint)
            except OSError:
                pass
            job.checkpoint = None
        if self.journal is not None:
            self.journal.remove_sidefiles(job.id)

    def _poison(self, job: Job, exc: BaseException, cause: str) -> None:
        """Isolate one failed job: free its device slot, mark it
        terminal with ``outcome="poisoned"``, and keep serving — every
        other resident and queued job continues bitwise-identical to a
        fault-free run (jobs are facade-isolated)."""
        if job.tally is not None:
            try:
                job.tally.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            job.tally = None
        if job in self._resident:
            self._resident.remove(job)
        job.state = DONE
        job.outcome = "poisoned"
        job.error = f"{type(exc).__name__}: {exc}"
        job.finished_s = time.perf_counter()
        self._jobs_total.inc(outcome="poisoned")
        self._job_seconds.observe(job.finished_s - job.submitted_s)
        log_warn(
            f"job {job.id} poisoned ({cause}): {job.error} — slot "
            "freed, remaining jobs unaffected"
        )
        self._trace_terminal(
            job, "poisoned", cause=cause, error=job.error[:200],
        )
        self.recorder.record(
            "job_poisoned", job=job.id, job_id=job.id,
            shape_key=job.shape_key,
            cause=cause, error=job.error[:200], moves=job.moves_done,
            retries=job.retries,
        )
        # The postmortem: the ring now holds the job's terminal root
        # span and its final quanta/retries/classify spans — dump it
        # before the journal commits the poisoned state.
        self._blackbox(
            job.id, reason=f"poisoned:{cause}",
            meta={
                "job_id": job.id, "trace_id": job.trace_id,
                "cause": cause, "error": job.error[:200],
            },
        )
        self._flush_journal()
        self._remove_checkpoint(job)

    def _preempt(self, job: Job) -> None:
        """Checkpoint-preempt one resident job (megastep boundary —
        quanta never split) and re-queue it.  Journaled schedulers
        park the checkpoint in the journal directory, where recovery
        already looks."""
        path = (
            self.journal.checkpoint_path(job.id)
            if self.journal is not None
            else os.path.join(self.checkpoint_dir, f"{job.id}.ckpt.npz")
        )
        job.tally.save_checkpoint(path)
        job.tally.close()
        job.tally = None
        job.checkpoint = path
        job.preemptions += 1
        self._resident.remove(job)
        self._preempt_total.inc()
        self.tracer.event(
            "preempted", trace_id=job.trace_id,
            parent=SpanTracer.root_id(job.trace_id), job_id=job.id,
            moves=job.moves_done, quanta=job.quanta,
        )
        self.recorder.record(
            "job_preempted", job=job.id, job_id=job.id,
            shape_key=job.shape_key,
            moves=job.moves_done, quanta=job.quanta,
        )
        self._enqueue(job)
        self._flush_journal()

    # ------------------------------------------------------------------ #
    # The scheduling loop
    # ------------------------------------------------------------------ #
    def step(self) -> bool:
        """One scheduling round: admit to capacity, run one quantum per
        resident job (round-robin fairness), then apply the preemption
        policy.  Returns True while any job is non-terminal.  A
        preemption signal landing mid-round defers to the next quantum
        boundary, where the journal flush writes consistent state.

        A DEGRADED journal (disk pressure) parks every resident and
        holds the round: the member neither admits nor dispatches
        until a fleet supervisor drains it (or an operator clears the
        disk and restarts).  Returns False then — a degraded member
        cannot make progress on its own."""
        if self.journal is not None and self.journal.degraded:
            self._park_degraded()
            return False
        self._in_step = True
        try:
            while len(self._resident) < self.max_resident:
                nxt = self._pop_next()
                if nxt is None:
                    break
                self._admit(nxt)
                self._queue_depth.set(self.queue_depth)
            for job in list(self._resident):
                if self._pending_signal is not None:
                    break
                self._quantum(job)
            if (
                self.preempt_after is not None
                and self.queue_depth > 0
                and len(self._resident) >= self.max_resident
            ):
                # Yield the slot held longest (most quanta since
                # admission, oldest first on ties) — one per round
                # keeps the policy simple and the churn bounded.
                ripe = [
                    j for j in self._resident
                    if j.quanta >= self.preempt_after
                ]
                if ripe:
                    self._preempt(max(ripe, key=lambda j: j.quanta))
            self._queue_depth.set(self.queue_depth)
        finally:
            self._in_step = False
            if self._pending_signal is not None:
                sig, self._pending_signal = self._pending_signal, None
                self._signal_flush(sig, None)
        return any(not j.terminal for j in self._jobs.values())

    def run(self, max_rounds: int = 100000) -> None:
        """Drive scheduling rounds until every submitted job is done."""
        for _ in range(max_rounds):
            if not self.step():
                return
        raise RuntimeError(
            f"scheduler did not drain within {max_rounds} rounds "
            f"({self.queue_depth} queued, {len(self._resident)} "
            "resident)"
        )

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #
    def job(self, job_id: str) -> Job:
        return self._jobs[job_id]

    def jobs(self) -> list[Job]:
        return list(self._jobs.values())

    def _jobs_json(self, query: dict | None = None) -> dict:
        """The live job table for the exporter's ``/jobs`` endpoint
        (and teleview): one JSON row per job with its trace identity
        and device-time attribution.  The table is capped at
        ``?limit=`` rows (default ``JOBS_JSON_LIMIT``), NEWEST first —
        a long-lived server accumulates terminal rows without bound
        and a scrape surface must stay scrape-sized."""
        limit = _jobs_limit(query)
        rows = sorted(
            self._jobs.values(), key=lambda j: j.index, reverse=True
        )
        return {
            "schema": FLIGHT_SCHEMA,
            "queue_depth": self.queue_depth,
            "resident": len(self._resident),
            "total_jobs": len(rows),
            "limit": limit,
            "jobs": [
                {
                    "id": j.id,
                    "index": j.index,
                    "state": j.state,
                    "outcome": j.outcome,
                    "error": j.error,
                    "shape_key": j.shape_key,
                    "n": j.n,
                    "n_moves": int(j.request.n_moves),
                    "moves_done": j.moves_done,
                    "preemptions": j.preemptions,
                    "retries": j.retries,
                    "trace_id": j.trace_id,
                    "device_seconds": round(j.device_seconds, 6),
                }
                for j in rows[:limit]
            ],
        }

    def result(self, job_id: str) -> np.ndarray:
        """Raw flux [ntet, n_groups, 2] of one finished job."""
        job = self._jobs[job_id]
        if job.result is None:
            raise RuntimeError(
                f"job {job_id} has no result (state={job.state}, "
                f"outcome={job.outcome})"
            )
        return job.result

    def stats(self) -> dict:
        """Summary for the bench / serve.py JSON."""
        outcomes = {
            s["labels"].get("outcome", ""): int(s["value"])
            for s in self._jobs_total.snapshot()["series"]
        }
        out = {
            "jobs": len(self._jobs),
            "outcomes": outcomes,
            "queue_depth": self.queue_depth,
            "resident": len(self._resident),
            "preemptions": int(
                sum(s["value"]
                    for s in self._preempt_total.snapshot()["series"])
            ),
            "retries": int(
                sum(s["value"]
                    for s in self._retries_total.snapshot()["series"])
            ),
            "recovered": self._n_recovered,
            "journal": (
                self.journal.dir if self.journal is not None else None
            ),
            "quanta": int(self._quanta_total.value()),
            "device_seconds": round(
                sum(j.device_seconds for j in self._jobs.values()), 6
            ),
            "quantum_moves": self.quantum,
            "max_resident": self.max_resident,
            "max_queued": self.max_queued,
            "classes": {
                key: sum(
                    1 for j in self._jobs.values()
                    if j.shape_key == key
                )
                for key in self._class_order
            },
            "aot": self.bank.stats() if self.bank is not None else None,
        }
        return out

    def abandon(self) -> None:
        """Crash-model teardown: release device state, signal handlers
        and the exporter WITHOUT any journal write — what a modeled
        server kill leaves behind must be exactly what the write-ahead
        journal already committed (otherwise a stale handler chained
        from a later scheduler in the same process could rewrite the
        journal with this scheduler's dead job table)."""
        for job in list(self._resident):
            if job.tally is not None:
                try:
                    job.tally.close()
                except Exception:  # pragma: no cover - best-effort
                    pass
                job.tally = None
            self._resident.remove(job)
        self._uninstall_signal_handlers()
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None

    def close(self) -> None:
        """Stop the exporter and drop any resident device state.  A
        journaled scheduler parks every resident job's checkpoint
        first, so a graceful shutdown is as resumable as a crash."""
        for job in list(self._resident):
            if job.tally is not None:
                if self.journal is not None:
                    try:
                        self._journal_checkpoint(job)
                    except Exception as e:  # pragma: no cover
                        log_warn(
                            f"close checkpoint of {job.id} failed: {e}"
                        )
                job.tally.close()
                job.tally = None
            self._resident.remove(job)
        self._flush_journal()
        # Every serving campaign leaves a postmortem artifact, crashed
        # or not — a graceful close dumps the same black box a signal
        # or a poison would have.
        self._blackbox("shutdown", reason="close")
        self._uninstall_signal_handlers()
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
