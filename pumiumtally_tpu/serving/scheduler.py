"""Shape-bucketed multi-tenant scheduler (tally-as-a-service).

A production server multiplexes many concurrent tally jobs over one
device.  This scheduler makes that a first-class loop:

  * Requests are PADDED onto the tuning shape ladder
    (``tuning/shapes.py`` — the same power-of-two ``bucket`` the
    autotuner and the AOT bank key on) and bucketed by shape class, so
    every job of a class dispatches the SAME compiled programs: one
    bank entry pair (packed init search + megastep) serves every job
    in the bucket, however many distinct request sizes arrive.
  * Up to ``max_resident`` jobs are RESIDENT at once (live device
    state: particle lanes + flux accumulator).  Admission is
    round-robin ACROSS shape classes, so one hot bucket cannot starve
    the others.
  * The device is time-sliced at MEGASTEP-K granularity: each
    scheduling round gives every resident job exactly one quantum (one
    ``run_source_moves`` call of up to ``quantum_moves`` fused moves —
    one H2D + one D2H per quantum, PR 6's contract), which is both the
    fairness grain and the natural preemption boundary.
  * Jobs finish by exhaustion (all requested moves), by DRAINING
    (every particle terminated), or by CONVERGENCE — with
    ``TallyConfig(convergence=True)`` the PR 5 ``converged()``
    statistic evicts a job early the moment its requested precision is
    reached, freeing the slot for queued work.
  * PREEMPTION reuses the PR 2 checkpoint subsystem: when queued jobs
    wait and a resident job has held its slot for ``preempt_after``
    quanta, the job is checkpointed to disk, its device state dropped,
    and it re-queues; on re-admission it restores and continues
    BITWISE-identically (megastep RNG is keyed by the persistent move
    counter, so replay equals the uninterrupted run —
    tests/test_serving.py pins it).

Observability rides the PR 1/PR 5 machinery: ``pumi_jobs_total
{outcome}``, ``pumi_queue_depth``, ``pumi_preemptions_total``, the
bank's ``pumi_aot_hits_total`` / ``pumi_aot_misses_total`` /
``pumi_compile_seconds_total`` (one shared registry), per-job and
per-quantum flight records, and the live Prometheus endpoint via
``PUMI_TPU_PROM_PORT``.
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import time

import numpy as np

from ..obs import FlightRecorder, MetricsRegistry, maybe_start_exporter
from ..tuning.shapes import bucket, classify
from ..utils.config import TallyConfig
from .bank import ProgramBank

# Job lifecycle: queued -> resident -> (preempted -> queued ->)* -> done
QUEUED, RESIDENT, PREEMPTED, DONE = (
    "queued", "resident", "preempted", "done",
)


@dataclasses.dataclass
class JobRequest:
    """One tally job: walk ``n_moves`` device-sourced moves for the
    given source particles and return the raw flux.  ``origins`` is
    [n, 3] float64 (host order); ``weights``/``groups`` default to
    ones/zeros.  ``source`` is an ``ops.source.SourceParams`` (its
    ``seed`` keys the job's RNG stream)."""

    origins: np.ndarray
    n_moves: int
    source: object | None = None
    weights: np.ndarray | None = None
    groups: np.ndarray | None = None
    job_id: str | None = None


class Job:
    """Scheduler-internal job state."""

    def __init__(self, job_id: str, request: JobRequest, n: int,
                 padded_n: int, shape_key: str):
        self.id = job_id
        self.request = request
        self.n = n
        self.padded_n = padded_n
        self.shape_key = shape_key
        self.state = QUEUED
        self.outcome: str | None = None
        self.tally = None
        self.moves_done = 0
        self.quanta = 0            # quanta run since last admission
        self.preemptions = 0
        self.needs_stage = True    # first quantum stages the lanes
        self.checkpoint: str | None = None
        self.result: np.ndarray | None = None
        self.totals: dict = collections.defaultdict(float)
        self.submitted_s = time.perf_counter()
        self.finished_s: float | None = None

    @property
    def terminal(self) -> bool:
        return self.state == DONE


@contextlib.contextmanager
def _quiet_exporter():
    """Suppress the per-tally Prometheus endpoint while the scheduler
    constructs job facades — the SCHEDULER's registry owns the scrape
    port; dozens of short-lived job tallies racing to bind it would
    only warn-spam."""
    prev = os.environ.pop("PUMI_TPU_PROM_PORT", None)
    try:
        yield
    finally:
        if prev is not None:
            os.environ["PUMI_TPU_PROM_PORT"] = prev


class TallyScheduler:
    """Multi-tenant megastep-quantum scheduler over one mesh.

    Args:
      mesh: the served TetMesh (device-resident, shared by every job).
      config: per-job TallyConfig template.  ``megastep`` is overridden
        by the resolved quantum so facade chunking and scheduler
        quanta coincide (a preemption boundary is always a megastep
        boundary).
      bank: a ProgramBank, a bank root path (constructed with the
        scheduler's registry), or None (jit path — every fresh process
        pays compile cost; the bench's aot=off baseline).
      max_resident: resident-job cap (device memory bound: each
        resident job holds padded lanes + one flux accumulator).
      quantum_moves: fused moves per scheduling quantum (default: the
        config/env/tuning-resolved megastep K).
      preempt_after: quanta a resident job may hold its slot while
        other jobs queue before it is checkpoint-preempted (None: run
        to completion).
      checkpoint_dir: where preemption checkpoints live (required when
        ``preempt_after`` is set).
    """

    def __init__(
        self,
        mesh,
        config: TallyConfig | None = None,
        *,
        bank: ProgramBank | str | None = None,
        max_resident: int = 2,
        quantum_moves: int | None = None,
        preempt_after: int | None = None,
        checkpoint_dir: str | None = None,
        registry: MetricsRegistry | None = None,
    ):
        self.mesh = mesh
        base = config or TallyConfig()
        self.quantum = int(
            quantum_moves
            if quantum_moves is not None
            else base.resolve_megastep()
        )
        if self.quantum < 1:
            raise ValueError(f"quantum_moves must be >= 1: {self.quantum}")
        # Facade chunking == scheduler quantum: run_source_moves(k)
        # with megastep=quantum runs one fused dispatch per quantum,
        # and a job interleaved with others chains bitwise-identically
        # to the same chunks run back to back.
        self.config = dataclasses.replace(base, megastep=self.quantum)
        self.max_resident = int(max_resident)
        if self.max_resident < 1:
            raise ValueError(
                f"max_resident must be >= 1: {self.max_resident}"
            )
        self.preempt_after = preempt_after
        self.checkpoint_dir = checkpoint_dir
        if preempt_after is not None and checkpoint_dir is None:
            raise ValueError(
                "preempt_after needs checkpoint_dir (preemption "
                "persists job state through the checkpoint subsystem)"
            )
        if checkpoint_dir is not None:
            # Fail at construction, not at the first mid-run
            # preemption (the atomic checkpoint writer mkstemps into
            # this directory and does not create it).
            os.makedirs(checkpoint_dir, exist_ok=True)
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.recorder = FlightRecorder()
        if isinstance(bank, str):
            bank = ProgramBank(
                bank, registry=self.registry, recorder=self.recorder
            )
        self.bank = bank
        r = self.registry
        self._jobs_total = r.counter(
            "pumi_jobs_total",
            "served tally jobs by outcome (completed: move budget "
            "exhausted or all particles terminated; converged: "
            "evicted early at the requested precision; failed)",
        )
        self._queue_depth = r.gauge(
            "pumi_queue_depth",
            "jobs waiting for a resident slot (preempted jobs "
            "re-queue and count)",
        )
        self._preempt_total = r.counter(
            "pumi_preemptions_total",
            "resident jobs checkpoint-preempted to admit queued work",
        )
        self._quanta_total = r.counter(
            "pumi_quanta_total",
            "scheduling quanta executed (one megastep-K dispatch "
            "window per resident job per round)",
        )
        self._job_seconds = r.histogram(
            "pumi_job_seconds",
            "wall seconds from job submission to completion",
        )
        # Per-class FIFO queues + a rotation pointer: admission takes
        # one job per class in turn, so a burst in one shape bucket
        # cannot starve the others.
        self._queues: dict[str, collections.deque] = {}
        self._class_order: list[str] = []
        self._next_class = 0
        self._resident: list[Job] = []
        self._jobs: dict[str, Job] = {}
        self._n_submitted = 0
        self._exporter = maybe_start_exporter(self.registry)

    # ------------------------------------------------------------------ #
    # Submission
    # ------------------------------------------------------------------ #
    def submit(self, request: JobRequest) -> str:
        """Enqueue one job; returns its id.  The job is padded onto the
        shape ladder here — its bucket decides which queue it joins
        and which bank entries will serve it."""
        origins = np.asarray(request.origins, np.float64).reshape(-1, 3)
        n = origins.shape[0]
        if n < 1:
            raise ValueError("a job needs at least one particle")
        if request.n_moves < 1:
            raise ValueError(f"n_moves must be >= 1: {request.n_moves}")
        for name, arr in (
            ("weights", request.weights), ("groups", request.groups),
        ):
            if arr is not None and np.asarray(arr).reshape(-1).size != n:
                # A silent [:n] truncation would scale the flux by the
                # wrong source weights — reject the mismatch up front.
                raise ValueError(
                    f"{name} has {np.asarray(arr).reshape(-1).size} "
                    f"entries for {n} particles — per-lane arrays must "
                    "match the request's UNPADDED particle count"
                )
        padded_n = bucket(n)
        cfg = self.config
        shape = classify(
            self.mesh.ntet, padded_n, cfg.n_groups, cfg.dtype,
            getattr(self.mesh, "geo20", None) is not None,
        )
        job_id = request.job_id or f"job-{self._n_submitted:05d}"
        if job_id in self._jobs:
            raise ValueError(f"duplicate job id {job_id!r}")
        self._n_submitted += 1
        job = Job(job_id, request, n, padded_n, shape.key())
        self._jobs[job_id] = job
        self._enqueue(job)
        self.recorder.record(
            "job_submitted", job=job_id, shape_key=job.shape_key,
            n=n, padded_n=padded_n, n_moves=int(request.n_moves),
        )
        return job_id

    def _enqueue(self, job: Job) -> None:
        q = self._queues.get(job.shape_key)
        if q is None:
            q = self._queues[job.shape_key] = collections.deque()
            self._class_order.append(job.shape_key)
        q.append(job)
        job.state = QUEUED if job.checkpoint is None else PREEMPTED
        self._queue_depth.set(self.queue_depth)

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _pop_next(self) -> Job | None:
        """Round-robin across shape-class queues."""
        if not self._class_order:
            return None
        for _ in range(len(self._class_order)):
            key = self._class_order[
                self._next_class % len(self._class_order)
            ]
            self._next_class += 1
            q = self._queues[key]
            if q:
                return q.popleft()
        return None

    # ------------------------------------------------------------------ #
    # Padding helpers
    # ------------------------------------------------------------------ #
    def _padded_inputs(self, job: Job):
        """Host arrays padded to the shape bucket: pad lanes sit at the
        first request position with zero weight and alive=False — they
        are initialized (parent-element search needs a valid position)
        but never walk, never score, and never sample."""
        req, n, N = job.request, job.n, job.padded_n
        origins = np.asarray(req.origins, np.float64).reshape(-1, 3)
        pad = np.broadcast_to(origins[0], (N - n, 3))
        origins_p = np.concatenate([origins, pad], axis=0)
        w = (
            np.ones(n) if req.weights is None
            else np.asarray(req.weights, np.float64).reshape(-1)[:n]
        )
        g = (
            np.zeros(n, np.int32) if req.groups is None
            else np.asarray(req.groups, np.int32).reshape(-1)[:n]
        )
        weights_p = np.concatenate([w, np.zeros(N - n)])
        groups_p = np.concatenate([g, np.zeros(N - n, np.int32)])
        alive_p = np.concatenate(
            [np.ones(n, bool), np.zeros(N - n, bool)]
        )
        return origins_p, weights_p, groups_p, alive_p

    # ------------------------------------------------------------------ #
    # Residency
    # ------------------------------------------------------------------ #
    def _admit(self, job: Job) -> None:
        from ..api import PumiTally

        with _quiet_exporter():
            tally = PumiTally(
                self.mesh, job.padded_n, self.config,
                program_bank=self.bank,
            )
        if job.checkpoint is not None:
            # Preempted job: restore the exact megastep boundary it was
            # parked at — the move counter keys the RNG stream, so the
            # continuation is bitwise the uninterrupted run.
            tally.restore_checkpoint(job.checkpoint)
            job.needs_stage = False
        else:
            origins_p, _, _, _ = self._padded_inputs(job)
            tally.initialize_particle_location(
                origins_p.reshape(-1).copy()
            )
            job.needs_stage = True
        job.tally = tally
        job.quanta = 0
        job.state = RESIDENT
        self._resident.append(job)
        self.recorder.record(
            "job_admitted", job=job.id, shape_key=job.shape_key,
            restored=job.checkpoint is not None,
        )

    def _quantum(self, job: Job) -> None:
        """One scheduling quantum: up to ``quantum_moves`` fused moves
        for one resident job, then the completion checks."""
        remaining = job.request.n_moves - job.moves_done
        k = min(self.quantum, remaining)
        kw = {}
        if job.needs_stage:
            _, w, g, alive = self._padded_inputs(job)
            kw = dict(weights=w, groups=g, alive=alive)
            job.needs_stage = False
        t0 = time.perf_counter()
        totals = job.tally.run_source_moves(
            k, job.request.source, **kw
        )
        job.moves_done += totals["moves"]
        job.quanta += 1
        for key, v in totals.items():
            job.totals[key] += v
        job.totals["alive"] = totals["alive"]
        self._quanta_total.inc()
        self.recorder.record(
            "quantum", job=job.id, shape_key=job.shape_key,
            moves=int(totals["moves"]), move_total=job.moves_done,
            alive=int(totals["alive"]),
            seconds=round(time.perf_counter() - t0, 6),
        )
        if totals["alive"] == 0 or job.moves_done >= job.request.n_moves:
            self._finish(job, "completed")
        elif self.config.convergence and job.tally.converged():
            self._finish(job, "converged")

    def _finish(self, job: Job, outcome: str) -> None:
        job.result = job.tally.raw_flux.copy()
        job.tally.close()
        job.tally = None
        if job.checkpoint is not None:
            try:
                os.remove(job.checkpoint)
            except OSError:
                pass
            job.checkpoint = None
        if job in self._resident:
            self._resident.remove(job)
        job.state = DONE
        job.outcome = outcome
        job.finished_s = time.perf_counter()
        self._jobs_total.inc(outcome=outcome)
        self._job_seconds.observe(job.finished_s - job.submitted_s)
        self.recorder.record(
            "job_done", job=job.id, shape_key=job.shape_key,
            outcome=outcome, moves=job.moves_done,
            preemptions=job.preemptions,
            seconds=round(job.finished_s - job.submitted_s, 6),
        )

    def _preempt(self, job: Job) -> None:
        """Checkpoint-preempt one resident job (megastep boundary —
        quanta never split) and re-queue it."""
        path = os.path.join(
            self.checkpoint_dir, f"{job.id}.ckpt.npz"
        )
        job.tally.save_checkpoint(path)
        job.tally.close()
        job.tally = None
        job.checkpoint = path
        job.preemptions += 1
        self._resident.remove(job)
        self._preempt_total.inc()
        self.recorder.record(
            "job_preempted", job=job.id, shape_key=job.shape_key,
            moves=job.moves_done, quanta=job.quanta,
        )
        self._enqueue(job)

    # ------------------------------------------------------------------ #
    # The scheduling loop
    # ------------------------------------------------------------------ #
    def step(self) -> bool:
        """One scheduling round: admit to capacity, run one quantum per
        resident job (round-robin fairness), then apply the preemption
        policy.  Returns True while any job is non-terminal."""
        while len(self._resident) < self.max_resident:
            nxt = self._pop_next()
            if nxt is None:
                break
            self._admit(nxt)
            self._queue_depth.set(self.queue_depth)
        for job in list(self._resident):
            self._quantum(job)
        if (
            self.preempt_after is not None
            and self.queue_depth > 0
            and len(self._resident) >= self.max_resident
        ):
            # Yield the slot held longest (most quanta since admission,
            # oldest first on ties) — one per round keeps the policy
            # simple and the churn bounded.
            ripe = [
                j for j in self._resident
                if j.quanta >= self.preempt_after
            ]
            if ripe:
                self._preempt(max(ripe, key=lambda j: j.quanta))
        self._queue_depth.set(self.queue_depth)
        return any(not j.terminal for j in self._jobs.values())

    def run(self, max_rounds: int = 100000) -> None:
        """Drive scheduling rounds until every submitted job is done."""
        for _ in range(max_rounds):
            if not self.step():
                return
        raise RuntimeError(
            f"scheduler did not drain within {max_rounds} rounds "
            f"({self.queue_depth} queued, {len(self._resident)} "
            "resident)"
        )

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #
    def job(self, job_id: str) -> Job:
        return self._jobs[job_id]

    def jobs(self) -> list[Job]:
        return list(self._jobs.values())

    def result(self, job_id: str) -> np.ndarray:
        """Raw flux [ntet, n_groups, 2] of one finished job."""
        job = self._jobs[job_id]
        if job.result is None:
            raise RuntimeError(
                f"job {job_id} is not finished (state={job.state})"
            )
        return job.result

    def stats(self) -> dict:
        """Summary for the bench / serve.py JSON."""
        outcomes = {
            s["labels"].get("outcome", ""): int(s["value"])
            for s in self._jobs_total.snapshot()["series"]
        }
        out = {
            "jobs": len(self._jobs),
            "outcomes": outcomes,
            "queue_depth": self.queue_depth,
            "resident": len(self._resident),
            "preemptions": int(
                sum(s["value"]
                    for s in self._preempt_total.snapshot()["series"])
            ),
            "quanta": int(self._quanta_total.value()),
            "quantum_moves": self.quantum,
            "max_resident": self.max_resident,
            "classes": {
                key: sum(
                    1 for j in self._jobs.values()
                    if j.shape_key == key
                )
                for key in self._class_order
            },
            "aot": self.bank.stats() if self.bank is not None else None,
        }
        return out

    def close(self) -> None:
        """Stop the exporter and drop any resident device state."""
        for job in list(self._resident):
            if job.tally is not None:
                job.tally.close()
                job.tally = None
            self._resident.remove(job)
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
