"""Saturation workload driver shared by scripts/serve.py and bench.py.

One definition of the synthetic many-job workload — N jobs spread
round-robin over a ladder of request sizes (each a distinct shape
class after padding), every job with its own RNG seed — so the server
entrypoint's ``--demo`` mode and the bench's ``BENCH_SERVE`` probe
drive the SAME scheduler with the SAME job mix and their
``jobs_per_sec`` numbers are comparable.
"""
from __future__ import annotations

import time

import numpy as np

from ..resilience.faultinject import InjectedKill


def synthetic_requests(
    mesh,
    n_jobs: int,
    *,
    class_sizes: tuple = (96, 192),
    n_moves: int = 8,
    seed: int = 0,
) -> list:
    """Build ``n_jobs`` JobRequests cycling over ``class_sizes``
    particle counts (each size pads to its own shape bucket).  Origins
    are element centroids sampled per-job; each job gets its own
    source seed, so jobs are statistically independent streams."""
    from ..ops.source import SourceParams
    from .scheduler import JobRequest

    centroids = np.asarray(mesh.centroids(), np.float64)
    out = []
    for i in range(n_jobs):
        n = int(class_sizes[i % len(class_sizes)])
        rng = np.random.default_rng([seed, i])
        elems = rng.integers(0, mesh.ntet, n)
        out.append(
            JobRequest(
                origins=centroids[elems],
                n_moves=int(n_moves),
                source=SourceParams(seed=seed + 1000 + i),
                job_id=f"sat-{i:04d}",
            )
        )
    return out


def run_saturation(
    mesh,
    config=None,
    *,
    bank=None,
    n_jobs: int = 8,
    class_sizes: tuple = (96, 192),
    n_moves: int = 8,
    seed: int = 0,
    max_resident: int = 2,
    quantum_moves: int | None = None,
    preempt_after: int | None = None,
    checkpoint_dir: str | None = None,
    max_queued: int | None = None,
    job_retries: int = 2,
    quantum_deadline_s: float | None = None,
    journal_dir: str | None = None,
    blackbox_dir: str | None = None,
    resume: bool = False,
    faults=None,
) -> dict:
    """Submit the synthetic workload, drain the scheduler, and return
    the measurement record: ``jobs_per_sec`` over the drain window
    (submission is instant; the window prices scheduling + dispatch),
    the scheduler/bank counter summary, and per-job rows.

    ``resume=True`` with a populated ``journal_dir`` recovers the
    previous process's job table first (``TallyScheduler.recover``)
    and only submits fleet members the journal does not already know —
    the restart path of a killed server re-runs the SAME call and
    loses nothing."""
    import os

    from .journal import JOURNAL_FILE
    from .scheduler import TallyScheduler

    kwargs = dict(
        bank=bank,
        max_resident=max_resident,
        quantum_moves=quantum_moves,
        preempt_after=preempt_after,
        checkpoint_dir=checkpoint_dir,
        max_queued=max_queued,
        job_retries=job_retries,
        quantum_deadline_s=quantum_deadline_s,
        blackbox_dir=blackbox_dir,
        faults=faults,
    )
    if (
        resume
        and journal_dir is not None
        and os.path.exists(os.path.join(journal_dir, JOURNAL_FILE))
    ):
        sched = TallyScheduler.recover(journal_dir, mesh, config, **kwargs)
    else:
        sched = TallyScheduler(
            mesh, config, journal_dir=journal_dir, **kwargs
        )
    crashed = False
    try:
        requests = synthetic_requests(
            mesh, n_jobs, class_sizes=class_sizes, n_moves=n_moves,
            seed=seed,
        )
        known = {j.id for j in sched.jobs()}
        ids = [
            r.job_id if r.job_id in known else sched.submit(r)
            for r in requests
        ]
        t0 = time.perf_counter()
        try:
            sched.run()
        except InjectedKill:
            # A modeled server crash: skip close() and its graceful
            # checkpoint parking — recovery must work from the
            # write-ahead journal ALONE (the chaos-campaign contract).
            # abandon() still releases device state and the signal
            # handlers, which a real dead process would not hold.
            crashed = True
            sched.abandon()
            raise
        elapsed = time.perf_counter() - t0
        stats = sched.stats()
        per_job = [
            {
                "job": j.id,
                "shape_key": j.shape_key,
                "outcome": j.outcome,
                "moves": j.moves_done,
                "preemptions": j.preemptions,
                "retries": j.retries,
                "recovery_seconds": round(j.recovery_seconds, 4),
                "device_seconds": round(j.device_seconds, 4),
                "trace_id": j.trace_id,
                "error": j.error,
            }
            for j in (sched.job(i) for i in ids)
        ]
        return {
            "n_jobs": n_jobs,
            "class_sizes": list(class_sizes),
            "n_moves": n_moves,
            "elapsed_s": round(elapsed, 4),
            "jobs_per_sec": round(n_jobs / elapsed, 3),
            "scheduler": stats,
            "per_job": per_job,
            # Raw flux per job id — callers that verify bitwise parity
            # (tests, the bench's off-vs-warm check) read these; JSON
            # writers drop the arrays first.  Poisoned/rejected jobs
            # have no flux and no entry.
            "results": {
                i: sched.result(i) for i in ids
                if sched.job(i).result is not None
            },
        }
    finally:
        if not crashed:
            sched.close()


def run_fleet_saturation(
    mesh,
    config=None,
    *,
    fleet_dir: str,
    n_members: int = 2,
    port: int = 0,
    bank=None,
    n_jobs: int = 8,
    class_sizes: tuple = (96, 192),
    n_moves: int = 8,
    seed: int = 0,
    resume: bool = False,
    faults=None,
    absorb_member_kills: bool = False,
    via_http: bool = True,
    **member_kwargs,
) -> dict:
    """The fleet-path twin of ``run_saturation``: same synthetic
    workload, but submitted through the NETWORK ingress (one POST per
    job, each with an idempotency key) into a ``FleetRouter`` spread
    over ``n_members`` schedulers, then drained.

    Every submission carries ``idempotency_key="key-<job_id>"``, so
    ``resume=True`` (the restart path of a killed router) simply
    re-POSTs the whole workload — the journaled key map in FLEET.json
    dedups every job the previous process already accepted, and the
    re-POST storm is itself the idempotency proof the chaos campaign
    leans on.  ``via_http=False`` calls ``router.submit`` directly
    (the bench's probe, where HTTP overhead would pollute
    ``jobs_per_sec``)."""
    import json as _json
    import os
    import urllib.request

    from .fleet import FLEET_FILE, FleetRouter
    from .gateway import TallyGateway
    from .journal import request_to_json

    kwargs = dict(
        bank=bank,
        faults=faults,
        absorb_member_kills=absorb_member_kills,
        **member_kwargs,
    )
    if resume and os.path.exists(os.path.join(fleet_dir, FLEET_FILE)):
        router = FleetRouter.recover(fleet_dir, mesh, config, **kwargs)
    else:
        router = FleetRouter(
            mesh, config, fleet_dir=fleet_dir, n_members=n_members,
            **kwargs,
        )
    gateway = TallyGateway(router, port=port) if via_http else None
    crashed = False
    try:
        requests = synthetic_requests(
            mesh, n_jobs, class_sizes=class_sizes, n_moves=n_moves,
            seed=seed,
        )
        ids = []
        for r in requests:
            key = f"key-{r.job_id}"
            if gateway is not None:
                body = _json.dumps(
                    dict(request_to_json(r), idempotency_key=key)
                ).encode()
                with urllib.request.urlopen(
                    urllib.request.Request(
                        f"{gateway.url}/submit", data=body,
                        method="POST",
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=30,
                ) as resp:
                    ids.append(_json.loads(resp.read())["job"])
            else:
                ids.append(router.submit(r, idempotency_key=key))
        t0 = time.perf_counter()
        try:
            router.run()
        except InjectedKill:
            # A modeled ROUTER crash (no member absorbed it): recovery
            # must work from FLEET.json + the member journals alone —
            # abandon() releases device state without journal writes,
            # like run_saturation's crash path.
            crashed = True
            router.abandon()
            raise
        elapsed = time.perf_counter() - t0
        stats = router.stats()
        per_job = [
            {
                "job": j.id,
                "shape_key": j.shape_key,
                "outcome": j.outcome,
                "member": router.member_of(j.id),
                "moves": j.moves_done,
                "preemptions": j.preemptions,
                "retries": j.retries,
                "device_seconds": round(j.device_seconds, 4),
                "trace_id": j.trace_id,
                "error": j.error,
            }
            for j in (router.job(i) for i in ids)
        ]
        return {
            "n_jobs": n_jobs,
            "n_members": stats["members"],
            "class_sizes": list(class_sizes),
            "n_moves": n_moves,
            "elapsed_s": round(elapsed, 4),
            "jobs_per_sec": round(n_jobs / elapsed, 3),
            "via_http": gateway is not None,
            "fleet": stats,
            "per_job": per_job,
            # Raw flux per job id (bitwise-parity consumers; JSON
            # writers drop the arrays first).
            "results": {
                i: router.result(i) for i in ids
                if router.job(i).result is not None
            },
        }
    finally:
        if gateway is not None:
            gateway.stop()
        if not crashed:
            router.close()
