"""Saturation workload driver shared by scripts/serve.py and bench.py.

One definition of the synthetic many-job workload — N jobs spread
round-robin over a ladder of request sizes (each a distinct shape
class after padding), every job with its own RNG seed — so the server
entrypoint's ``--demo`` mode and the bench's ``BENCH_SERVE`` probe
drive the SAME scheduler with the SAME job mix and their
``jobs_per_sec`` numbers are comparable.
"""
from __future__ import annotations

import time

import numpy as np

from ..resilience.faultinject import InjectedKill


def synthetic_requests(
    mesh,
    n_jobs: int,
    *,
    class_sizes: tuple = (96, 192),
    n_moves: int = 8,
    seed: int = 0,
) -> list:
    """Build ``n_jobs`` JobRequests cycling over ``class_sizes``
    particle counts (each size pads to its own shape bucket).  Origins
    are element centroids sampled per-job; each job gets its own
    source seed, so jobs are statistically independent streams."""
    from ..ops.source import SourceParams
    from .scheduler import JobRequest

    centroids = np.asarray(mesh.centroids(), np.float64)
    out = []
    for i in range(n_jobs):
        n = int(class_sizes[i % len(class_sizes)])
        rng = np.random.default_rng([seed, i])
        elems = rng.integers(0, mesh.ntet, n)
        out.append(
            JobRequest(
                origins=centroids[elems],
                n_moves=int(n_moves),
                source=SourceParams(seed=seed + 1000 + i),
                job_id=f"sat-{i:04d}",
            )
        )
    return out


def run_saturation(
    mesh,
    config=None,
    *,
    bank=None,
    n_jobs: int = 8,
    class_sizes: tuple = (96, 192),
    n_moves: int = 8,
    seed: int = 0,
    max_resident: int = 2,
    quantum_moves: int | None = None,
    preempt_after: int | None = None,
    checkpoint_dir: str | None = None,
    max_queued: int | None = None,
    job_retries: int = 2,
    quantum_deadline_s: float | None = None,
    journal_dir: str | None = None,
    blackbox_dir: str | None = None,
    resume: bool = False,
    faults=None,
) -> dict:
    """Submit the synthetic workload, drain the scheduler, and return
    the measurement record: ``jobs_per_sec`` over the drain window
    (submission is instant; the window prices scheduling + dispatch),
    the scheduler/bank counter summary, and per-job rows.

    ``resume=True`` with a populated ``journal_dir`` recovers the
    previous process's job table first (``TallyScheduler.recover``)
    and only submits fleet members the journal does not already know —
    the restart path of a killed server re-runs the SAME call and
    loses nothing."""
    import os

    from .journal import JOURNAL_FILE
    from .scheduler import TallyScheduler

    kwargs = dict(
        bank=bank,
        max_resident=max_resident,
        quantum_moves=quantum_moves,
        preempt_after=preempt_after,
        checkpoint_dir=checkpoint_dir,
        max_queued=max_queued,
        job_retries=job_retries,
        quantum_deadline_s=quantum_deadline_s,
        blackbox_dir=blackbox_dir,
        faults=faults,
    )
    if (
        resume
        and journal_dir is not None
        and os.path.exists(os.path.join(journal_dir, JOURNAL_FILE))
    ):
        sched = TallyScheduler.recover(journal_dir, mesh, config, **kwargs)
    else:
        sched = TallyScheduler(
            mesh, config, journal_dir=journal_dir, **kwargs
        )
    crashed = False
    try:
        requests = synthetic_requests(
            mesh, n_jobs, class_sizes=class_sizes, n_moves=n_moves,
            seed=seed,
        )
        known = {j.id for j in sched.jobs()}
        ids = [
            r.job_id if r.job_id in known else sched.submit(r)
            for r in requests
        ]
        t0 = time.perf_counter()
        try:
            sched.run()
        except InjectedKill:
            # A modeled server crash: skip close() and its graceful
            # checkpoint parking — recovery must work from the
            # write-ahead journal ALONE (the chaos-campaign contract).
            # abandon() still releases device state and the signal
            # handlers, which a real dead process would not hold.
            crashed = True
            sched.abandon()
            raise
        elapsed = time.perf_counter() - t0
        stats = sched.stats()
        per_job = [
            {
                "job": j.id,
                "shape_key": j.shape_key,
                "outcome": j.outcome,
                "moves": j.moves_done,
                "preemptions": j.preemptions,
                "retries": j.retries,
                "recovery_seconds": round(j.recovery_seconds, 4),
                "device_seconds": round(j.device_seconds, 4),
                "trace_id": j.trace_id,
                "error": j.error,
            }
            for j in (sched.job(i) for i in ids)
        ]
        return {
            "n_jobs": n_jobs,
            "class_sizes": list(class_sizes),
            "n_moves": n_moves,
            "elapsed_s": round(elapsed, 4),
            "jobs_per_sec": round(n_jobs / elapsed, 3),
            "scheduler": stats,
            "per_job": per_job,
            # Raw flux per job id — callers that verify bitwise parity
            # (tests, the bench's off-vs-warm check) read these; JSON
            # writers drop the arrays first.  Poisoned/rejected jobs
            # have no flux and no entry.
            "results": {
                i: sched.result(i) for i in ids
                if sched.job(i).result is not None
            },
        }
    finally:
        if not crashed:
            sched.close()
