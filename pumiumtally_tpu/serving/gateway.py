"""Network ingress for the serving fleet: idempotent HTTP job intake.

The missing half of ROADMAP item 3's "a request is an in-process
Python call": ``TallyGateway`` puts a plain-stdlib HTTP server (the
``obs/exporter.py`` ThreadingHTTPServer pattern — no dependencies,
daemon threads, dies with the process) in front of a ``FleetRouter``:

  * ``POST /submit`` — body is the ``serving/journal.py`` request wire
    format (``request_to_json``: origins/n_moves/weights/groups/
    source/job_id — float64 payloads survive bitwise through json's
    repr round-trip) plus an optional ``idempotency_key``.  The key is
    journaled in FLEET.json BEFORE the job is accepted onto any member
    (``FleetRouter.submit``, protolint-verified), so a client that
    times out and retries the POST gets the SAME job id back and never
    starts a second execution.  An optional ``traceparent`` header
    (W3C ``00-<32 hex>-<16 hex>-<2 hex>``, or a bare 16-32 hex trace
    id) makes the job JOIN the caller's distributed trace instead of
    minting its own root; a malformed header is a 400 — a client that
    tried to join a trace deserves a refusal, not a silent fork.
    Answers ``{"job": id, "trace_id": ...}`` (the dedup path returns
    the ORIGINAL submission's trace, matching the job that runs).
  * ``GET /status/<job>`` — state/outcome/moves/member/trace identity.
  * ``GET /result/<job>`` — the finished flux, bitwise: dtype + shape
    + base64 of the raw little-endian buffer (json floats would be
    fine too, but base64 is unambiguous and 4x smaller).  409 while
    the job has no result yet.
  * ``GET /progress/<job>?since=N&timeout=S`` — streams the job's
    flight records as JSONL, one line per record, polling the fleet's
    shared recorder until the job is terminal (or ``timeout`` seconds
    pass).  Every row carries the job's ``trace_id``, so a tailing
    client can correlate the stream with the span log (TRACE.jsonl /
    teleview) without a second lookup.  Served with HTTP/1.0
    connection-close framing — no Content-Length, the closed socket
    ends the stream — so ``curl`` tails live progress with zero
    client smarts.
  * ``POST /cancel`` — body ``{"job": id}``; answers
    ``{"job": id, "cancelled": bool}`` (false: already terminal).
  * ``GET /healthz`` — liveness for load balancers.

Every path that embeds a job id validates it with the journal's
``check_job_id`` BEFORE any filesystem name could be formed from it —
a path-unsafe id is a 400, never a file probe.  Malformed JSON and
validation failures are 400s with the reason in the body; unknown jobs
are 404s; unknown paths answer 404 naming the valid endpoints (the
exporter's teach-don't-stonewall rule).

Request-level robustness (the self-healing-fleet PR's ingress half):

  * Every connection gets a per-request READ/WRITE socket deadline
    (``request_timeout_s`` → the handler's ``timeout``, applied by
    socketserver's ``setup()`` via ``settimeout``): a client that
    stalls mid-body or stops draining a response times the SOCKET out
    instead of wedging a daemon handler thread forever.  The timed-out
    connection is closed, never answered partially.
  * Backpressure is a 503 WITH retry guidance: when every healthy
    member is at its admission bound (``FleetRouter.backpressured``),
    ``POST /submit`` answers 503 + ``Retry-After`` and a body carrying
    ``retry_after_s``/``retry_jitter_s`` — clients sleep
    ``retry_after_s + uniform(0, retry_jitter_s)`` and retry with the
    SAME idempotency key, so a rejected burst decorrelates instead of
    hot-looping in lockstep.  The check runs BEFORE ``router.submit``
    journals anything: a rejected request burns no idempotency key.
"""
from __future__ import annotations

import base64
import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.log import log_info
from .journal import check_job_id, request_from_json

#: Routes the 404 body teaches (the gateway's whole surface).
ROUTES = (
    "POST /submit", "POST /cancel", "GET /status/<job>",
    "GET /result/<job>", "GET /progress/<job>", "GET /healthz",
)

# W3C trace-context header (version-traceid-parentid-flags), or the
# bare trace id our own SpanTracer mints (16 hex) / other tracers'
# 32-hex ids.  The trace id is all the fleet keeps — span parentage
# inside the job is ours, the caller only needs the join key.
_W3C_TRACEPARENT = re.compile(
    r"00-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}"
)
_BARE_TRACE_ID = re.compile(r"[0-9a-f]{16,32}")


def parse_traceparent(header: str | None) -> str | None:
    """The caller's trace id from a ``traceparent`` header, or None
    when the header is absent/blank (the job mints its own trace).
    Raises ValueError on a malformed non-empty header."""
    if header is None or not header.strip():
        return None
    text = header.strip().lower()
    m = _W3C_TRACEPARENT.fullmatch(text)
    if m is not None:
        return m.group(1)
    if _BARE_TRACE_ID.fullmatch(text):
        return text
    raise ValueError(
        f"traceparent {header!r} is neither W3C "
        "00-<32 hex>-<16 hex>-<2 hex> nor a bare 16-32 hex trace id"
    )


class TallyGateway:
    """One HTTP ingress bound to one ``FleetRouter`` (module docstring
    API).  Handler threads and the router's scheduling loop serialize
    on the router's lock — the gateway holds no job state of its own,
    so everything a handler answers comes from (journaled) router
    state."""

    def __init__(self, router, port: int = 0, host: str = "127.0.0.1",
                 *, request_timeout_s: float = 30.0,
                 retry_after_s: float = 1.0):
        if float(request_timeout_s) <= 0:
            raise ValueError(
                f"request_timeout_s must be > 0: {request_timeout_s}"
            )
        if float(retry_after_s) <= 0:
            raise ValueError(
                f"retry_after_s must be > 0: {retry_after_s}"
            )
        self.router = router
        self.request_timeout_s = float(request_timeout_s)
        self.retry_after_s = float(retry_after_s)
        gateway = self

        class _Handler(BaseHTTPRequestHandler):
            # socketserver's setup() applies this as the connection's
            # settimeout — one deadline covering every blocking read
            # AND write on the socket (module docstring).
            timeout = self.request_timeout_s

            def do_POST(self):  # noqa: N802 — http.server API
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/submit":
                        self._answer(gateway._submit(
                            self._body(),
                            traceparent=self.headers.get("traceparent"),
                        ))
                    elif path == "/cancel":
                        self._answer(gateway._cancel(self._body()))
                    else:
                        self._unknown(path)
                except OSError:
                    # Stalled or vanished client (socket timeout,
                    # reset): drop the connection; there is nobody
                    # left to answer, and the handler thread must not
                    # wedge (TimeoutError is an OSError here).
                    self.close_connection = True

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    path, _, query = self.path.partition("?")
                    if path == "/healthz":
                        self._answer((200, {"ok": True}))
                    elif path.startswith("/status/"):
                        self._answer(
                            gateway._status(path[len("/status/"):])
                        )
                    elif path.startswith("/result/"):
                        self._answer(
                            gateway._result(path[len("/result/"):])
                        )
                    elif path.startswith("/progress/"):
                        self._stream(path[len("/progress/"):], query)
                    else:
                        self._unknown(path)
                except OSError:
                    self.close_connection = True

            # -- plumbing ---------------------------------------- #
            def _body(self) -> bytes:
                length = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(length)

            def _answer(self, status_payload) -> None:
                status, payload, *rest = status_payload
                headers = rest[0] if rest else {}
                body = (
                    json.dumps(payload, sort_keys=True) + "\n"
                ).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in headers.items():
                    self.send_header(name, str(value))
                self.end_headers()
                self.wfile.write(body)

            def _unknown(self, path: str) -> None:
                self._answer((404, {
                    "error": f"unknown path {path!r}",
                    "routes": list(ROUTES),
                }))

            def _stream(self, job_id: str, query: str) -> None:
                """JSONL progress stream (module docstring framing:
                HTTP/1.0 connection-close, so no Content-Length and
                the socket end IS the end of stream)."""
                params = dict(
                    kv.split("=", 1)
                    for kv in query.split("&") if "=" in kv
                )
                try:
                    check_job_id(job_id)
                except ValueError as e:
                    self._answer((400, {"error": str(e)}))
                    return
                try:
                    since = int(params.get("since", -1))
                    timeout = float(params.get("timeout", 30.0))
                except ValueError as e:
                    self._answer((400, {"error": f"bad query: {e}"}))
                    return
                try:
                    records, terminal = gateway.router.progress(
                        job_id, since
                    )
                except KeyError:
                    self._answer(
                        (404, {"error": f"unknown job {job_id!r}"})
                    )
                    return
                try:
                    trace_id = gateway.router.job(job_id).trace_id
                except KeyError:  # pragma: no cover - races a drop
                    trace_id = None
                self.send_response(200)
                self.send_header(
                    "Content-Type", "application/jsonl"
                )
                self.end_headers()
                deadline = time.monotonic() + timeout
                while True:
                    for rec in records:
                        row = dict(rec)
                        row.setdefault("trace_id", trace_id)
                        self.wfile.write(
                            (json.dumps(row, sort_keys=True,
                                        default=str) + "\n").encode()
                        )
                        since = max(since, rec.get("seq", since))
                    self.wfile.flush()
                    if terminal or time.monotonic() > deadline:
                        return
                    time.sleep(0.05)
                    try:
                        records, terminal = gateway.router.progress(
                            job_id, since
                        )
                    except KeyError:  # pragma: no cover - races a drop
                        return

            def log_message(self, *args):  # requests are not log events
                pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        # stop() races between FleetRouter teardown paths and test
        # finalizers; the flag flip must be atomic so exactly one
        # caller runs the shutdown sequence (astlint PUMI007).
        self._stop_lock = threading.Lock()
        self._stopped = False  # guarded by: self._stop_lock
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="pumi-tally-gateway",
            daemon=True,
        )
        self._thread.start()
        log_info(f"tally gateway serving at {self.url}")

    # ------------------------------------------------------------------ #
    # Route handlers (return (status, json-able payload))
    # ------------------------------------------------------------------ #
    def _submit(self, body: bytes, traceparent: str | None = None):
        try:
            caller_trace = parse_traceparent(traceparent)
        except ValueError as e:
            return 400, {"error": str(e)}
        try:
            payload = json.loads(body.decode() or "null")
        except ValueError as e:
            return 400, {"error": f"body is not JSON: {e}"}
        if not isinstance(payload, dict):
            return 400, {"error": "body must be a JSON object"}
        key = payload.pop("idempotency_key", None)
        if key is not None and not isinstance(key, str):
            return 400, {"error": "idempotency_key must be a string"}
        # Path-unsafe ids are refused BEFORE request_from_json could
        # hand them anywhere a filesystem name is formed.
        job_id = payload.get("job_id")
        if job_id is not None:
            try:
                check_job_id(str(job_id))
            except ValueError as e:
                return 400, {"error": str(e)}
        try:
            request = request_from_json(payload)
        except (KeyError, TypeError, ValueError) as e:
            return 400, {
                "error": f"bad request: {type(e).__name__}: {e}"
            }
        # The caller's traceparent wins only when the wire request did
        # not already carry a trace id (a retried submit round-trips
        # the original identity through the body).
        if caller_trace is not None and request.trace_id is None:
            request.trace_id = caller_trace
        # Backpressure answers BEFORE router.submit journals anything:
        # a 503'd request must not burn an idempotency key on a job no
        # member would admit (module docstring).
        if self.router.backpressured():
            return self._too_busy(
                "fleet backpressured: every healthy member is at "
                "its admission bound"
            )
        try:
            accepted = self.router.submit(
                request, idempotency_key=key
            )
        except ValueError as e:
            return 400, {"error": str(e)}
        except RuntimeError as e:
            # No alive member to place on (mid-eviction trough): the
            # request is retryable, not wrong.
            return self._too_busy(str(e))
        try:
            trace_id = self.router.job(accepted).trace_id
        except KeyError:  # pragma: no cover - races an instant drop
            trace_id = caller_trace
        return 200, {"job": accepted, "trace_id": trace_id}

    def _too_busy(self, reason: str):
        """503 + Retry-After + jittered-backoff guidance (module
        docstring): the client sleeps ``retry_after_s + uniform(0,
        retry_jitter_s)`` then retries with the SAME idempotency
        key."""
        return 503, {
            "error": reason,
            "retry_after_s": self.retry_after_s,
            "retry_jitter_s": self.retry_after_s / 2.0,
            "guidance": (
                "sleep retry_after_s + uniform(0, retry_jitter_s), "
                "then retry the same request with the same "
                "idempotency_key"
            ),
        }, {"Retry-After": int(math.ceil(self.retry_after_s))}

    def _cancel(self, body: bytes):
        try:
            payload = json.loads(body.decode() or "null")
        except ValueError as e:
            return 400, {"error": f"body is not JSON: {e}"}
        if not isinstance(payload, dict) or "job" not in payload:
            return 400, {"error": 'body must be {"job": <id>}'}
        job_id = str(payload["job"])
        try:
            check_job_id(job_id)
        except ValueError as e:
            return 400, {"error": str(e)}
        try:
            cancelled = self.router.cancel(job_id)
        except KeyError:
            return 404, {"error": f"unknown job {job_id!r}"}
        return 200, {"job": job_id, "cancelled": cancelled}

    def _status(self, job_id: str):
        try:
            check_job_id(job_id)
        except ValueError as e:
            return 400, {"error": str(e)}
        try:
            job = self.router.job(job_id)
        except KeyError:
            return 404, {"error": f"unknown job {job_id!r}"}
        return 200, {
            "job": job.id,
            "state": job.state,
            "outcome": job.outcome,
            "error": job.error,
            "moves_done": job.moves_done,
            "n_moves": int(job.request.n_moves),
            "member": self.router.member_of(job_id),
            "preemptions": job.preemptions,
            "retries": job.retries,
            "trace_id": job.trace_id,
            "device_seconds": job.device_seconds,
        }

    def _result(self, job_id: str):
        try:
            check_job_id(job_id)
        except ValueError as e:
            return 400, {"error": str(e)}
        try:
            flux = self.router.result(job_id)
        except KeyError:
            return 404, {"error": f"unknown job {job_id!r}"}
        except RuntimeError as e:
            return 409, {"error": str(e)}
        import numpy as np

        arr = np.ascontiguousarray(flux)
        le = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
        return 200, {
            "job": job_id,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "data_b64": base64.b64encode(le.tobytes()).decode(),
        }

    # ------------------------------------------------------------------ #
    @property
    def port(self) -> int:
        """The bound port (resolves port 0 to the ephemeral choice)."""
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        """Shut the ingress down and release the socket (idempotent —
        teardown paths and finalizers both call it)."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def decode_result(payload: dict):
    """Reverse of ``GET /result``'s encoding — the client-side helper
    tests and the chaos campaign use for bitwise comparison."""
    import numpy as np

    raw = base64.b64decode(payload["data_b64"])
    arr = np.frombuffer(
        raw, dtype=np.dtype(payload["dtype"]).newbyteorder("<")
    )
    return (
        arr.astype(np.dtype(payload["dtype"]), copy=False)
        .reshape(payload["shape"])
    )
