"""The persistent AOT compiled-program bank (ROADMAP item 3).

One tally = one mesh = one freshly-jitted program means every new
server process pays the full XLA compile cost of the walk and megastep
programs before it can serve a single request.  This module removes
that cost: the two program families a served job dispatches — the
packed walk step (``ops/walk.py trace_packed``, which also carries the
initial-location search) and the fused device-sourced move loop
(``megastep``) — are lowered, compiled, SERIALIZED
(``jax.experimental.serialize_executable``) and written to a disk bank,
so a fresh server process deserializes executables instead of
recompiling them: ZERO XLA compiles of the program families in steady
state (pinned by a fresh-subprocess test in tests/test_serving.py).

Layout — one directory per environment section, exactly the
``{backend, x64, n_devices}`` sectioning TUNING.json uses (a CPU-built
executable means nothing to a TPU process, and vice versa)::

  <root>/<env key e.g. cpu-x64off-d1>/<family>-<signature hash>/
      PROGRAM.bin   the serialized executable (PjRt bytes)
      META.json     schema, pinned environment, family, statics,
                    dynamic-arg signature, lowered-HLO sha256,
                    donated-argument count, shape-class key,
                    compile seconds, program sha256

The entry key hashes the dynamic-argument signature (shape/dtype of
every pytree leaf plus the tree structure) and the full static-kwarg
set — the same inputs that key the jit cache — so a program is reused
exactly where the jit path would reuse its compiled entry.  The
in/out pytree structure an executable needs at load time is NOT
persisted: a fresh ``.trace(...).lower()`` of the same call (pure
tracing, no compile, sub-second) reconstructs it, and doubles as the
staleness probe — the trace's lowered-HLO sha256 must match the one
recorded at compile time, so an entry built by older code is
recompiled instead of silently serving a stale program.

Load-time validation (the PR 9 finding, resolved)
-------------------------------------------------
analysis/costmodel.py:145 documents that executables DESERIALIZED from
a cache report an EMPTY aliasing plan (``memory_analysis().alias_size
_in_bytes == 0``) — which is why the cost contracts bypass the
persistent compile cache.  The bank cannot bypass itself, so every
loaded executable is re-validated against the donation + 1+1-transfer
contract at load time, against the compiled HLO TEXT (which, unlike
``memory_analysis``, survives the round trip: ``input_output_alias``
and any host-callback custom-calls are module attributes):

  * ``cost.donation.aot``  the aliasing plan must still cover at least
    one output (the donated flux accumulator).  A serialized executable
    that lost its donation doubles accumulator HBM and breaks the
    facade's re-arm contract.
  * ``cost.io.aot``        no host-callback custom-call targets — a
    callback is a hidden per-dispatch host sync that would silently
    turn the 1+1 transfer contract into 1+1+N.

Any mismatch (or a lowered-HLO staleness mismatch) RECOMPILES the
program and REWRITES the cache entry, counted in
``pumi_aot_rewrites_total{cause=...}`` and recorded as a named Finding
on ``bank.findings``.  The same validator runs as graft-check layer 3's
``cost.donation.aot`` gate (analysis/costmodel.check_aot), so the AOT
path is provably as donated as the jit path on every CI run.

Programs that cannot serialize (e.g. a Pallas interpret-mode body)
fall back to the jit path for the lifetime of the process — the bank
degrades to today's behavior, never blocks a dispatch.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Callable, NamedTuple

BANK_SCHEMA = 1
PROGRAM_FILE = "PROGRAM.bin"
META_FILE = "META.json"

# Fault hook (tests/test_serving.py): compile the next bank entry
# WITHOUT donated arguments, so the written executable genuinely lost
# its aliasing plan — the load-time validator must then name
# cost.donation.aot, recompile, and rewrite the entry.
ENV_FAULT = "PUMI_TPU_AOT_FAULT"


def environment() -> dict:
    """The pinned bank environment — the same contract as the tuning
    database and the contract captures."""
    from ..analysis.contracts import environment as _env

    return _env()


def section_key(env: dict | None = None) -> str:
    from ..tuning.db import env_key

    return env_key(env or environment())


class _Family(NamedTuple):
    """One bankable program family: its production jit wrapper, the
    plain-jit fallback for unbankable programs, where the donated flux
    sits in the positional args, and which kwargs are DYNAMIC arrays
    (everything else in the call's kwargs is a static)."""

    name: str
    jit: object
    fallback: Callable
    impl: Callable
    flux_index: int
    dyn_kwargs: tuple


def _families() -> dict:
    import inspect

    from ..ops import walk

    # Flux positions derived from the impl signatures (the same idiom
    # walk.py uses for its own wrappers) so a reordered/inserted
    # parameter breaks loudly here instead of silently resolving
    # tally_scatter='auto' against the wrong argument.
    mega_flux = list(
        inspect.signature(walk.megastep_impl).parameters
    ).index("flux")
    return {
        "trace_packed": _Family(
            "trace_packed", walk._trace_packed_jit, walk.trace_packed,
            walk.trace_packed_impl, walk._PACKED_FLUX_ARG_INDEX,
            ("weight", "group", "conv_state"),
        ),
        "megastep": _Family(
            "megastep", walk._megastep_jit, walk.megastep,
            walk.megastep_impl, mega_flux, (),
        ),
    }


# --------------------------------------------------------------------- #
# Entry keying
# --------------------------------------------------------------------- #
def _leaf_sig(x) -> str:
    import numpy as np

    dt = getattr(x, "dtype", None)
    if dt is None:
        return repr(x)
    shape = ",".join(map(str, getattr(x, "shape", ())))
    return f"{np.dtype(dt).name}[{shape}]"


def call_signature(args: tuple, dyn_kwargs: dict) -> list[str]:
    """Shape/dtype signature of every dynamic leaf plus the pytree
    structure — what distinguishes one compiled entry from another on
    the dynamic side (mirrors the jit cache key's aval component)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, dyn_kwargs))
    return [_leaf_sig(x) for x in leaves] + [str(treedef)]


def canonical_statics(statics: dict) -> dict:
    """Static kwargs as stable strings (floats repr round-trip;
    tuples/None repr deterministically) for hashing and META."""
    return {k: repr(v) for k, v in sorted(statics.items())}


def entry_key(family: str, args: tuple, dyn_kwargs: dict,
              statics: dict) -> str:
    payload = json.dumps(
        {
            "schema": BANK_SCHEMA,
            "family": family,
            "signature": call_signature(args, dyn_kwargs),
            "statics": canonical_statics(statics),
        },
        sort_keys=True,
    )
    h = hashlib.sha256(payload.encode()).hexdigest()[:20]
    return f"{family}-{h}"


# --------------------------------------------------------------------- #
# Load-time validation (the compiled half of the donation/1+1 contract)
# --------------------------------------------------------------------- #
_ALIAS_MARKS = ("may-alias", "must-alias")
_CALLBACK_RE = re.compile(r'custom_call_target\s*=\s*"([^"]*callback[^"]*)"')


def alias_marks(compiled) -> int:
    """Number of aliased (donated) entries in one executable's
    compiled-HLO ``input_output_alias`` plan — the compile-time
    expectation the load-time validator compares against."""
    txt = compiled.as_text()
    return sum(txt.count(m) for m in _ALIAS_MARKS)


def validate_loaded(
    compiled, family: str = "", *, expect_alias: int | None = None
) -> list[tuple[str, str]]:
    """Validate one LOADED executable against the donation +
    1+1-transfer contract.  Returns ``[(symbol, message), ...]`` —
    empty means the executable is as donated and as transfer-free as a
    fresh compile.  Checked on the compiled HLO text, which survives
    serialization (``memory_analysis`` does not — the PR 9 finding this
    validator exists to close).

    ``expect_alias`` is the alias-entry count of the FRESH compile
    (recorded in META.json at write time); the loaded plan must match
    it exactly — a PARTIAL drop (e.g. flux kept but the convergence /
    batch-squares accumulators lost) is the same named finding as a
    total one.  Without it, at least one alias entry (the donated
    flux) is still required."""
    tag = f" ({family})" if family else ""
    try:
        txt = compiled.as_text()
    except Exception as e:  # pragma: no cover - backend-specific
        return [(
            "cost.donation.aot",
            f"loaded executable{tag} exposes no HLO text to validate "
            f"the aliasing plan against ({e}) — treat as a dropped "
            "donation and recompile",
        )]
    out: list[tuple[str, str]] = []
    n_alias = sum(txt.count(m) for m in _ALIAS_MARKS)
    if "input_output_alias" not in txt or n_alias < 1:
        out.append((
            "cost.donation.aot",
            f"loaded executable{tag} carries no input_output_alias "
            "entry — the flux donation was dropped in serialization; "
            "peak memory grows by one accumulator and the re-arm "
            "contract breaks",
        ))
    elif expect_alias is not None and n_alias != expect_alias:
        out.append((
            "cost.donation.aot",
            f"loaded executable{tag} carries {n_alias} aliased "
            f"entr{'y' if n_alias == 1 else 'ies'} but the fresh "
            f"compile recorded {expect_alias} — a PARTIAL donation "
            "drop (e.g. the convergence/batch-squares accumulators) "
            "grows peak memory per resident job",
        ))
    callbacks = _CALLBACK_RE.findall(txt)
    if callbacks:
        out.append((
            "cost.io.aot",
            f"loaded executable{tag} contains host-callback custom-"
            f"call(s) {sorted(set(callbacks))} — a hidden per-dispatch "
            "host sync; the 1+1 transfer contract does not survive it",
        ))
    return out


class _Program(NamedTuple):
    """One resolved bank program: the loaded/compiled executable (None
    = unbankable this process, dispatch falls back to the jit path) and
    its provenance tag for telemetry ("hit", "miss", a rewrite cause
    — "stale" / "corrupt" / "invalid" — or "unbankable")."""

    compiled: object | None
    provenance: str


# --------------------------------------------------------------------- #
# The bank
# --------------------------------------------------------------------- #
class ProgramBank:
    """Disk-backed AOT executable cache for the serving program
    families.  Attach to a facade via ``PumiTally(...,
    program_bank=bank)``; the facade then routes its packed-walk and
    megastep dispatches through :meth:`dispatch`."""

    def __init__(self, root: str, *, registry=None, recorder=None,
                 tracer=None):
        from ..obs import FlightRecorder, MetricsRegistry, SpanTracer

        self.root = str(root)
        self.env = environment()
        self.section = section_key(self.env)
        self.section_dir = os.path.join(self.root, self.section)
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.recorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        # Span tracer (obs/trace.py): the scheduler passes its own so
        # resolve/deserialize/compile spans land in the CURRENT job's
        # trace via the ambient binding; a standalone bank gets a
        # private (ring-only) tracer.
        self.tracer = tracer if tracer is not None else SpanTracer()
        r = self.registry
        self._hits = r.counter(
            "pumi_aot_hits_total",
            "program-bank dispatches served from a deserialized "
            "AOT executable (no XLA compile)",
        )
        self._misses = r.counter(
            "pumi_aot_misses_total",
            "program-bank dispatches that compiled (entry absent, "
            "stale, or invalid)",
        )
        self._compile_s = r.counter(
            "pumi_compile_seconds_total",
            "wall seconds spent in XLA compilation by the program bank",
        )
        self._rewrites = r.counter(
            "pumi_aot_rewrites_total",
            "bank entries recompiled and rewritten after load-time "
            "validation (labeled by cause: donation, io, stale, "
            "corrupt)",
        )
        self._lock = threading.Lock()
        # In-memory programs resolved this process, keyed by entry key.
        self._programs: dict[str, _Program] = {}
        # Load-time validation findings (analysis.Finding objects) —
        # the test/introspection surface mirroring the cost.donation.aot
        # lint gate.
        self.findings: list = []

    # -- counter views (the bench/scheduler summary surface) ----------- #
    @property
    def hits(self) -> int:
        return int(self._hits.value())

    @property
    def misses(self) -> int:
        return int(self._misses.value())

    @property
    def rewrites(self) -> int:
        seen = self._rewrites.snapshot()["series"]
        return int(sum(s["value"] for s in seen))

    @property
    def compile_seconds(self) -> float:
        return float(self._compile_s.value())

    def stats(self) -> dict:
        return {
            "root": self.root,
            "section": self.section,
            "hits": self.hits,
            "misses": self.misses,
            "rewrites": self.rewrites,
            "compile_seconds": round(self.compile_seconds, 3),
            "entries": len(self._programs),
        }

    # ------------------------------------------------------------------ #
    def dispatch(self, family: str, args: tuple, kwargs: dict, *,
                 shape_key: str | None = None):
        """Run one facade dispatch through the bank: resolve the entry
        (load-or-compile on first use per process), then call the
        executable with the dynamic arguments only (statics are baked
        into the compiled program).  Unbankable programs fall back to
        the production jit wrapper — same results, jit-cache compile
        cost."""
        fam = _families()[family]
        kwargs = dict(kwargs)
        if kwargs.get("tally_scatter", "auto") == "auto":
            # Resolve exactly like the jit wrappers do, BEFORE the
            # entry key forms — "auto" is not a compilable static.
            from ..ops.walk import resolve_tally_scatter

            kwargs["tally_scatter"] = resolve_tally_scatter(
                "auto", args[fam.flux_index]
            )
        dyn = {k: kwargs.pop(k) for k in fam.dyn_kwargs if k in kwargs}
        statics = kwargs
        # The steady-state memo key: leaf shapes/dtypes + tree
        # structure + the statics themselves (hashable by definition —
        # they are jit statics).  Everything the disk entry key hashes,
        # but as a plain tuple lookup — no json/sha256 on the per-move
        # hot path; the hex entry key is derived only on first
        # resolution (_acquire).
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, dyn))
        memo_key = (
            family,
            treedef,
            tuple(
                (getattr(x, "shape", None), str(getattr(x, "dtype", x)))
                for x in leaves
            ),
            tuple(sorted(statics.items(), key=lambda kv: kv[0])),
        )
        with self._lock:
            prog = self._programs.get(memo_key)
        if prog is None:
            prog = self._acquire(
                fam, memo_key, args, dyn, statics, shape_key
            )
        if prog.compiled is None:
            return fam.fallback(*args, **dyn, **statics)
        return prog.compiled(*args, **dyn)

    # ------------------------------------------------------------------ #
    def _acquire(self, fam, memo_key, args, dyn, statics, shape_key):
        """Resolve one entry: fresh trace+lower (pure — reconstructs
        the pytree metadata and the staleness hash), then load+validate
        from disk or compile+serialize+write."""
        import jax

        key = entry_key(fam.name, args, dyn, statics)
        with self.tracer.span(
            "aot_resolve", family=fam.name, key=key
        ) as sp:
            prog = self._acquire_inner(
                fam, memo_key, args, dyn, statics, shape_key, key
            )
            sp["outcome"] = prog.provenance
        return prog

    def _acquire_inner(self, fam, memo_key, args, dyn, statics,
                       shape_key, key):
        import jax

        traced = fam.jit.trace(*args, **dyn, **statics)
        lowered = traced.lower()
        in_tree = jax.tree_util.tree_flatten(lowered.args_info)[1]
        out_tree = lowered.out_tree
        hlo_sha = hashlib.sha256(lowered.as_text().encode()).hexdigest()
        entry_dir = os.path.join(self.section_dir, key)
        meta_path = os.path.join(entry_dir, META_FILE)
        prog_path = os.path.join(entry_dir, PROGRAM_FILE)

        compiled, provenance = None, "miss"
        loaded = self._try_load(
            fam, key, meta_path, prog_path, in_tree, out_tree, hlo_sha
        )
        if loaded is not None:
            compiled, provenance = loaded
        if compiled is None:
            if provenance == "miss":
                self._misses.inc()
            compiled = self._compile_and_write(
                fam, key, lowered, entry_dir, hlo_sha, args, dyn,
                statics, shape_key,
            )
            if compiled is None:
                prog = _Program(None, "unbankable")
                with self._lock:
                    self._programs[memo_key] = prog
                return prog
        prog = _Program(compiled, provenance)
        with self._lock:
            self._programs[memo_key] = prog
        self.recorder.record(
            "aot", family=fam.name, key=key, outcome=provenance,
            shape_key=shape_key, job_id=self.tracer.current[1],
        )
        return prog

    def _try_load(self, fam, key, meta_path, prog_path, in_tree,
                  out_tree, hlo_sha):
        """Load one disk entry.  Returns ``(compiled, "hit")`` on a
        clean validated load, ``(None, "<cause>")`` when the entry
        exists but must be rewritten (counted), or None on a plain
        miss."""
        if not (os.path.exists(meta_path) and os.path.exists(prog_path)):
            return None
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
            with open(prog_path, "rb") as fh:
                payload = fh.read()
        except (OSError, ValueError) as e:
            # ValueError covers json.JSONDecodeError AND the
            # UnicodeDecodeError a byte-flipped META raises before the
            # json parser even runs — a torn/corrupted entry must
            # degrade to a recompile-and-rewrite, never crash a
            # dispatch.
            self._note_rewrite(fam, key, "corrupt", f"unreadable: {e}")
            return (None, "corrupt")
        if not isinstance(meta, dict):
            self._note_rewrite(
                fam, key, "corrupt",
                f"META.json parses but is not an object: {type(meta).__name__}",
            )
            return (None, "corrupt")
        if (
            meta.get("schema") != BANK_SCHEMA
            or meta.get("environment") != self.env
        ):
            self._note_rewrite(
                fam, key, "stale",
                f"schema/environment mismatch (entry: "
                f"{meta.get('schema')}/{meta.get('environment')}, "
                f"bank: {BANK_SCHEMA}/{self.env})",
            )
            return (None, "stale")
        if meta.get("sha256") != hashlib.sha256(payload).hexdigest():
            self._note_rewrite(
                fam, key, "corrupt", "program bytes fail their digest"
            )
            return (None, "corrupt")
        if meta.get("hlo_sha256") != hlo_sha:
            # The code that traces this call today lowers a DIFFERENT
            # program than the one that was compiled — an entry from an
            # older build must never serve stale semantics.
            self._note_rewrite(
                fam, key, "stale",
                "lowered-HLO hash drifted since the entry was compiled",
            )
            return (None, "stale")
        try:
            with self.tracer.span(
                "aot_deserialize", family=fam.name, key=key,
                bytes=len(payload),
            ):
                compiled = deserialize_and_load(
                    payload, in_tree, out_tree
                )
        except Exception as e:
            self._note_rewrite(
                fam, key, "corrupt", f"deserialization failed: {e}"
            )
            return (None, "corrupt")
        problems = validate_loaded(
            compiled, fam.name, expect_alias=meta.get("alias_marks")
        )
        if problems:
            for symbol, message in problems:
                self._note_rewrite(
                    fam, key,
                    "donation" if symbol == "cost.donation.aot" else "io",
                    message, symbol=symbol,
                )
            return (None, "invalid")
        self._hits.inc()
        return (compiled, "hit")

    def _note_rewrite(self, fam, key, cause, message, *,
                      symbol=None) -> None:
        from ..analysis import Finding
        from ..utils.log import log_warn

        self._rewrites.inc(cause=cause)
        self.findings.append(
            Finding(
                rule="COST",
                path=os.path.join(self.section, key),
                line=0,
                symbol=symbol or f"aot.{cause}",
                message=f"[{fam.name}] {message}",
            )
        )
        self.recorder.record(
            "aot_rewrite", family=fam.name, key=key, cause=cause,
            message=message, job_id=self.tracer.current[1],
        )
        log_warn(
            f"program bank: rewriting entry {key} ({cause}): {message}"
        )

    # ------------------------------------------------------------------ #
    def _compile_and_write(self, fam, key, lowered, entry_dir, hlo_sha,
                           args, dyn, statics, shape_key):
        """Compile (persistent compile cache bypassed — a cache-served
        executable would record the cache's provenance, not a fresh
        compile's, and its reported aliasing plan is exactly the PR 9
        artifact this bank validates against), serialize, and write the
        entry atomically.  Returns the compiled program, or None when
        the family cannot compile at all (never expected — compile
        errors propagate)."""
        import jax
        from jax.experimental.serialize_executable import serialize

        from ..analysis.costmodel import fresh_compile

        t0 = time.perf_counter()
        if os.environ.get(ENV_FAULT, "") == "drop_donation":
            # Fault hook: an UNDONATED twin of the same program — same
            # statics, same trees, no aliasing plan — so the written
            # entry reproduces a genuine donation drop for the
            # load-time validator to catch.
            twin = jax.jit(fam.impl, static_argnames=tuple(statics))
            lowered = twin.trace(*args, **dyn, **statics).lower()
        with self.tracer.span("aot_compile", family=fam.name, key=key):
            compiled = fresh_compile(lowered)
        dt = time.perf_counter() - t0
        self._compile_s.inc(dt)
        try:
            payload, _, _ = serialize(compiled)
        except (ValueError, TypeError) as e:
            from ..utils.log import log_warn

            log_warn(
                f"program bank: {fam.name} entry {key} is not "
                f"serializable ({e}); serving it from the jit path "
                "this process"
            )
            return None
        donated = sum(
            lowered.as_text().count(m)
            for m in ("tf.aliasing_output", "jax.buffer_donor")
        )
        meta = {
            "schema": BANK_SCHEMA,
            "environment": self.env,
            "family": fam.name,
            "key": key,
            "shape_key": shape_key,
            "signature": call_signature(args, dyn),
            "statics": canonical_statics(statics),
            "hlo_sha256": hlo_sha,
            "donated": donated,
            # Compiled-plan alias entries, the load-time validator's
            # exact expectation: a PARTIAL donation drop in a future
            # serialization change must not hide behind the flux alias.
            "alias_marks": alias_marks(compiled),
            "compile_seconds": round(dt, 3),
            "sha256": hashlib.sha256(payload).hexdigest(),
        }
        self._write_entry(entry_dir, payload, meta)
        return compiled

    @staticmethod
    def _write_entry(entry_dir: str, payload: bytes, meta: dict) -> None:
        """Atomic entry write: bytes first, META last (an entry without
        META is invisible — the two-phase discipline the checkpoint
        store established)."""
        os.makedirs(entry_dir, exist_ok=True)
        for name, data in (
            (PROGRAM_FILE, payload),
            (META_FILE, (json.dumps(meta, indent=1, sort_keys=True)
                         + "\n").encode()),
        ):
            tmp = os.path.join(entry_dir, name + ".tmp")
            with open(tmp, "wb") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, os.path.join(entry_dir, name))

    # ------------------------------------------------------------------ #
    def memory_analysis(self) -> dict:
        """HBM footprint over every program RESOLVED for dispatch so
        far (the fleet profiler's high-water source).  Per executable
        the footprint is argument + output + temp bytes from XLA's
        ``memory_analysis()``; executables that expose none —
        deserialized entries report empty analyses (the PR 9 finding),
        and CPU backends may expose nothing at all — count as
        ``unanalyzed`` rather than as zero-byte programs."""
        high = 0
        analyzed = unanalyzed = 0
        with self._lock:
            programs = list(self._programs.values())
        for prog in programs:
            if prog.compiled is None:
                continue
            try:
                ma = prog.compiled.memory_analysis()
                footprint = int(
                    getattr(ma, "argument_size_in_bytes", 0)
                    + getattr(ma, "output_size_in_bytes", 0)
                    + getattr(ma, "temp_size_in_bytes", 0)
                )
            except Exception:
                unanalyzed += 1
                continue
            if footprint <= 0:
                unanalyzed += 1
                continue
            analyzed += 1
            high = max(high, footprint)
        return {
            "high_water_bytes": high,
            "analyzed": analyzed,
            "unanalyzed": unanalyzed,
        }

    def entries_on_disk(self) -> list[str]:
        """Committed entry keys in this environment's section."""
        if not os.path.isdir(self.section_dir):
            return []
        return sorted(
            d for d in os.listdir(self.section_dir)
            if os.path.exists(
                os.path.join(self.section_dir, d, META_FILE)
            )
        )
