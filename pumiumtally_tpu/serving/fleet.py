"""Multi-chip serving fleet: crash-safe routing over member schedulers.

ROADMAP item 3's fleet half: one ``FleetRouter`` owns N journaled
``TallyScheduler`` members (one per device slot — CPU-testable on the
8-device mesh, one chip each on real hardware), places every job by
shape-class bucket, queue depth, and AOT-bank warmth, and survives any
member (or its own) death without losing or double-running a job.

Layout — one directory per fleet::

  <fleet_dir>/FLEET.json          the write-ahead ROUTING journal
                                  (atomic tmp+fsync+rename, like
                                  JOBS.json)
  <fleet_dir>/TRACE.jsonl         the shared span stream (one tracer
                                  for every member, so a migrated
                                  job's trace reads as one spine)
  <fleet_dir>/member-K/           member K's own crash-safe scheduler
                                  journal (serving/journal.py layout)

FLEET.json document (schema 1)::

  {"schema": 1, "members": N, "n_submitted": M,
   "accepted":    {idempotency_key: job_id},
   "requests":    {job_id: request_json},   # journaled, not yet
                                            # dispatched to a member
   "assignments": {job_id: {"member": K, "migrations": J}},
   "evicted":     {member_index: {"cause": ...}},  # supervisor evictions
   "breaches":    {member_index: [{"slo": ..., "burn": ...}]}}
                                  # SLO breach advisories journaled
                                  # before the quarantine they explain

Write-ahead orderings (machine-checked by analysis/protolint.py, not
chaos-only):

  * **idempotency-record-before-accept** (``FleetRouter.submit``): the
    ``accepted[key] = job_id`` record and the request payload are
    flushed to FLEET.json BEFORE the job is placed on any member.  A
    client retrying a POST after any crash therefore maps to the SAME
    job id — the retry can never start a second execution, because
    acceptance is only ever decided by the journaled map.
  * **assignment-record-before-dispatch** (``FleetRouter._place``):
    the ``assignments[job_id] = member`` record is flushed BEFORE the
    job is handed to that member's scheduler.  A crash between the
    two leaves a journaled assignment whose member journal does not
    know the job — recovery re-dispatches it (the request payload is
    still journaled).  Reversed, a crash after dispatch but before
    the record would leave a job some member owns that the router
    cannot attribute — double-run fodder on restart.
  * **eviction-record-before-drain** (``FleetSupervisor._evict``,
    serving/supervisor.py): the ``evicted[member] = cause`` record is
    flushed to FLEET.json BEFORE the member's jobs are drained onto
    survivors.  A supervisor crash mid-drain leaves a journaled
    eviction whose member may still hold jobs — recovery replays the
    drain from the evicted member's on-disk journal
    (``_replace_from_disk``), with the assignment record arbitrating
    the copies exactly as for an interrupted migration.

The assignment record is also the DUPLICATE arbiter: migration adopts
a job on member B before dropping it from member A (so a crash between
the two loses nothing), which briefly leaves the job in two member
journals — recovery keeps only the copy the assignment names and drops
the stale one.

Cross-chip migration rides the existing checkpoint subsystem:
checkpoint-preempt on member A (megastep boundary), copy the side
files, ``adopt_job`` on member B — bitwise vs the uninterrupted run,
because the megastep RNG is keyed by the persistent move counter the
checkpoint carries, and every member shares one mesh/config/bank.  The
trace continues across the hop with a ``migrated`` link event (PR 16's
``recovered``, but across members instead of process lifetimes).

Member death (``absorb_member_kills=True``, or an explicit
``kill_member``) is absorbed by re-placing the dead member's JOURNALED
jobs onto survivors — the on-disk write-ahead journal is the authority
for what the member owned; its in-memory table died with it.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading

import numpy as np

from ..obs import (
    FLIGHT_SCHEMA,
    FlightRecorder,
    MetricsRegistry,
    SpanTracer,
    maybe_start_exporter,
)
from ..obs.aggregate import (
    FLEETSTATS_FILE,
    FLEETSTATS_SCHEMA,
    FleetAggregator,
)
from ..obs.profile import FleetProfiler
from ..obs.slo import SLOEvaluator, default_slos
from ..resilience.faultinject import FaultInjector, InjectedKill
from ..tuning.shapes import bucket, classify
from ..utils.checkpoint import atomic_write_json
from ..utils.log import log_info, log_warn
from .bank import ProgramBank
from .journal import (
    JOURNAL_FILE,
    TRACE_FILE,
    SchedulerJournal,
    check_job_id,
    request_from_json,
    request_to_json,
)
from .scheduler import JobRequest, TallyScheduler, _quiet_exporter

FLEET_SCHEMA = 1
FLEET_FILE = "FLEET.json"

# The fleet observability plane (aggregator + SLO evaluation +
# profiler sampling + FLEETSTATS.json snapshots) is ON by default;
# PUMI_TPU_FLEET_OBS=off disables it wholesale (the bench's A/B knob).
ENV_FLEET_OBS = "PUMI_TPU_FLEET_OBS"


def _fleet_obs_enabled() -> bool:
    return os.environ.get(ENV_FLEET_OBS, "").strip().lower() != "off"


class FleetJournal:
    """The atomic FLEET.json routing journal (module docstring format).
    The router is the single writer; recovery is the single reader."""

    def __init__(self, dirname: str):
        self.dir = str(dirname)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, FLEET_FILE)

    def member_dir(self, index: int) -> str:
        return os.path.join(self.dir, f"member-{int(index):02d}")

    def trace_path(self) -> str:
        """The fleet-wide span sink: every member (and every process
        lifetime of the router) appends to one TRACE.jsonl, so a
        migrated job's trace reconstructs from one directory."""
        return os.path.join(self.dir, TRACE_FILE)

    def flush(self, doc: dict) -> None:
        atomic_write_json(self.path, {"schema": FLEET_SCHEMA, **doc})

    def load(self) -> dict | None:
        """The committed routing document, or None before the first
        flush.  A parse failure is REJECTED loudly: the atomic writer
        cannot tear this file, so an unreadable document means someone
        else wrote it — recovering over it could silently re-run or
        drop accepted jobs."""
        if not os.path.exists(self.path):
            return None
        with open(self.path) as fh:
            try:
                doc = json.load(fh)
            except ValueError as e:
                raise ValueError(
                    f"fleet journal {self.path} is not valid JSON "
                    f"({e}) — the atomic writer cannot tear it, so "
                    "this document was written by something else; "
                    "refusing to recover over it"
                ) from e
        if not isinstance(doc, dict) or doc.get("schema") != FLEET_SCHEMA:
            raise ValueError(
                f"fleet journal {self.path}: schema "
                f"{doc.get('schema') if isinstance(doc, dict) else doc!r}"
                f" != {FLEET_SCHEMA}"
            )
        return doc


class FleetMember:
    """One device slot: a journaled TallyScheduler plus the router's
    placement view of it (liveness, lifetime placements, which shape
    classes it has already served — the warmth signal, plus the
    supervisor's health view).

    ``scheduler`` may be None for a member the routing journal records
    as EVICTED: recovery keeps the slot (member indices are stable —
    FLEET.json assignments reference them) but never rebuilds device
    state for it.  Every ``.scheduler`` access in the router is
    guarded by ``.alive``, which is False for such a slot.
    """

    def __init__(self, index: int, scheduler: TallyScheduler | None,
                 registry: MetricsRegistry | None = None):
        self.index = index
        self.scheduler = scheduler
        #: This member's OWN metrics registry (every scheduler family
        #: lands here, attributable to the member).  It outlives the
        #: scheduler — an evicted member's counters stay in the fleet
        #: rollup, keeping the aggregated counters monotonic.
        self.registry = registry
        self.alive = scheduler is not None
        #: Supervisor classification: healthy / brownout / wedged /
        #: disk-pressured while alive; "evicted" once drained
        #: (serving/supervisor.py owns the transitions).
        self.health = "healthy" if scheduler is not None else "evicted"
        #: Quarantined members stop receiving NEW placements (the
        #: supervisor's grace period before eviction) but keep running
        #: the jobs they hold.
        self.quarantined = False
        self.placed = 0            # jobs dispatched here (lifetime)
        self.warm: set[str] = set()  # shape classes served here

    @property
    def load(self) -> int:
        return (
            self.scheduler.queue_depth + self.scheduler.resident_count
        )


class FleetRouter:
    """Crash-safe job routing over ``n_members`` schedulers sharing one
    mesh, config, AOT bank, tracer, and recorder.  Each member keeps
    its OWN metrics registry (``FleetMember.registry``); the router's
    registry holds the fleet/supervisor/SLO families, and the
    observability plane (obs/aggregate.py) merges the member
    registries into the ``/fleetz`` rollup + FLEETSTATS.json.

    Thread model: the router's scheduling loop (``step``/``run``) and
    the gateway's HTTP handler threads (serving/gateway.py) serialize
    on ``self.lock`` — every public method takes it, so member
    schedulers only ever run single-threaded.
    """

    def __init__(
        self,
        mesh,
        config=None,
        *,
        fleet_dir: str,
        n_members: int = 2,
        bank: ProgramBank | str | None = None,
        registry: MetricsRegistry | None = None,
        faults: FaultInjector | None = None,
        absorb_member_kills: bool = False,
        slos: tuple | None = None,
        _recover: bool = False,
        _evicted: tuple = (),
        **member_kwargs,
    ):
        if int(n_members) < 1:
            raise ValueError(f"n_members must be >= 1: {n_members}")
        self.mesh = mesh
        self.config = config
        self.journal = FleetJournal(fleet_dir)
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.recorder = FlightRecorder(schema=FLIGHT_SCHEMA)
        self.tracer = SpanTracer(sink=self.journal.trace_path())
        self.absorb_member_kills = bool(absorb_member_kills)
        self.lock = threading.RLock()
        if isinstance(bank, str):
            bank = ProgramBank(
                bank, registry=self.registry, recorder=self.recorder,
                tracer=self.tracer,
            )
        self.bank = bank
        r = self.registry
        self._members_gauge = r.gauge(
            "pumi_fleet_members",
            "alive fleet members (schedulers accepting dispatch)",
        )
        self._migrations_total = r.counter(
            "pumi_fleet_migrations_total",
            "jobs re-placed across members (explicit cross-chip "
            "migration + dead-member re-placement onto survivors)",
        )
        self._fleet_queue_depth = r.gauge(
            "pumi_fleet_queue_depth",
            "per-member scheduler queue depth (labeled by member; "
            "dead members report 0)",
        )
        # Routing state — the in-memory mirror of FLEET.json.  All of
        # it is only touched under self.lock (class docstring).
        self._accepted: dict[str, str] = {}     # idempotency key -> id
        self._requests: dict[str, dict] = {}    # journaled, undispatched
        self._pending: dict[str, JobRequest] = {}
        self._assignments: dict[str, dict] = {}
        self._evicted: dict[int, dict] = {}     # member index -> {cause}
        #: SLO breach advisories journaled by the supervisor BEFORE it
        #: quarantines the offender (breach-record-before-quarantine):
        #: {member index: [{"slo": ..., "burn": ...}, ...]}.
        self._breaches: dict[int, list] = {}
        self._n_submitted = 0
        # Alert edges already handed to the profiler's capture hook
        # (keyed by (slo, since) so a re-fired alert captures again).
        self._seen_alerts: set = set()
        # Members never bind the scrape port (the ROUTER's exporter
        # owns it, with the fleet endpoints mounted) and never install
        # signal handlers (their write-ahead journals are flushed at
        # every transition; recovery needs no graceful flush).
        self.members: list[FleetMember] = []
        for i in range(int(n_members)):
            if i in _evicted:
                # A journaled-evicted slot: keep the index stable for
                # FLEET.json references, never rebuild device state.
                self.members.append(FleetMember(i, None))
                continue
            mdir = self.journal.member_dir(i)
            # Every member gets its OWN registry (the aggregator's
            # contract, obs/aggregate.py): scheduler families are
            # attributable per member and merge into the fleet rollup
            # instead of silently interleaving in one shared table.
            mreg = MetricsRegistry()
            mkw = dict(
                member_kwargs,
                bank=self.bank,
                registry=mreg,
                tracer=self.tracer,
                recorder=self.recorder,
                blackbox_dir=self.journal.dir,
                faults=faults,
                handle_signals=False,
                member_index=i,
            )
            with _quiet_exporter():
                if _recover and os.path.exists(
                    os.path.join(mdir, JOURNAL_FILE)
                ):
                    sched = TallyScheduler.recover(
                        mdir, mesh, config, **mkw
                    )
                else:
                    sched = TallyScheduler(
                        mesh, config, journal_dir=mdir, **mkw
                    )
            member = FleetMember(i, sched, registry=mreg)
            for j in sched.jobs():
                member.warm.add(j.shape_key)
            # A recovered member's journaled jobs count as placements
            # here — the per-member placement stats must reflect
            # ownership, not just this lifetime's dispatches.
            member.placed = len(sched.jobs())
            self.members.append(member)
        # The observability plane (aggregate + SLO + profile — the
        # three obs/ layers).  PUMI_TPU_FLEET_OBS=off runs the fleet
        # bare: no aggregation, no burn-rate gauges, no FLEETSTATS
        # snapshots (the bench's A/B knob).
        self.obs_enabled = _fleet_obs_enabled()
        self.aggregator: FleetAggregator | None = None
        self.slo: SLOEvaluator | None = None
        self.profiler: FleetProfiler | None = None
        if self.obs_enabled:
            self.aggregator = FleetAggregator(self._obs_registries)
            self.slo = SLOEvaluator(
                default_slos() if slos is None else slos,
                self.registry, self.recorder,
            )
            self.profiler = FleetProfiler(
                self.registry, journal_dir=self.journal.dir,
                bank=self.bank,
            )
        endpoints = {
            "/jobs": self._jobs_json,
            "/trace": self.tracer.chrome,
            "/fleet": self.fleet_json,
        }
        if self.aggregator is not None:
            endpoints["/fleetz"] = self.aggregator.render_prometheus
        self._exporter = maybe_start_exporter(
            self.registry, endpoints=endpoints,
        )
        self._update_gauges()
        # First FLEETSTATS snapshot: the last-known fleet picture must
        # exist from round zero — a router killed before its first
        # step still leaves one for fleetview to reconstruct.
        self.obs_tick()

    # ------------------------------------------------------------------ #
    # The routing journal
    # ------------------------------------------------------------------ #
    def _flush_fleet(self) -> None:
        self.journal.flush({
            "members": len(self.members),
            "n_submitted": self._n_submitted,
            "accepted": dict(self._accepted),
            "requests": dict(self._requests),
            "assignments": {
                k: dict(v) for k, v in self._assignments.items()
            },
            "evicted": {
                str(k): dict(v) for k, v in self._evicted.items()
            },
            "breaches": {
                str(k): [dict(b) for b in v]
                for k, v in self._breaches.items()
            },
        })

    def record_breach(self, index: int, alert: dict) -> None:
        """Journal an SLO breach advisory against member ``index``
        BEFORE the supervisor quarantines it
        (breach-record-before-quarantine, protolint-checked on
        ``FleetSupervisor._advise_slo``): the quarantine decision must
        be explainable from FLEET.json alone — a crash right after the
        quarantine flag flips still leaves the WHY on disk."""
        with self.lock:
            self._breaches.setdefault(int(index), []).append({
                "slo": str(alert.get("slo")),
                "burn": dict(alert.get("burn") or {}),
            })
            self._flush_fleet()

    def record_eviction(self, index: int, cause: str) -> None:
        """Journal the decision to evict member ``index`` BEFORE any
        drain work starts (eviction-record-before-drain, module
        docstring; the supervisor's ``_evict`` is protolint-checked to
        call this first).  A crash after this record replays the drain
        at recovery from the member's on-disk journal."""
        with self.lock:
            self._evicted[int(index)] = {"cause": str(cause)}
            self._flush_fleet()

    # ------------------------------------------------------------------ #
    # Submission (network-facing: serving/gateway.py calls this)
    # ------------------------------------------------------------------ #
    def submit(self, request: JobRequest, *,
               idempotency_key: str | None = None) -> str:
        """Accept one job and place it on a member.  With an
        ``idempotency_key``, acceptance is decided by the JOURNALED
        key map: a key seen before returns the original job id without
        touching any scheduler (a retried POST never double-runs), and
        a new key is journaled BEFORE the job is placed
        (idempotency-record-before-accept, protolint-verified)."""
        with self.lock:
            if idempotency_key is not None:
                try:
                    check_job_id(idempotency_key)
                except ValueError:
                    raise ValueError(
                        f"idempotency key {idempotency_key!r} is not "
                        "journal-safe (allowed: 1-128 chars of "
                        "[A-Za-z0-9._-])"
                    ) from None
                known = self._accepted.get(idempotency_key)
                if known is not None:
                    self.recorder.record(
                        "fleet_dedup", job=known, job_id=known,
                        idempotency_key=idempotency_key,
                    )
                    return known
            # Validation happens BEFORE the acceptance record: a bad
            # request must be rejected without journaling a key that
            # maps to a job no member will ever run.
            origins = np.asarray(
                request.origins, np.float64
            ).reshape(-1, 3)
            n = origins.shape[0]
            if n < 1:
                raise ValueError("a job needs at least one particle")
            if request.n_moves < 1:
                raise ValueError(
                    f"n_moves must be >= 1: {request.n_moves}"
                )
            for name, arr in (
                ("weights", request.weights),
                ("groups", request.groups),
            ):
                if (
                    arr is not None
                    and np.asarray(arr).reshape(-1).size != n
                ):
                    raise ValueError(
                        f"{name} has "
                        f"{np.asarray(arr).reshape(-1).size} entries "
                        f"for {n} particles"
                    )
            job_id = request.job_id or f"fleet-{self._n_submitted:05d}"
            check_job_id(job_id)
            if job_id in self._assignments or job_id in self._requests:
                raise ValueError(f"duplicate job id {job_id!r}")
            request = dataclasses.replace(request, job_id=job_id)
            shape_key = self._shape_key(n)
            self._n_submitted += 1
            if idempotency_key is not None:
                self._accepted[idempotency_key] = job_id
            self._requests[job_id] = request_to_json(request)
            self._pending[job_id] = request
            # Idempotency-record-before-accept: the key map + request
            # payload are durable before ANY member sees the job.
            self._flush_fleet()
            self._place(job_id, shape_key)
            return job_id

    def _shape_key(self, n: int) -> str:
        cfg = next(
            m.scheduler.config for m in self.members
            if m.scheduler is not None
        )
        return classify(
            self.mesh.ntet, bucket(n), cfg.n_groups, cfg.dtype,
            getattr(self.mesh, "geo20", None) is not None,
        ).key()

    # ------------------------------------------------------------------ #
    # Placement
    # ------------------------------------------------------------------ #
    def _choose(self, shape_key: str,
                exclude: tuple = ()) -> FleetMember | None:
        """Least-loaded alive member, warm members first: a member
        that has already served this shape class holds the deserialized
        programs resident (the shared on-disk bank makes the first
        touch cheap everywhere, but warm re-use is free), so warmth
        wins until queue depth tips the balance.  Quarantined members
        (supervisor grace period) rank strictly LAST: they keep their
        jobs but get new work only when no healthy member exists."""
        best = None
        best_score = None
        for m in self.members:
            if not m.alive or m.index in exclude:
                continue
            score = (
                1 if m.quarantined else 0,
                m.load,
                0 if shape_key in m.warm else 1,
                m.placed,
                m.index,
            )
            if best_score is None or score < best_score:
                best, best_score = m, score
        return best

    def _place(self, job_id: str, shape_key: str, *, entry: dict | None = None,
               src_dir: str | None = None, member: int | None = None,
               exclude: tuple = (), link: str = "migrated") -> int:
        """Assign ``job_id`` to a member and dispatch it there — in
        that order: the FLEET.json assignment record is flushed BEFORE
        the member's scheduler sees the job
        (assignment-record-before-dispatch, protolint-verified).  A
        fresh submission dispatches its pending request; a migration
        (``entry``/``src_dir``) adopts the journaled entry, continuing
        the job's trace with the given ``link`` event (``migrated`` or
        the supervisor's ``evicted``)."""
        if member is not None:
            target = self.members[member]
            if not target.alive:
                raise ValueError(f"member {member} is not alive")
        else:
            target = self._choose(shape_key, exclude)
        if target is None:
            raise RuntimeError(
                f"no alive fleet member to place {job_id} on"
            )
        prev = self._assignments.get(job_id)
        self._assignments[job_id] = {
            "member": target.index,
            "migrations": (
                int(prev["migrations"]) + 1 if prev is not None else 0
            ),
        }
        self._flush_fleet()
        self._dispatch_job(
            target, job_id, entry=entry, src_dir=src_dir, link=link
        )
        return target.index

    def _dispatch_job(self, member: FleetMember, job_id: str, *,
                      entry: dict | None = None,
                      src_dir: str | None = None,
                      link: str = "migrated") -> None:
        if entry is not None:
            member.scheduler.adopt_job(entry, src_dir=src_dir, link=link)
            self._migrations_total.inc()
        else:
            member.scheduler.submit(self._pending.pop(job_id))
            # The member journal now holds the request — the router's
            # pre-dispatch copy has served its crash window (pruned
            # from FLEET.json at the next flush).
            self._requests.pop(job_id, None)
        member.placed += 1
        member.warm.add(member.scheduler.job(job_id).shape_key)
        self.recorder.record(
            "fleet_placed", job=job_id, job_id=job_id,
            member=member.index, migrated=entry is not None,
        )
        self._update_gauges()

    # ------------------------------------------------------------------ #
    # Cross-chip migration + member death
    # ------------------------------------------------------------------ #
    def migrate(self, job_id: str, to_member: int | None = None) -> int:
        """Move one non-terminal job to another member: checkpoint-
        preempt on the current owner (megastep boundary), re-journal
        the assignment, adopt on the target from the copied side files
        (bitwise — the checkpoint's move counter keys the RNG), then
        drop the source copy (adopt-before-drop: a crash in between
        leaves two journaled copies, and the assignment record names
        the one recovery keeps).  Returns the new member index."""
        with self.lock:
            assignment = self._assignments[job_id]
            src = self.members[assignment["member"]]
            if not src.alive:
                raise ValueError(
                    f"job {job_id} is on dead member {src.index}"
                )
            job = src.scheduler.job(job_id)
            if job.terminal:
                raise ValueError(
                    f"job {job_id} is terminal ({job.outcome}) — "
                    "nothing to migrate"
                )
            src.scheduler.preempt_job(job_id)
            fleet_entry = src.scheduler.export_entry(job_id)
            new_index = self._place(
                job_id, job.shape_key, entry=fleet_entry,
                src_dir=src.scheduler.journal.dir,
                member=to_member, exclude=(src.index,),
            )
            src.scheduler.drop_job(job_id)
            log_info(
                f"fleet migration: {job_id} member {src.index} -> "
                f"{new_index} at move {job.moves_done}"
            )
            return new_index

    def kill_member(self, index: int, reason: str = "killed") -> None:
        """Chaos hook: model member ``index`` dying NOW (crash-model
        teardown, no journal writes) and absorb the death by
        re-placing its journaled jobs onto survivors."""
        with self.lock:
            member = self.members[index]
            if not member.alive:
                return
            self._absorb_death(member, reason=reason)

    def _absorb_death(self, member: FleetMember, *, reason: str) -> None:
        member.scheduler.abandon()
        member.alive = False
        self._update_gauges()
        log_warn(
            f"fleet member {member.index} died ({reason}); re-placing "
            "its journaled jobs onto survivors"
        )
        if not any(m.alive for m in self.members):
            raise RuntimeError(
                f"fleet member {member.index} died ({reason}) and no "
                "members survive"
            )
        # The dead member's WRITE-AHEAD journal on disk is the
        # authority for what it owned — its in-memory table died with
        # it.  Terminal jobs re-place too (their persisted fluxes ride
        # along), so every accepted job stays owned by an alive member.
        moved = self._replace_from_disk(member.index)
        self.recorder.record(
            "member_death", member=member.index, reason=reason,
            replaced=moved,
        )
        log_info(
            f"fleet member {member.index}: {moved} journaled jobs "
            "re-placed onto survivors"
        )

    def _replace_from_disk(self, index: int, *,
                           link: str = "migrated") -> int:
        """Re-place member ``index``'s JOURNALED jobs onto survivors:
        the on-disk write-ahead journal is the authority for what the
        member owned (its in-memory table is dead or untrustworthy).
        Copies whose assignment already names another member are
        skipped — they are the stale half of an interrupted migration,
        drain, or eviction."""
        mdir = self.journal.member_dir(index)
        doc = SchedulerJournal(mdir).load() or {"jobs": {}}
        moved = 0
        for entry in sorted(
            doc.get("jobs", {}).values(), key=lambda e: e["index"]
        ):
            jid = entry["id"]
            assignment = self._assignments.get(jid)
            if assignment is not None and (
                assignment["member"] != index
            ):
                continue  # stale copy; the assignment names the owner
            self._place(
                jid, entry["shape_key"], entry=entry, src_dir=mdir,
                exclude=(index,), link=link,
            )
            moved += 1
        return moved

    # ------------------------------------------------------------------ #
    # Supervisor eviction (serving/supervisor.py drives these)
    # ------------------------------------------------------------------ #
    def drain_member(self, index: int, *, cause: str) -> int:
        """Cooperatively evict an ALIVE member: park + export every
        job it owns onto healthy peers (``evicted`` trace link), then
        retire the member.  This is the brownout / disk-pressure path
        — the member's scheduler still answers, so its in-memory table
        (not just the on-disk journal) hands the jobs over, including
        a degraded-disk member's unpersisted results.  Callers flush
        ``record_eviction`` FIRST (eviction-record-before-drain)."""
        with self.lock:
            member = self.members[index]
            if not member.alive:
                return 0
            if not any(
                m.alive and m.index != member.index
                for m in self.members
            ):
                raise RuntimeError(
                    f"cannot drain member {index} ({cause}): no other "
                    "alive member to take its jobs"
                )
            src = member.scheduler
            moved = 0
            for job in sorted(src.jobs(), key=lambda j: j.index):
                # park_job (not preempt_job): identical on a healthy
                # disk, but a disk-pressured member frees the slot
                # without a durable checkpoint and resumes from the
                # last committed one (or move 0) — bitwise either way.
                src.park_job(job.id)
                assignment = self._assignments.get(job.id)
                if assignment is not None and (
                    assignment["member"] != member.index
                ):
                    src.drop_job(job.id)
                    continue  # stale copy; the assignment names the owner
                entry = src.export_entry(job.id)
                self._place(
                    job.id, job.shape_key, entry=entry,
                    src_dir=src.journal.dir,
                    exclude=(member.index,), link="evicted",
                )
                target = self.members[
                    self._assignments[job.id]["member"]
                ]
                adopted = target.scheduler.job(job.id)
                if (job.terminal and job.result is not None
                        and adopted.result is None):
                    # Degraded-disk flux loss: the source finished the
                    # job but could not persist its flux — re-persist
                    # from the in-memory result on the adopting member.
                    adopted.result = job.result.copy()
                    adopted.flux_name = target.scheduler.journal.write_flux(
                        job.id, adopted.result
                    )
                    target.scheduler._flush_journal()
                src.drop_job(job.id)
                moved += 1
            src.abandon()
            member.alive = False
            member.health = "evicted"
            member.quarantined = False
            self._update_gauges()
            self.recorder.record(
                "member_evicted", member=member.index, cause=cause,
                replaced=moved, cooperative=True,
            )
            log_warn(
                f"fleet member {member.index} evicted ({cause}): "
                f"{moved} jobs drained onto healthy peers"
            )
            return moved

    def drain_member_from_journal(self, index: int, *,
                                  cause: str) -> int:
        """Evict a WEDGED member: its scheduler no longer answers
        probes, so its in-memory table is untrustworthy — abandon the
        device state and re-place from the on-disk write-ahead journal
        exactly like a member death, but under the supervisor's
        ``evicted`` trace link.  Callers flush ``record_eviction``
        FIRST (eviction-record-before-drain)."""
        with self.lock:
            member = self.members[index]
            if not member.alive:
                return 0
            member.scheduler.abandon()
            member.alive = False
            member.health = "evicted"
            member.quarantined = False
            self._update_gauges()
            if not any(m.alive for m in self.members):
                raise RuntimeError(
                    f"cannot evict wedged member {index} ({cause}): "
                    "no members survive"
                )
            moved = self._replace_from_disk(member.index, link="evicted")
            self.recorder.record(
                "member_evicted", member=member.index, cause=cause,
                replaced=moved, cooperative=False,
            )
            log_warn(
                f"fleet member {member.index} evicted ({cause}): "
                f"{moved} journaled jobs re-placed onto survivors"
            )
            return moved

    # ------------------------------------------------------------------ #
    # The observability plane (obs/aggregate.py, obs/slo.py,
    # obs/profile.py — constructed in __init__, ticked per round)
    # ------------------------------------------------------------------ #
    def _obs_registries(self) -> list:
        """Aggregation sources: every member that EVER had a registry
        (dead members included — their counters must stay in the fold
        so the fleet rollup never moves backwards)."""
        return [
            (f"m{m.index}", m.registry)
            for m in self.members if m.registry is not None
        ]

    def _obs_members(self) -> list:
        """The SLO/profiler view: (index, label, registry, alive)."""
        return [
            (m.index, f"m{m.index}", m.registry, m.alive)
            for m in self.members
        ]

    def fleetstats_path(self) -> str:
        return os.path.join(self.journal.dir, FLEETSTATS_FILE)

    def slo_alerts_by_member(self) -> dict:
        """Active SLO alerts grouped by attributed member — the
        supervisor's advisory input (empty with the plane off)."""
        with self.lock:
            if self.slo is None:
                return {}
            return self.slo.alerts_by_member()

    def obs_tick(self) -> None:
        """One quantum-cadence pass of the observability plane:
        evaluate SLO burn rates (alert edges arm the profiler's
        anomaly capture), sample per-member utilization, and snapshot
        the merged fleet picture atomically to FLEETSTATS.json — a
        dead router still leaves a last-known truth source on disk.
        No-op with PUMI_TPU_FLEET_OBS=off."""
        with self.lock:
            if not self.obs_enabled:
                return
            members = self._obs_members()
            alerts = self.slo.evaluate(members)
            for alert in list(alerts.values()):
                edge = (alert["slo"], alert["since"])
                if edge not in self._seen_alerts:
                    self._seen_alerts.add(edge)
                    self.profiler.on_alert(alert)
            self.profiler.sample(members)
            atomic_write_json(self.fleetstats_path(), {
                "schema": FLEETSTATS_SCHEMA,
                "fleet": self.fleet_json(),
                "slo": self.slo.status(),
                "profile": self.profiler.status(),
                "metrics": self.aggregator.merge(),
                "router_metrics": self.registry.snapshot(),
            })

    # ------------------------------------------------------------------ #
    # The scheduling loop
    # ------------------------------------------------------------------ #
    def step(self) -> bool:
        """One round over every alive member.  An ``InjectedKill``
        from a member's quantum is the chaos campaign's member-death
        model: with ``absorb_member_kills`` the router absorbs it
        (abandon + re-place onto survivors) and keeps serving; without
        it the kill propagates — the whole-process crash model the
        router-kill scenario exercises."""
        with self.lock:
            pending = False
            for member in list(self.members):
                if not member.alive:
                    continue
                if member.scheduler.wedged:
                    # A wedged member holds its jobs but makes no
                    # progress — it still reports pending so the loop
                    # does not declare the fleet drained; only the
                    # supervisor's missed-heartbeat eviction
                    # (serving/supervisor.py) can free the jobs.
                    pending = True
                    continue
                try:
                    pending = member.scheduler.step() or pending
                except InjectedKill:
                    if not self.absorb_member_kills:
                        raise
                    self._absorb_death(member, reason="injected-kill")
                    pending = True
            self._update_gauges()
            self.obs_tick()
            return pending

    def run(self, max_rounds: int = 100000) -> None:
        for _ in range(max_rounds):
            if not self.step():
                return
        raise RuntimeError(
            f"fleet did not drain within {max_rounds} rounds"
        )

    def backpressured(self) -> bool:
        """True when the fleet cannot usefully accept a NEW job right
        now: no alive member, or every alive non-quarantined member
        (falling back to any-alive when the whole fleet is
        quarantined) is at its admission bound.  The gateway turns
        this into a 503 + ``Retry-After`` BEFORE journaling an
        acceptance record — a rejected submission must not burn an
        idempotency key on a job no member would admit."""
        with self.lock:
            candidates = [
                m for m in self.members
                if m.alive and not m.quarantined
            ]
            if not candidates:
                candidates = [m for m in self.members if m.alive]
            if not candidates:
                return True
            return all(
                m.scheduler.max_queued is not None
                and m.scheduler.queue_depth >= m.scheduler.max_queued
                for m in candidates
            )

    # ------------------------------------------------------------------ #
    # Recovery (the router-kill half of the chaos campaign)
    # ------------------------------------------------------------------ #
    @classmethod
    def recover(cls, fleet_dir: str, mesh, config=None, **kwargs):
        """Rebuild a fleet over an existing FLEET.json + member
        journals: every member recovers its own job table
        (``TallyScheduler.recover`` — checkpoint resumes are bitwise),
        then the router reconciles the routing journal against what
        the members actually know, closing both crash windows the
        write-ahead order leaves open (module docstring)."""
        journal = FleetJournal(fleet_dir)
        doc = journal.load()
        if doc is None:
            raise ValueError(
                f"no fleet journal at {journal.path} — nothing to "
                "recover"
            )
        evicted = {
            int(k): dict(v)
            for k, v in doc.get("evicted", {}).items()
        }
        router = cls(
            mesh, config, fleet_dir=fleet_dir,
            n_members=int(doc["members"]), _recover=True,
            _evicted=tuple(sorted(evicted)), **kwargs,
        )
        try:
            with router.lock:
                router._accepted = {
                    str(k): str(v)
                    for k, v in doc.get("accepted", {}).items()
                }
                router._requests = dict(doc.get("requests", {}))
                router._assignments = {
                    k: {"member": int(v["member"]),
                        "migrations": int(v.get("migrations", 0))}
                    for k, v in doc.get("assignments", {}).items()
                }
                router._evicted = evicted
                router._breaches = {
                    int(k): [dict(b) for b in v]
                    for k, v in doc.get("breaches", {}).items()
                }
                router._n_submitted = int(doc.get("n_submitted", 0))
                router._reconcile()
        except BaseException:
            router.abandon()
            raise
        return router

    def _reconcile(self) -> None:
        """Close the write-ahead crash windows after recovery: drop
        stale duplicate copies a mid-migration crash left behind, then
        re-dispatch every journaled-accepted job no alive member
        knows."""
        # (i) A job in a member journal whose assignment names another
        # member is the stale half of an interrupted migration — the
        # adopted copy (journaled before the drop) is the real one.
        for m in self.members:
            if not m.alive:
                continue
            for j in list(m.scheduler.jobs()):
                assignment = self._assignments.get(j.id)
                if assignment is None:
                    # A member knows a job the router never recorded:
                    # impossible under the write-ahead order; heal by
                    # adopting the member's view rather than orphaning
                    # the work.
                    self._assignments[j.id] = {
                        "member": m.index, "migrations": 0,
                    }
                elif assignment["member"] != m.index:
                    log_warn(
                        f"fleet recovery: dropping stale copy of "
                        f"{j.id} from member {m.index} (assigned to "
                        f"member {assignment['member']})"
                    )
                    m.scheduler.drop_job(j.id)
        # (i½) A journaled eviction whose drain the crash interrupted:
        # replay it from the evicted member's on-disk journal.  Jobs
        # the drain already moved have assignments naming their new
        # owner and are skipped; jobs it never reached still carry the
        # evicted member's assignment and re-place now
        # (eviction-record-before-drain's recovery half).
        for idx in sorted(self._evicted):
            if idx < len(self.members) and not self.members[idx].alive:
                self._replace_from_disk(idx, link="evicted")
        # (ii) Journaled-accepted jobs nobody knows: the crash landed
        # between the acceptance/assignment record and the dispatch —
        # the journaled request payload replays it.
        owned = {
            j.id for m in self.members if m.alive
            for j in m.scheduler.jobs()
        }
        for jid in sorted(set(self._assignments) | set(self._requests)):
            if jid in owned:
                self._requests.pop(jid, None)
                continue
            req_json = self._requests.get(jid)
            if req_json is None:  # pragma: no cover - defensive
                log_warn(
                    f"fleet recovery: {jid} assigned but neither "
                    "dispatched nor journaled as a request — lost to "
                    "a pre-journal crash window that should not exist"
                )
                continue
            self._pending[jid] = request_from_json(req_json)
            assignment = self._assignments.get(jid)
            n = np.asarray(req_json["origins"]).reshape(-1, 3).shape[0]
            self._place(
                jid, self._shape_key(n),
                member=(
                    assignment["member"]
                    if assignment is not None
                    and self.members[assignment["member"]].alive
                    else None
                ),
            )
        self._flush_fleet()
        log_info(
            f"fleet recovery: {len(self.members)} members, "
            f"{len(owned)} jobs owned, "
            f"{len(self._accepted)} idempotency keys restored"
        )

    # ------------------------------------------------------------------ #
    # Introspection (gateway + exporter surfaces)
    # ------------------------------------------------------------------ #
    def owner_of(self, job_id: str) -> FleetMember | None:
        assignment = self._assignments.get(job_id)
        if assignment is None:
            return None
        member = self.members[assignment["member"]]
        return member if member.alive else None

    def job(self, job_id: str):
        with self.lock:
            member = self.owner_of(job_id)
            if member is None:
                raise KeyError(job_id)
            return member.scheduler.job(job_id)

    def jobs(self) -> list:
        with self.lock:
            return [
                j for m in self.members if m.alive
                for j in m.scheduler.jobs()
            ]

    def result(self, job_id: str) -> np.ndarray:
        with self.lock:
            member = self.owner_of(job_id)
            if member is None:
                raise KeyError(job_id)
            return member.scheduler.result(job_id)

    def cancel(self, job_id: str) -> bool:
        with self.lock:
            member = self.owner_of(job_id)
            if member is None:
                raise KeyError(job_id)
            return member.scheduler.cancel(job_id)

    def member_of(self, job_id: str) -> int | None:
        with self.lock:
            assignment = self._assignments.get(job_id)
            return None if assignment is None else assignment["member"]

    def progress(self, job_id: str,
                 since: int = -1) -> tuple[list[dict], bool]:
        """Flight records for one job with seq > ``since`` (the shared
        recorder spans every member, so a migrated job's progress is
        one stream) plus its terminal flag — the gateway's streaming
        endpoint polls this."""
        with self.lock:
            member = self.owner_of(job_id)
            if member is None:
                raise KeyError(job_id)
            records = [
                r for r in self.recorder.records()
                if r.get("job") == job_id and r.get("seq", -1) > since
            ]
            return records, member.scheduler.job(job_id).terminal

    def _update_gauges(self) -> None:
        self._members_gauge.set(
            sum(1 for m in self.members if m.alive)
        )
        for m in self.members:
            self._fleet_queue_depth.set(
                m.scheduler.queue_depth if m.alive else 0,
                member=f"m{m.index}",
            )

    def _jobs_json(self, query: dict | None = None) -> dict:
        """Aggregated job table for the exporter's ``/jobs``: every
        member's rows plus the owning member index, capped at
        ``?limit=`` rows (default 500), newest first — same contract
        as the solo scheduler's table."""
        from .scheduler import _jobs_limit

        limit = _jobs_limit(query)
        with self.lock:
            rows = []
            total = 0
            for m in self.members:
                if not m.alive:
                    continue
                table = m.scheduler._jobs_json({"limit": limit})
                total += table["total_jobs"]
                for row in table["jobs"]:
                    rows.append(dict(row, member=m.index))
            # Newest first across members: the per-member submission
            # ordinal is the freshness signal (ids tie-break so the
            # order is total).
            rows.sort(
                key=lambda r: (r["index"], r["id"]), reverse=True
            )
            return {
                "schema": FLIGHT_SCHEMA,
                "queue_depth": sum(
                    m.scheduler.queue_depth
                    for m in self.members if m.alive
                ),
                "resident": sum(
                    m.scheduler.resident_count
                    for m in self.members if m.alive
                ),
                "total_jobs": total,
                "limit": limit,
                "jobs": rows[:limit],
            }

    def fleet_json(self) -> dict:
        """The ``/fleet`` endpoint: routing + liveness view."""
        with self.lock:
            return {
                "schema": FLIGHT_SCHEMA,
                "members": [
                    {
                        "member": m.index,
                        "alive": m.alive,
                        "health": m.health,
                        "quarantined": m.quarantined,
                        "queue_depth": (
                            m.scheduler.queue_depth if m.alive else 0
                        ),
                        "resident": (
                            m.scheduler.resident_count
                            if m.alive else 0
                        ),
                        "placed": m.placed,
                        "jobs": (
                            len(m.scheduler.jobs()) if m.alive else 0
                        ),
                        "warm_classes": sorted(m.warm),
                        "journal": self.journal.member_dir(m.index),
                    }
                    for m in self.members
                ],
                "assignments": len(self._assignments),
                "accepted_keys": len(self._accepted),
                "migrations": int(self._migrations_total.value()),
            }

    def stats(self) -> dict:
        """Fleet summary for serve.py's JSON (per-member placement
        counts included — the chaos campaign asserts over them)."""
        with self.lock:
            all_jobs = [
                j for m in self.members if m.alive
                for j in m.scheduler.jobs()
            ]
            outcomes: dict[str, int] = {}
            for j in all_jobs:
                if j.outcome is not None:
                    outcomes[j.outcome] = outcomes.get(j.outcome, 0) + 1
            return {
                "members": len(self.members),
                "alive": sum(1 for m in self.members if m.alive),
                "jobs": len(all_jobs),
                "outcomes": outcomes,
                "queue_depth": sum(
                    m.scheduler.queue_depth
                    for m in self.members if m.alive
                ),
                "placements": {
                    f"member-{m.index}": m.placed for m in self.members
                },
                "migrations": int(self._migrations_total.value()),
                "retries": sum(j.retries for j in all_jobs),
                "recovered": sum(
                    m.scheduler._n_recovered
                    for m in self.members if m.alive
                ),
                "journal": self.journal.dir,
                "aot": (
                    self.bank.stats() if self.bank is not None else None
                ),
            }

    # ------------------------------------------------------------------ #
    # Teardown
    # ------------------------------------------------------------------ #
    def close(self) -> None:
        """Graceful shutdown: every alive member parks its residents'
        checkpoints and flushes its journal, then the routing journal
        commits last."""
        with self.lock:
            for m in self.members:
                if m.alive:
                    m.scheduler.close()
            self._flush_fleet()
            # Final fleet picture (and close any open anomaly capture)
            # before the exporter goes away.
            if self.profiler is not None:
                self.profiler.stop_capture()
            self.obs_tick()
            if self._exporter is not None:
                self._exporter.stop()
                self._exporter = None

    def abandon(self) -> None:
        """Crash-model teardown: release device state everywhere, no
        journal writes — recovery must work from what the write-ahead
        journals already committed."""
        with self.lock:
            for m in self.members:
                if m.alive:
                    m.scheduler.abandon()
            if self.profiler is not None:
                self.profiler.stop_capture()
            if self._exporter is not None:
                self._exporter.stop()
                self._exporter = None
