"""Tally-as-a-service: the AOT program bank + shape-bucketed scheduler
(ROADMAP item 3).

``ProgramBank`` persists compiled walk/megastep executables to disk per
(shape class x environment section) so a warm server process serves
jobs with ZERO XLA compiles; ``TallyScheduler`` multiplexes concurrent
jobs over one device at megastep-K granularity with convergence-based
early eviction, checkpoint preemption, per-job failure isolation
(transient quanta replay bitwise, persistent failures poison exactly
one job), admission backpressure, and a crash-safe ``JOBS.json``
write-ahead journal (``SchedulerJournal``, ``TallyScheduler.recover``)
so a killed server resumes every job bitwise; ``run_saturation`` is
the shared many-job workload driver behind scripts/serve.py and
bench.py's ``BENCH_SERVE`` probe.
"""
from .bank import ProgramBank, validate_loaded
from .journal import SchedulerJournal
from .saturate import run_saturation, synthetic_requests
from .scheduler import JobRequest, TallyScheduler

__all__ = [
    "JobRequest",
    "ProgramBank",
    "SchedulerJournal",
    "TallyScheduler",
    "run_saturation",
    "synthetic_requests",
    "validate_loaded",
]
