"""Tally-as-a-service: the AOT program bank + shape-bucketed scheduler
plus the multi-chip fleet layer on top (ROADMAP item 3).

``ProgramBank`` persists compiled walk/megastep executables to disk per
(shape class x environment section) so a warm server process serves
jobs with ZERO XLA compiles; ``TallyScheduler`` multiplexes concurrent
jobs over one device at megastep-K granularity with convergence-based
early eviction, checkpoint preemption, per-job failure isolation
(transient quanta replay bitwise, persistent failures poison exactly
one job), admission backpressure, and a crash-safe ``JOBS.json``
write-ahead journal (``SchedulerJournal``, ``TallyScheduler.recover``)
so a killed server resumes every job bitwise; ``FleetRouter`` owns one
scheduler per device behind a write-ahead ``FLEET.json`` routing
journal (idempotent acceptance, crash-safe placement, cross-chip
migration, member-death absorption); ``FleetSupervisor`` closes the
detect-decide-drain loop over it (health-probe-driven eviction,
brownout quarantine, disk-pressure drain — serving/supervisor.py);
``TallyGateway`` is the network
ingress in front of it; ``run_saturation`` / ``run_fleet_saturation``
are the shared many-job workload drivers behind scripts/serve.py and
bench.py's ``BENCH_SERVE`` / ``BENCH_FLEET`` probes.
"""
from .bank import ProgramBank, validate_loaded
from .fleet import FleetJournal, FleetMember, FleetRouter
from .gateway import TallyGateway, decode_result
from .journal import SchedulerJournal
from .saturate import (
    run_fleet_saturation,
    run_saturation,
    synthetic_requests,
)
from .scheduler import JobRequest, TallyScheduler
from .supervisor import FleetSupervisor

__all__ = [
    "FleetJournal",
    "FleetMember",
    "FleetRouter",
    "FleetSupervisor",
    "JobRequest",
    "ProgramBank",
    "SchedulerJournal",
    "TallyGateway",
    "TallyScheduler",
    "decode_result",
    "run_fleet_saturation",
    "run_saturation",
    "synthetic_requests",
    "validate_loaded",
]
