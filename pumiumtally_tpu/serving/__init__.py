"""Tally-as-a-service: the AOT program bank + shape-bucketed scheduler
(ROADMAP item 3).

``ProgramBank`` persists compiled walk/megastep executables to disk per
(shape class x environment section) so a warm server process serves
jobs with ZERO XLA compiles; ``TallyScheduler`` multiplexes concurrent
jobs over one device at megastep-K granularity with convergence-based
early eviction and checkpoint preemption; ``run_saturation`` is the
shared many-job workload driver behind scripts/serve.py and bench.py's
``BENCH_SERVE`` probe.
"""
from .bank import ProgramBank, validate_loaded
from .saturate import run_saturation, synthetic_requests
from .scheduler import JobRequest, TallyScheduler

__all__ = [
    "JobRequest",
    "ProgramBank",
    "TallyScheduler",
    "run_saturation",
    "synthetic_requests",
    "validate_loaded",
]
