"""Self-healing fleet supervisor: health-probe-driven eviction.

ROADMAP item 3 left "health-probe-driven eviction (vs explicit /
injected kills)" open: the fleet healed only when a dispatch RAISED.
A member that silently wedges, slows to a crawl, or fills its disk was
never detected — its jobs stalled forever.  ``FleetSupervisor`` closes
the detect-decide-drain loop with NO explicit kill signal anywhere:

  detect   every ``tick()`` probes each alive member's heartbeat
           (``TallyScheduler.heartbeat`` — the per-chip device_put
           round-trip probe from resilience/coordinator.py) and reads
           its per-quantum latency window
           (``scheduler.recent_quantum_seconds``, fed by the PR 16
           device-time attribution path) and its journal's
           disk-pressure flag (serving/journal.py "Degraded mode").
  decide   members classify into a small state machine::

             healthy ──(probe miss x heartbeat_misses)──▶ wedged
             healthy ──(median quantum > slow_factor x
                        fleet median over `window` quanta)──▶ brownout
             healthy ──(journal.degraded)──▶ disk-pressured
             healthy ──(SLO burn-rate alert attributes this
                        member — obs/slo.py advisory)──▶ slo-burn

           Any unhealthy state QUARANTINES the member first (it keeps
           its jobs and keeps running, but receives no new
           placements — ``FleetRouter._choose`` ranks quarantined
           members strictly last).  Only ``grace_ticks`` CONSECUTIVE
           unhealthy ticks escalate to eviction, and
           ``restore_ticks`` consecutive healthy ticks lift the
           quarantine — the hysteresis that keeps a slow-but-
           recovering member from being false-positively drained.
  drain    eviction journals the decision FIRST
           (``FleetRouter.record_eviction`` → FLEET.json ``evicted``
           map), THEN drains: a wedged member's in-memory table is
           untrustworthy, so its on-disk write-ahead journal re-places
           (``drain_member_from_journal``); a brownout or
           disk-pressured member still answers, so it hands its jobs
           over cooperatively (``drain_member`` — park, export, adopt
           on a healthy peer, drop).  The record-before-drain edge is
           machine-checked by analysis/protolint.py
           (eviction-record-before-drain in PROTOCOLS.json): a
           supervisor crash mid-drain leaves a journaled eviction that
           recovery replays, so no job is ever orphaned or duplicated.

Evicted jobs stay BITWISE equal to the fault-free run: re-placement
rides the same checkpoint-adoption path as cross-chip migration (the
megastep RNG is keyed by the persistent move counter), and a
disk-pressured member's unpersisted state replays from its last
durable checkpoint or from move 0 — both bitwise, since the RNG stream
depends on the counter, not on wall history.  The trace continues
across the hop with an ``evicted`` link event (scripts/teleview.py
accepts it like ``recovered``/``migrated``).

Metrics (on the router's registry, scraped by the router's exporter):

  pumi_member_health{member,state}    1 for the member's current state
                                      (healthy/brownout/wedged/
                                      disk-pressured/slo-burn/
                                      evicted), 0 for the others
  pumi_evictions_total{cause}         evictions by detected cause
  pumi_supervisor_probe_seconds       wall seconds per tick() sweep

Threading: the supervisor is driven SYNCHRONOUSLY (``tick()`` between
scheduling rounds, or ``run()`` which interleaves them) and serializes
on the router's lock — no background thread touches member schedulers,
matching the router's thread model.
"""
from __future__ import annotations

import statistics
import time

from ..utils.log import log_info, log_warn

#: Every state ``pumi_member_health`` reports (module docstring state
#: machine; "evicted" is terminal).  "slo-burn" is the observability
#: plane's advisory state: the member is burning an SLO's error
#: budget (obs/slo.py multi-window burn-rate alert attributed it) —
#: quarantined through the same hysteresis as a latency brownout, but
#: the trigger is the fleet-level objective, not the raw quantum
#: window.
HEALTH_STATES = (
    "healthy", "brownout", "wedged", "disk-pressured", "slo-burn",
    "evicted",
)


class FleetSupervisor:
    """Periodic health sweep over one ``FleetRouter`` (module
    docstring).  Construct it over a live router and either call
    ``tick()`` from your own loop or ``run()`` to drive the fleet to
    drain with supervision interleaved.

    Knobs (all per-tick, so the wall-clock grace scales with however
    often the caller ticks):

      slow_factor       brownout threshold: member median quantum
                        latency > ``slow_factor`` x fleet median
      window            quanta in the sliding latency window (a member
                        needs a full window before it can be judged
                        slow; the fleet needs >= 2 judged members for
                        a median)
      heartbeat_misses  consecutive failed probes before "wedged"
      grace_ticks       consecutive unhealthy ticks tolerated in
                        quarantine before eviction
      restore_ticks     consecutive healthy ticks before a quarantined
                        member is restored
    """

    def __init__(self, router, *, slow_factor: float = 3.0,
                 window: int = 4, heartbeat_misses: int = 2,
                 grace_ticks: int = 2, restore_ticks: int = 2):
        if float(slow_factor) <= 1.0:
            raise ValueError(
                f"slow_factor must be > 1.0: {slow_factor}"
            )
        for name, v in (("window", window),
                        ("heartbeat_misses", heartbeat_misses),
                        ("grace_ticks", grace_ticks),
                        ("restore_ticks", restore_ticks)):
            if int(v) < 1:
                raise ValueError(f"{name} must be >= 1: {v}")
        self.router = router
        self.slow_factor = float(slow_factor)
        self.window = int(window)
        self.heartbeat_misses = int(heartbeat_misses)
        self.grace_ticks = int(grace_ticks)
        self.restore_ticks = int(restore_ticks)
        #: Per-member streak counters: consecutive probe misses,
        #: consecutive healthy ticks, consecutive unhealthy ticks.
        self._track: dict[int, dict] = {}
        r = router.registry
        self._health_gauge = r.gauge(
            "pumi_member_health",
            "1 for the member's current supervisor-classified health "
            "state (healthy/brownout/wedged/disk-pressured/slo-burn/"
            "evicted), 0 for the others — labeled by member and state",
        )
        self._evictions_total = r.counter(
            "pumi_evictions_total",
            "members evicted by the fleet supervisor, labeled by the "
            "detected cause (wedged/brownout/disk-pressured/slo-burn)",
        )
        self._probe_seconds = r.histogram(
            "pumi_supervisor_probe_seconds",
            "wall seconds per supervisor tick (heartbeat probes + "
            "latency classification over every alive member)",
        )
        for m in router.members:
            self._set_health(m)

    # ------------------------------------------------------------------ #
    # Detection
    # ------------------------------------------------------------------ #
    def tick(self) -> None:
        """One detect-decide sweep over every alive member (module
        docstring state machine).  May evict — which re-places jobs
        onto healthy peers and can raise ``RuntimeError`` when none
        survive to take them."""
        t0 = time.perf_counter()
        with self.router.lock:
            members = [m for m in self.router.members if m.alive]
            # The observability plane's advisory signal: active
            # burn-rate alerts attributed to a member (obs/slo.py,
            # evaluated by the router's obs tick).  Empty when the
            # plane is off.
            slo_alerts = self.router.slo_alerts_by_member()
            # Latency view: a member is judged only on a FULL window,
            # and only against a fleet median built from >= 2 judged
            # members — one member alone has nothing to be slower than.
            medians = {}
            for m in members:
                recent = list(m.scheduler.recent_quantum_seconds)
                if len(recent) >= self.window:
                    medians[m.index] = statistics.median(
                        recent[-self.window:]
                    )
            fleet_median = (
                statistics.median(medians.values())
                if len(medians) >= 2 else None
            )
            for m in members:
                track = self._track.setdefault(
                    m.index, {"misses": 0, "ok": 0, "unhealthy": 0}
                )
                beat = m.scheduler.heartbeat()
                track["misses"] = 0 if beat else track["misses"] + 1
                if track["misses"] >= self.heartbeat_misses:
                    state = "wedged"
                elif (m.scheduler.journal is not None
                      and m.scheduler.journal.degraded):
                    state = "disk-pressured"
                elif slo_alerts.get(m.index):
                    # SLO advisory ranks above the raw latency window:
                    # the objective IS the contract, and the breach
                    # record (journaled by _advise_slo before the
                    # quarantine) must cite the SLO signal.
                    state = "slo-burn"
                elif (fleet_median is not None
                      and fleet_median > 0.0
                      and m.index in medians
                      and medians[m.index]
                      > self.slow_factor * fleet_median):
                    state = "brownout"
                else:
                    state = "healthy"
                if state == "slo-burn" and not m.quarantined:
                    self._advise_slo(m, slo_alerts[m.index][0])
                self._apply(m, state, credit=beat)
        self._probe_seconds.observe(time.perf_counter() - t0)

    # ------------------------------------------------------------------ #
    # Decision (hysteresis) + drain
    # ------------------------------------------------------------------ #
    def _apply(self, member, state: str, *, credit: bool) -> None:
        """Fold one tick's classification into the member's streaks:
        quarantine on the first unhealthy tick, evict after
        ``grace_ticks`` consecutive ones, restore after
        ``restore_ticks`` consecutive healthy ticks.  A healthy
        classification with a MISSED probe (``credit=False`` — below
        the wedged deadline but suspect) neither breaks nor builds the
        healthy streak."""
        track = self._track[member.index]
        if state == "healthy":
            track["unhealthy"] = 0
            if credit:
                track["ok"] += 1
            if member.quarantined and track["ok"] >= self.restore_ticks:
                member.quarantined = False
                member.health = "healthy"
                self.router.recorder.record(
                    "member_restored", member=member.index,
                )
                log_info(
                    f"fleet member {member.index} restored to healthy "
                    f"after {track['ok']} clean ticks — quarantine "
                    "lifted, jobs untouched"
                )
            elif not member.quarantined:
                member.health = "healthy"
            self._set_health(member)
            return
        track["ok"] = 0
        track["unhealthy"] += 1
        member.health = state
        if not member.quarantined:
            self._quarantine(member, state)
        self._set_health(member)
        if track["unhealthy"] > self.grace_ticks:
            self._evict(member, state)

    def _quarantine(self, member, state: str) -> None:
        """Flip one member into quarantine (no new placements, jobs
        keep running) and record the decision with the state that
        triggered it."""
        member.quarantined = True
        self.router.recorder.record(
            "member_quarantined", member=member.index, state=state,
        )
        log_warn(
            f"fleet member {member.index} quarantined ({state}): "
            "no new placements; eviction after "
            f"{self.grace_ticks} more unhealthy ticks"
        )

    def _advise_slo(self, member, alert: dict) -> None:
        """Act on one SLO burn-rate attribution: journal the breach
        advisory to FLEET.json FIRST, then quarantine the offender
        (breach-record-before-quarantine, PROTOCOLS.json,
        protolint-checked) — the quarantine must be explainable from
        the routing journal alone even if the process dies right
        after the flag flips.  Eviction/restore hysteresis stays with
        ``_apply``: this is an advisory entry point, not a second
        state machine."""
        self.router.record_breach(member.index, alert)
        member.health = "slo-burn"
        self._quarantine(member, "slo-burn")

    def _evict(self, member, cause: str) -> int:
        """Evict one member: journal the decision, THEN drain its
        jobs onto healthy peers.  The order is the crash-safety
        contract (eviction-record-before-drain, PROTOCOLS.json,
        protolint-checked): a journaled eviction whose drain never ran
        is replayed at recovery from the member's on-disk journal;
        reversed, a crash after the drain but before the record would
        leave re-placed jobs under a member the routing journal still
        calls healthy."""
        self.router.record_eviction(member.index, cause)
        if cause == "wedged":
            # The member answers nothing — its in-memory table is
            # untrustworthy; the on-disk write-ahead journal re-places.
            moved = self.router.drain_member_from_journal(
                member.index, cause=cause
            )
        else:
            # Brownout / disk pressure: the scheduler still answers,
            # so it hands its jobs over cooperatively (including a
            # degraded-disk member's unpersisted results).
            moved = self.router.drain_member(member.index, cause=cause)
        self._evictions_total.inc(cause=cause)
        self._set_health(member)
        self._track.pop(member.index, None)
        return moved

    def _set_health(self, member) -> None:
        for state in HEALTH_STATES:
            self._health_gauge.set(
                1.0 if member.health == state else 0.0,
                member=f"m{member.index}", state=state,
            )

    # ------------------------------------------------------------------ #
    # The supervised scheduling loop
    # ------------------------------------------------------------------ #
    def step(self) -> bool:
        """One scheduling round + one supervision sweep.  Returns True
        while any accepted job is non-terminal — including jobs held
        by a wedged member the router's own loop cannot advance, so a
        supervised fleet never declares itself drained while work is
        stuck behind a pending eviction."""
        pending = self.router.step()
        self.tick()
        return pending or any(
            not j.terminal for j in self.router.jobs()
        )

    def run(self, max_rounds: int = 100000) -> None:
        for _ in range(max_rounds):
            if not self.step():
                return
        raise RuntimeError(
            f"supervised fleet did not drain within {max_rounds} "
            "rounds"
        )
