"""Crash-safe scheduler journal: the ``JOBS.json`` write-ahead log.

A served job must survive the server, not just the device: one SIGKILL
mid-run previously lost every queued and resident job the scheduler
held in memory.  This module persists the scheduler's whole job table
through a single-file write-ahead journal so a fresh process can
``TallyScheduler.recover(journal_dir)`` and continue every job —
bitwise, because the megastep RNG is keyed by the persistent move
counter the PR 2 checkpoints carry.

Layout — one directory per scheduler::

  <journal_dir>/JOBS.json            the journal document (atomic
                                     tmp+fsync+rename on every flush —
                                     a crash leaves the previous
                                     committed document, never a torn
                                     one)
  <journal_dir>/<job>.ckpt.npz       the job's latest quantum-boundary
                                     checkpoint (the PR 2 atomic
                                     writer; doubles as the preemption
                                     checkpoint when journaling is on)
  <journal_dir>/<job>.flux.npy       the finished job's raw flux
                                     (atomic), so results survive the
                                     process that computed them

Document format (schema 2; schema-1 documents from pre-tracing
processes still load — the added fields default)::

  {"schema": 2, "quantum_moves": K,
   "jobs": {job_id: {id, index, state: "pending"|"done", outcome,
                     error, shape_key, n, padded_n, moves_done,
                     preemptions, retries, checkpoint, flux,
                     trace_id, device_seconds,
                     request: {...}}}}

Schema 2 persists each job's ``trace_id`` (so a recovered job
CONTINUES its distributed trace across the crash — obs/trace.py) and
its accumulated ``device_seconds`` attribution.  The span stream
itself goes to ``<journal_dir>/TRACE.jsonl`` (append-only JSONL,
best-effort: a torn tail line is skipped by readers).

Write-ahead discipline: the journal is flushed AFTER every state
transition (submit/reject/quantum/preempt/finish/poison) and each
resident job's checkpoint is written BEFORE the flush that references
it.  The two writes are individually atomic but not jointly: a crash
between them leaves a journal whose ``moves_done`` lags the checkpoint
on disk.  That skew is harmless by construction — the checkpoint
carries its own move counter, recovery re-reads it at restore time,
and replaying quanta a stale journal forgot is bitwise (the RNG stream
is keyed by the counter, not by wall history).

Degraded mode: a durable write failing with an ENOSPC-class errno
(disk full / quota exceeded) marks the journal ``degraded`` instead of
propagating out of the flush path — the scheduler's in-memory job
table is intact, and crashing over it would turn a full disk into lost
work. While degraded, flushes and flux persists are skipped (the
on-disk document freezes at the last committed state), the owning
scheduler parks its residents at the next quantum boundary, and the
fleet supervisor drains the member by exporting its jobs to healthy
peers (serving/supervisor.py). The flag is sticky for the journal's
lifetime: a disk does not un-fill under a process that keeps writing,
and recovery after an operator clears space is a fresh process.

Request payloads round-trip EXACTLY: Python's json emits floats via
``repr`` (shortest round trip), so float64 origins/weights come back
bit-identical, and ``SourceParams.tables()`` coerces the
string-keyed region dicts json produces back to integer classes.
Requests are serialized ONCE at submit and the dict reused on every
flush, but each flush still rewrites the whole document — the
single-file layout trades O(jobs) flush cost for atomicity, sized for
the current single-chip fleet scale (sharding the journal like the
checkpoint store is the known next step if job counts grow).
"""
from __future__ import annotations

import dataclasses
import errno
import io
import json
import os
import re

import numpy as np

from ..utils.checkpoint import atomic_write_bytes, atomic_write_json
from ..utils.log import log_warn

#: The errnos that mean "the disk is full", not "the write is wrong":
#: these degrade the journal instead of crashing the scheduler.
DISK_FULL_ERRNOS = (errno.ENOSPC, errno.EDQUOT)

JOURNAL_SCHEMA = 2
#: Schemas this reader accepts (older documents lack trace fields,
#: which recovery defaults).
JOURNAL_SCHEMAS_READABLE = (1, 2)
JOURNAL_FILE = "JOBS.json"
TRACE_FILE = "TRACE.jsonl"

# Journaled job ids become filenames — refuse anything that cannot be
# one (path separators, parent-dir tricks) before it is persisted.
_SAFE_ID = re.compile(r"[A-Za-z0-9._-]{1,128}")


def check_job_id(job_id: str) -> str:
    if not _SAFE_ID.fullmatch(job_id) or job_id in (".", ".."):
        raise ValueError(
            f"job id {job_id!r} is not journal-safe (allowed: "
            "1-128 chars of [A-Za-z0-9._-])"
        )
    return job_id


# --------------------------------------------------------------------- #
# Request (de)serialization
# --------------------------------------------------------------------- #
def request_to_json(request) -> dict:
    """One JobRequest as a json-safe dict (module docstring contract:
    float64 payloads survive bitwise through repr round-trip)."""
    from ..ops.source import SourceParams

    origins = np.asarray(request.origins, np.float64).reshape(-1, 3)
    src = request.source
    if src is not None and not isinstance(src, SourceParams):
        raise TypeError(
            "journaling serves SourceParams sources only; got "
            f"{type(src).__name__} (a custom source object cannot be "
            "reconstructed by a fresh recovery process)"
        )
    return {
        "origins": origins.tolist(),
        "n_moves": int(request.n_moves),
        "weights": (
            None if request.weights is None
            else np.asarray(request.weights, np.float64)
            .reshape(-1).tolist()
        ),
        "groups": (
            None if request.groups is None
            else np.asarray(request.groups, np.int32)
            .reshape(-1).tolist()
        ),
        "source": (
            None if src is None else dataclasses.asdict(src)
        ),
        "job_id": request.job_id,
        "trace_id": getattr(request, "trace_id", None),
    }


def request_from_json(d: dict):
    from ..ops.source import SourceParams
    from .scheduler import JobRequest

    src = d.get("source")
    return JobRequest(
        origins=np.asarray(d["origins"], np.float64).reshape(-1, 3),
        n_moves=int(d["n_moves"]),
        source=None if src is None else SourceParams(**src),
        weights=(
            None if d.get("weights") is None
            else np.asarray(d["weights"], np.float64)
        ),
        groups=(
            None if d.get("groups") is None
            else np.asarray(d["groups"], np.int32)
        ),
        job_id=d.get("job_id"),
        trace_id=d.get("trace_id"),
    )


# --------------------------------------------------------------------- #
# The journal
# --------------------------------------------------------------------- #
class SchedulerJournal:
    """Atomic JOBS.json document plus the per-job checkpoint/flux
    side files (module docstring layout).  The scheduler is the single
    writer; recovery is the single reader."""

    def __init__(self, dirname: str):
        self.dir = str(dirname)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, JOURNAL_FILE)
        #: Sticky disk-pressure flag (module docstring "Degraded
        #: mode"): set by the first ENOSPC-class durable-write failure;
        #: while set, flush/write_flux are skipped instead of raising.
        self.degraded = False
        #: Optional fault injector — or a zero-arg provider returning
        #: one — whose ``maybe_disk_full`` gates every durable write.
        #: The owning scheduler wires a provider so an injector
        #: swapped in mid-run (the chaos harness pattern) still gates.
        self.faults = None
        #: Optional ``(op, exc) -> None`` callback fired once, on the
        #: transition into degraded mode (the scheduler hangs metrics
        #: and flight-recorder notes off it).
        self.on_degraded = None

    def note_disk_failure(self, op: str, exc: OSError) -> None:
        """Record an ENOSPC-class failure of durable write ``op`` and
        enter degraded mode (idempotent; first transition logs and
        fires ``on_degraded``)."""
        if self.degraded:
            return
        self.degraded = True
        log_warn(
            "journal degraded: durable write failed with disk "
            "pressure — freezing the on-disk document and parking "
            "residents (serving/journal.py 'Degraded mode')",
            dir=self.dir, op=op, error=str(exc),
        )
        if self.on_degraded is not None:
            self.on_degraded(op, exc)

    def _gate_durable(self) -> None:
        """Fault-injection gate for one durable write
        (``disk_full_at:N``); raises the injected ENOSPC."""
        faults = self.faults() if callable(self.faults) else self.faults
        if faults is not None:
            faults.maybe_disk_full()

    # -- side files ---------------------------------------------------- #
    def checkpoint_path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.ckpt.npz")

    def flux_path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.flux.npy")

    def trace_path(self) -> str:
        """The journal-local span sink (TRACE.jsonl): every process
        lifetime serving this journal appends to the same stream, so
        teleview can reconstruct a cross-crash trace from one dir."""
        return os.path.join(self.dir, TRACE_FILE)

    def blackbox_path(self, tag: str) -> str:
        """Where a postmortem black box for ``tag`` (a job id or a
        shutdown reason) lands inside the journal dir."""
        return os.path.join(self.dir, f"{tag}.blackbox.json")

    def write_flux(self, job_id: str, arr: np.ndarray) -> str | None:
        """Persist one finished job's raw flux atomically; returns the
        journal-relative name the document records, or None when the
        disk is full (degraded mode — the result stays in memory and a
        draining supervisor re-persists it on the adopting member)."""
        if self.degraded:
            return None
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr))
        try:
            self._gate_durable()
            atomic_write_bytes(self.flux_path(job_id), buf.getvalue())
        except OSError as exc:
            if exc.errno not in DISK_FULL_ERRNOS:
                raise
            self.note_disk_failure("flux persist", exc)
            return None
        return os.path.basename(self.flux_path(job_id))

    def load_flux(self, job_id: str) -> np.ndarray | None:
        path = self.flux_path(job_id)
        if not os.path.exists(path):
            return None
        return np.load(path)

    def remove_sidefiles(self, job_id: str, *, flux: bool = False) -> None:
        paths = [self.checkpoint_path(job_id)]
        if flux:
            paths.append(self.flux_path(job_id))
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass

    # -- the document -------------------------------------------------- #
    def flush(self, entries: list[dict], *, quantum_moves: int) -> None:
        if self.degraded:
            return
        doc = {
            "schema": JOURNAL_SCHEMA,
            "quantum_moves": int(quantum_moves),
            "jobs": {e["id"]: e for e in entries},
        }
        try:
            self._gate_durable()
            atomic_write_json(self.path, doc)
        except OSError as exc:
            if exc.errno not in DISK_FULL_ERRNOS:
                raise
            self.note_disk_failure("journal flush", exc)

    def load(self) -> dict | None:
        """The committed document, or None when no journal exists yet.
        A parse failure is a real error (the atomic writer cannot tear
        the file — unreadable means someone else wrote it)."""
        if not os.path.exists(self.path):
            return None
        with open(self.path) as fh:
            doc = json.load(fh)
        if (not isinstance(doc, dict)
                or doc.get("schema") not in JOURNAL_SCHEMAS_READABLE):
            raise ValueError(
                f"journal {self.path}: schema "
                f"{doc.get('schema') if isinstance(doc, dict) else doc!r}"
                f" not in {JOURNAL_SCHEMAS_READABLE}"
            )
        return doc
