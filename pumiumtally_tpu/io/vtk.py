"""VTK output for tet meshes with cell data.

Replaces Omega_h::vtk::write_parallel (pumipic_particle_data_structure
.cpp:704): writes XML VTK UnstructuredGrid (.vtu) files, plus a .pvtu index
when the tally is produced by multiple hosts/pieces (the reference's
"parallel VTK" advertised in README.md:10). Pure Python/numpy — IO is glue,
not a hot path.
"""
from __future__ import annotations

import base64
import os
import struct

import numpy as np

_VTK_TETRA = 10


def _b64(arr: np.ndarray) -> str:
    raw = arr.tobytes()
    header = struct.pack("<I", len(raw))
    return base64.b64encode(header + raw).decode("ascii")


def _data_array(name: str, arr: np.ndarray, n_components: int = 1) -> str:
    if arr.dtype == np.float64:
        vtype = "Float64"
    elif arr.dtype == np.float32:
        vtype = "Float32"
    elif arr.dtype == np.int64:
        vtype = "Int64"
    elif arr.dtype == np.int32:
        vtype = "Int32"
    elif arr.dtype == np.uint8:
        vtype = "UInt8"
    else:
        arr = arr.astype(np.float64)
        vtype = "Float64"
    comp = f' NumberOfComponents="{n_components}"' if n_components != 1 else ""
    return (
        f'<DataArray type="{vtype}" Name="{name}"{comp} format="binary">\n'
        f"{_b64(np.ascontiguousarray(arr))}\n</DataArray>\n"
    )


def write_vtu(
    filename: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    cell_data: dict[str, np.ndarray] | None = None,
) -> None:
    """Write one UnstructuredGrid piece with per-cell scalar fields."""
    coords = np.asarray(coords, dtype=np.float64)
    tet2vert = np.asarray(tet2vert, dtype=np.int64)
    ncell, nvert = tet2vert.shape[0], coords.shape[0]
    cell_data = cell_data or {}

    parts = [
        '<?xml version="1.0"?>\n'
        '<VTKFile type="UnstructuredGrid" version="1.0" '
        'byte_order="LittleEndian" header_type="UInt32">\n'
        "<UnstructuredGrid>\n"
        f'<Piece NumberOfPoints="{nvert}" NumberOfCells="{ncell}">\n'
    ]
    parts.append("<Points>\n")
    parts.append(_data_array("Points", coords, n_components=3))
    parts.append("</Points>\n<Cells>\n")
    parts.append(_data_array("connectivity", tet2vert.ravel()))
    parts.append(
        _data_array("offsets", (np.arange(ncell, dtype=np.int64) + 1) * 4)
    )
    parts.append(
        _data_array("types", np.full(ncell, _VTK_TETRA, dtype=np.uint8))
    )
    parts.append("</Cells>\n<CellData>\n")
    for name, arr in cell_data.items():
        parts.append(_data_array(name, np.asarray(arr)))
    parts.append("</CellData>\n</Piece>\n</UnstructuredGrid>\n</VTKFile>\n")

    with open(filename, "w") as f:
        f.write("".join(parts))


def write_pvtu(
    filename: str,
    piece_files: list[str],
    cell_data_names: list[str],
    float_type: str = "Float64",
) -> None:
    """Write the parallel index referencing per-host .vtu pieces."""
    parts = [
        '<?xml version="1.0"?>\n'
        '<VTKFile type="PUnstructuredGrid" version="1.0" '
        'byte_order="LittleEndian">\n'
        '<PUnstructuredGrid GhostLevel="0">\n'
        "<PPoints>\n"
        f'<PDataArray type="{float_type}" Name="Points" NumberOfComponents="3"/>\n'
        "</PPoints>\n<PCellData>\n"
    ]
    for name in cell_data_names:
        parts.append(f'<PDataArray type="{float_type}" Name="{name}"/>\n')
    parts.append("</PCellData>\n")
    for piece in piece_files:
        parts.append(f'<Piece Source="{os.path.basename(piece)}"/>\n')
    parts.append("</PUnstructuredGrid>\n</VTKFile>\n")
    with open(filename, "w") as f:
        f.write("".join(parts))


def write_legacy_vtk(
    filename: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    cell_data: dict[str, np.ndarray] | None = None,
) -> None:
    """Write legacy ASCII VTK ('# vtk DataFile') — the format VTK readers
    select for a .vtk extension."""
    coords = np.asarray(coords, dtype=np.float64)
    tet2vert = np.asarray(tet2vert, dtype=np.int64)
    ncell = tet2vert.shape[0]
    cell_data = cell_data or {}
    with open(filename, "w") as f:
        f.write("# vtk DataFile Version 3.0\n")
        f.write("pumiumtally_tpu flux tally\nASCII\n")
        f.write("DATASET UNSTRUCTURED_GRID\n")
        f.write(f"POINTS {coords.shape[0]} double\n")
        np.savetxt(f, coords, fmt="%.17g")
        f.write(f"CELLS {ncell} {ncell * 5}\n")
        cells = np.column_stack(
            [np.full(ncell, 4, dtype=np.int64), tet2vert]
        )
        np.savetxt(f, cells, fmt="%d")
        f.write(f"CELL_TYPES {ncell}\n")
        np.savetxt(f, np.full(ncell, _VTK_TETRA, dtype=np.int64), fmt="%d")
        if cell_data:
            f.write(f"CELL_DATA {ncell}\n")
            for name, arr in cell_data.items():
                f.write(f"SCALARS {name} double 1\nLOOKUP_TABLE default\n")
                np.savetxt(f, np.asarray(arr, dtype=np.float64), fmt="%.17g")


def write_flux_vtk(
    filename: str,
    mesh,
    normalized_flux: np.ndarray,
    volumes: np.ndarray | None = None,
    rel_err: np.ndarray | None = None,
) -> None:
    """Write the finalized tally in the reference's output layout: one
    'flux_group_<g>' cell field per energy group plus a 'volume' field
    (finalizeAndWritePumiFlux, cpp:685-705). The format follows the
    extension: .vtu → XML UnstructuredGrid, .vtk → legacy ASCII.

    ``rel_err`` (the [ntet, n_groups] per-bin relative error from the
    convergence accumulators — ``tally.relative_error()``) additionally
    writes one 'rel_err_group_<g>' cell field next to each flux group,
    so the uncertainty map rides the same file as the answer it
    qualifies."""
    normalized_flux = np.asarray(normalized_flux)
    cell_data: dict[str, np.ndarray] = {}
    for g in range(normalized_flux.shape[1]):
        cell_data[f"flux_group_{g}"] = normalized_flux[:, g, 0]
    if rel_err is not None:
        rel_err = np.asarray(rel_err)
        if rel_err.shape != normalized_flux.shape[:2]:
            raise ValueError(
                f"rel_err must be [ntet, n_groups] = "
                f"{normalized_flux.shape[:2]}, got {rel_err.shape}"
            )
        for g in range(rel_err.shape[1]):
            cell_data[f"rel_err_group_{g}"] = rel_err[:, g]
    cell_data["volume"] = (
        np.asarray(volumes)
        if volumes is not None
        else np.asarray(mesh.volumes)
    )
    if not filename.endswith((".vtu", ".vtk")):
        filename += ".vtu"
    writer = write_legacy_vtk if filename.endswith(".vtk") else write_vtu
    writer(
        filename,
        np.asarray(mesh.coords, dtype=np.float64),
        np.asarray(mesh.tet2vert, dtype=np.int64),
        cell_data,
    )
