"""Compile-time performance contracts (layer 3).

No TPU headline number has been captured since round 4, so a perf
regression in the walk / megastep / Pallas programs is invisible until
a rare hardware window — unless it is visible in the *compiled program
itself*.  XLA's ``lower().compile().cost_analysis()`` and
``memory_analysis()`` expose flops, transcendentals, bytes accessed and
the argument/output/temp/alias memory split on the CPU backend, for the
exact programs the facades dispatch.  This layer compiles the five
program families (trace, trace_packed, megastep, the packed partitioned
step, the Pallas kernel in interpret mode) on the pinned
cpu/8-device/x64-off lint environment at a small ladder of shapes and
gates three kinds of contract:

Baseline-free invariants (``check_cost``) — hold with no committed
capture at all:

  cost.f64.<family>       zero f64-typed ops in the optimized HLO of an
                          f32-config program (under an x64-capable
                          runtime an audit-path f64 leak compiles real
                          f64 flops into the hot loop; under the pinned
                          x64-off env this doubles as a pin that the
                          lint environment itself stayed f32).
  cost.donation.<family>  the aliased (donated) byte count covers the
                          flux accumulator — a dropped donation shows
                          up here as alias_bytes collapsing below the
                          analytic flux size, i.e. a peak-memory jump
                          of exactly one accumulator.
  cost.peak.<family>      temp (and hence peak = args + outputs + temp
                          - alias) memory stays inside an analytic
                          allowance derived from the donated flux, the
                          per-lane state and the mesh tables — a lost
                          fusion that materializes a big intermediate
                          breaks it.
  cost.vmem.pallas        ``walk_pallas.kernel_vmem_bytes`` (the
                          auto-fallback budget estimator) stays within
                          tolerance of this module's own analytic tile
                          footprint — the two are a deliberately
                          duplicated contract mirror, so an estimator
                          edit that forgets a term is named here.
  cost.scaling.<axis>.<family>
                          fitted log-log scaling exponents of flops /
                          bytes / temp across the shape ladder stay
                          sublinear-or-linear in ``n_particles`` and
                          ``ntet`` — an accidental O(n^2) broadcast or
                          a lost fusion becomes a named CI failure
                          (clean programs measure <= 1.0; the gate is
                          ``SCALING_MAX``).

Committed-baseline drift (``diff_cost``) — the full resource signature
(metrics, per-segment normalized costs, exponents) is diffed against
``PERF_CONTRACTS.json`` with per-metric tolerance bands (``DRIFT_TOL``:
flop counts are near-exact, temp memory is allowed scheduler slack).
Intentional changes regenerate the capture with ``python
scripts/lint.py --write-perf-contracts`` (and say why in the PR);
``scripts/perfdiff.py`` pretty-prints the old->new delta for the PR
description.

Like CONTRACTS.json the capture is environment-pinned (backend, device
count, x64) and ``diff_cost`` refuses cross-environment compares.
Everything here runs on CPU in seconds — every future perf PR gets a
hardware-free regression gate.
"""
from __future__ import annotations

import json
import re

from . import Finding
from . import contracts as C

PERF_CONTRACTS_FILE = "PERF_CONTRACTS.json"

FAMILIES = ("megastep", "pallas", "partitioned", "trace", "trace_packed")

# The shape ladder: n_particles at fixed mesh, mesh cells at fixed
# n_particles.  First rung of each axis is the contracts base shape
# (_N, _CELLS) — shared, so the lint runner compiles it once.
LADDER_N = (16, 64, 256)
LADDER_CELLS = (2, 3, 4)  # box(c) -> ntet = 6 * c**3

# Superlinear-growth gate on fitted exponents.  Clean programs measure
# <= 1.0 on every axis (the walk is linear in lanes; flux/table traffic
# is linear in ntet); 1.35 leaves fit noise while an accidental
# quadratic broadcast fits ~2.0.
SCALING_MAX = {"n_particles": 1.35, "ntet": 1.35}
SCALING_METRICS = ("flops", "bytes_accessed", "temp_bytes")
# Absolute drift band on committed exponents.
SCALING_TOL = 0.10

# Per-metric relative tolerance bands for diff against the committed
# capture.  Flop/op counts are properties of the optimized HLO and are
# near-exact across runs; byte counts and especially temp memory absorb
# scheduler/layout slack across jaxlib point releases.
DRIFT_TOL = {
    "flops": 0.02,
    "transcendentals": 0.02,
    "bytes_accessed": 0.05,
    "arg_bytes": 0.0,
    "out_bytes": 0.0,
    "alias_bytes": 0.0,
    "temp_bytes": 0.25,
    "peak_bytes": 0.10,
    "f64_ops": 0.0,
}

# kernel_vmem_bytes vs. the analytic tile footprint mirror.
VMEM_TOL = 0.20

# Fixed slack of the temp-memory allowance: XLA's own small scratch
# (sort buffers, reduction scratch) independent of problem size.
TEMP_SLACK_BYTES = 64 * 1024

# -- contract mirror of the Pallas kernel's VMEM layout ---------------- #
# Deliberately DUPLICATED from ops/walk_pallas.py (TABLE_COLS /
# DEFAULT_LANE_BLOCK / kernel_vmem_bytes): the estimator gates the
# auto-fallback policy, this mirror gates the estimator.  If the kernel
# layout changes, both must change in the same PR — that is the point.
_MIRROR_TABLE_COLS = 28
_MIRROR_LANE_BLOCK = 128


def pallas_footprint_bytes(ntet, n_particles, n_groups, itemsize) -> int:
    """Analytic VMEM working set of one kernel launch: decoded walk
    table + flux tiles (operand, accumulator, output) + per-lane state
    + per-block one-hot / peel temporaries."""
    b = min(_MIRROR_LANE_BLOCK, max(n_particles, 1))
    table = ntet * _MIRROR_TABLE_COLS * itemsize
    flux = 3 * ntet * n_groups * 2 * itemsize
    lanes = n_particles * (10 * itemsize + 9 * 4)
    blocks = b * ntet * itemsize + b * b + b * 2 * n_groups * itemsize
    return table + flux + lanes + blocks


# --------------------------------------------------------------------- #
# Metric extraction from one compiled program
# --------------------------------------------------------------------- #
def fresh_compile(lowered):
    """Compile one ``.lower()`` result with the persistent compilation
    cache bypassed: an executable DESERIALIZED from the cache reports
    an empty aliasing plan (``alias_size_in_bytes == 0``) and slightly
    different temp sizes, which would fake a dropped donation on warm
    runs and make any capture depend on cache state.  Unsetting the dir
    alone is not enough — the cache module keeps serving once
    initialized — so the cache is also reset; restoring the dir
    afterwards lets the host process re-initialize it lazily (the
    on-disk entries survive).  Shared by :func:`compile_metrics`, the
    :func:`check_aot` gate, and the serving program bank's compile
    path (serving/bank.py)."""
    import jax
    from jax.experimental.compilation_cache import (
        compilation_cache as _cc,
    )

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()
    try:
        return lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def compile_metrics(traced, keep=None, key=None) -> dict:
    """Compile one ``jax.jit(...).trace(...)`` result on the current
    backend and extract its resource signature.  Unlike the contracts
    layer this DOES invoke the backend compiler (still CPU-only, still
    no execution) — that is where flop counts and the memory plan live.
    The compile bypasses the persistent compilation cache
    (:func:`fresh_compile`) so the capture is byte-stable across fresh
    processes.  ``keep[key]`` retains the compiled executable for a
    caller that wants to reuse it (the lint runner hands the base-rung
    compiles to :func:`check_aot` instead of compiling twice)."""
    compiled = fresh_compile(traced.lower())
    if keep is not None:
        keep[key] = compiled
    ca = compiled.cost_analysis()
    props = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    mem = compiled.memory_analysis()
    arg = int(getattr(mem, "argument_size_in_bytes", 0))
    out = int(getattr(mem, "output_size_in_bytes", 0))
    temp = int(getattr(mem, "temp_size_in_bytes", 0))
    alias = int(getattr(mem, "alias_size_in_bytes", 0))
    # Optimized-HLO f64 census: every f64-typed value in the compiled
    # module (the per-dtype flop split XLA does not expose; any f64 op
    # in an f32-config program is a contract break regardless).
    f64_ops = len(re.findall(r"f64\[", compiled.as_text()))
    return {
        "flops": int(props.get("flops", 0)),
        "transcendentals": int(props.get("transcendentals", 0)),
        "bytes_accessed": int(props.get("bytes accessed", 0)),
        "arg_bytes": arg,
        "out_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "peak_bytes": arg + out + temp - alias,
        "f64_ops": f64_ops,
    }


def family_analytic(
    family,
    *,
    n,
    cells,
    n_groups=C._G,
    itemsize=4,
    max_local=None,
) -> dict:
    """Analytic resource model of one family at one rung — the
    baseline-free side of every memory check.  All quantities are
    per-device (the partitioned step's memory_analysis reports
    per-shard sizes)."""
    ntet = 6 * cells**3
    n_moves = 4 if family == "megastep" else 1
    if family == "partitioned":
        if max_local is None:
            raise ValueError(
                "partitioned analytic needs max_local (owned + halo "
                "tets per part, from partition_mesh)"
            )
        flux = max_local * n_groups * 2 * itemsize
        # Per-part staging record + migration scratch, with margin.
        lanes = C.partitioned_cap(n) * 128
        table = max_local * _MIRROR_TABLE_COLS * itemsize
        blocks = 0
    else:
        flux = ntet * n_groups * 2 * itemsize
        # Positions/dest (6 floats), weight, travel + int lane state,
        # with margin (the megastep adds RNG counters per lane).
        lanes = n * 80
        table = ntet * _MIRROR_TABLE_COLS * itemsize
        blocks = 0
        if family == "pallas":
            b = min(_MIRROR_LANE_BLOCK, max(n, 1))
            blocks = (
                b * ntet * itemsize + b * b
                + b * 2 * n_groups * itemsize
            )
    return {
        "family": family,
        "n": n,
        "cells": cells,
        "ntet": ntet,
        "n_groups": n_groups,
        "itemsize": itemsize,
        "n_moves": n_moves,
        "flux_bytes": flux,
        "lane_bytes": lanes,
        "table_bytes": table,
        "block_bytes": blocks,
    }


def temp_allowance_bytes(analytic: dict) -> int:
    """Analytic ceiling on a program's temp memory: a few copies of the
    flux accumulator and the lane state (double buffering, packing), the
    mesh tables once or twice, the Pallas block temporaries, plus fixed
    scratch slack.  At the tiny base rung the fixed slack dominates, so
    the peak gate is ALSO applied at the top n_particles rung, where the
    analytic terms dominate and a materialized O(n*ntet) or O(n^2)
    intermediate — or a duplicated flux accumulator — overflows the
    allowance instead of hiding under the slack."""
    return (
        TEMP_SLACK_BYTES
        + 4 * (analytic["flux_bytes"] + analytic["lane_bytes"])
        + 2 * analytic["table_bytes"]
        + 4 * analytic["block_bytes"]
    )


def rung_signature(metrics: dict, analytic: dict) -> dict:
    """metrics + per-segment normalized costs + the analytic context
    they are checked against, for one (family, rung) compile.

    "Segment" here is one modeled lane-move: HLO cost analysis counts
    the walk while-body once (trip counts are dynamic), so the honest
    normalization unit is lanes x fused moves, not physical segments.
    """
    seg = max(analytic["n"] * analytic["n_moves"], 1)
    return {
        "metrics": metrics,
        "normalized": {
            "flops_per_segment": round(metrics["flops"] / seg, 2),
            "bytes_per_segment": round(
                metrics["bytes_accessed"] / seg, 2
            ),
        },
        "analytic": analytic,
    }


# --------------------------------------------------------------------- #
# Scaling fits
# --------------------------------------------------------------------- #
def fit_exponent(sizes, values) -> float:
    """Least-squares slope of log(value) vs log(size) — the asymptotic
    exponent of the metric in the ladder variable."""
    import math

    if len(sizes) != len(values) or len(sizes) < 2:
        raise ValueError("need >= 2 ladder rungs to fit an exponent")
    if min(values) <= 0 or min(sizes) <= 0:
        raise ValueError("exponent fit needs positive sizes and values")
    ls = [math.log(s) for s in sizes]
    lv = [math.log(v) for v in values]
    k = len(ls)
    sx, sy = sum(ls), sum(lv)
    sxx = sum(a * a for a in ls)
    sxy = sum(a * b for a, b in zip(ls, lv))
    return (k * sxy - sx * sy) / (k * sxx - sx * sx)


# --------------------------------------------------------------------- #
# Capture
# --------------------------------------------------------------------- #
def _base_max_local(dtype=None):
    import jax.numpy as jnp

    from ..parallel.mesh_partition import partition_mesh

    mesh, _ = C._problem(dtype or jnp.float32)
    return partition_mesh(mesh, C._N_PARTS).max_local


def capture(families=None, base_traced=None, keep_compiled=None) -> dict:
    """Compile the requested families over the shape ladder and build
    the full resource capture.

    ``base_traced`` reuses the contracts layer's :func:`C.build_traced`
    result for the shared base rung (same (n, cells) — the lint runner
    traces the five programs once for both layers); the ladder's other
    rungs are traced and compiled here.  ``keep_compiled`` (a dict)
    retains the BASE-rung executables by family so the lint runner can
    hand them to :func:`check_aot` without a second compile.
    """
    # The first rung of each axis IS the contracts base shape — the
    # shared-trace reuse and the fitted exponents' size vector both
    # assume it, so an edit to either side must fail loudly here.
    assert LADDER_N[0] == C._N and LADDER_CELLS[0] == C._CELLS, (
        "ladder rung 0 must equal the contracts base shape "
        f"({C._N}, {C._CELLS})"
    )
    fams = tuple(families or FAMILIES)
    max_local = _base_max_local() if "partitioned" in fams else None

    # One compile_metrics sweep per rung; the base rung is rung 0 of
    # BOTH axes, so the ladder costs 1 + 2 + 2 compiled rungs total.
    def rung_metrics(n, cells, traced=None, keep=None):
        traced = traced or C.build_traced(fams, n=n, cells=cells)
        return {
            f: compile_metrics(traced[f], keep=keep, key=f)
            for f in fams
        }

    base_n, base_cells = C._N, C._CELLS
    base_metrics = rung_metrics(
        base_n, base_cells, traced=base_traced, keep=keep_compiled
    )
    n_axis = [base_metrics]
    for n in LADDER_N[1:]:
        n_axis.append(rung_metrics(n, base_cells))
    t_axis = [base_metrics]
    for cells in LADDER_CELLS[1:]:
        t_axis.append(rung_metrics(base_n, cells))

    out_families = {}
    for fam in fams:
        scaling = {}
        for axis, sizes, rungs in (
            ("n_particles", LADDER_N, n_axis),
            ("ntet", [6 * c**3 for c in LADDER_CELLS], t_axis),
        ):
            exps = {}
            for metric in SCALING_METRICS:
                vals = [r[fam][metric] for r in rungs]
                if min(vals) > 0:
                    exps[metric] = round(
                        fit_exponent(list(sizes), vals), 3
                    )
            scaling[axis] = exps
        out_families[fam] = {
            "base": rung_signature(
                base_metrics[fam],
                family_analytic(fam, n=base_n, cells=base_cells,
                                max_local=max_local),
            ),
            # The top n_particles rung carries its own memory checks:
            # there the analytic flux/lane terms dominate the fixed
            # slack, so a materialized quadratic intermediate cannot
            # hide under it (see temp_allowance_bytes).
            "top": rung_signature(
                n_axis[-1][fam],
                family_analytic(fam, n=LADDER_N[-1], cells=base_cells,
                                max_local=max_local),
            ),
            "scaling": scaling,
        }
    return {
        "environment": C.environment(),
        "ladder": {
            "n_particles": list(LADDER_N),
            "ntet": [6 * c**3 for c in LADDER_CELLS],
        },
        "families": out_families,
    }


# --------------------------------------------------------------------- #
# Invariants
# --------------------------------------------------------------------- #
def _finding(symbol: str, message: str) -> Finding:
    return Finding(
        rule="COST",
        path=PERF_CONTRACTS_FILE,
        line=0,
        symbol=symbol,
        message=message,
    )


def check_cost(cap: dict) -> list[Finding]:
    """Baseline-free resource invariants — fire with no committed
    capture at all (see the module docstring for the catalogue).

    The per-rung checks (f64 census, donation alias, temp/peak
    allowance) run on every captured rung — the base rung and, when
    present, the top n_particles rung, where the analytic memory terms
    dominate the fixed slack.  A finding symbol is emitted once per
    family even when both rungs trip."""
    out: list[Finding] = []
    seen: set[str] = set()

    def emit(symbol, message):
        if symbol not in seen:
            seen.add(symbol)
            out.append(_finding(symbol, message))

    for fam, entry in sorted(cap["families"].items()):
        for rung in ("base", "top"):
            if rung not in entry:
                continue
            m = entry[rung]["metrics"]
            a = entry[rung]["analytic"]
            if m["f64_ops"]:
                emit(
                    f"cost.f64.{fam}",
                    f"{m['f64_ops']} f64-typed op(s) in the optimized "
                    f"HLO of an f32-config program ({rung} rung) — f64 "
                    "flops on the hot path (integrity/audit.py is the "
                    "sanctioned f64 surface, and it runs on host)",
                )
            if m["alias_bytes"] < a["flux_bytes"]:
                emit(
                    f"cost.donation.{fam}",
                    f"aliased (donated) bytes {m['alias_bytes']} < "
                    f"analytic flux accumulator {a['flux_bytes']} "
                    f"({rung} rung) — the donation was dropped, peak "
                    "memory grows by one accumulator and the re-arm "
                    "contract breaks",
                )
            allow = temp_allowance_bytes(a)
            if m["temp_bytes"] > allow:
                emit(
                    f"cost.peak.{fam}",
                    f"temp memory {m['temp_bytes']} B exceeds the "
                    f"analytic allowance {allow} B at the {rung} rung "
                    "(flux + lane state + tables + slack) — peak "
                    "memory left the donated-flux + per-lane envelope; "
                    "a fused intermediate probably materialized",
                )
        a = entry["base"]["analytic"]
        if fam == "pallas":
            from ..ops.walk_pallas import kernel_vmem_bytes

            est = kernel_vmem_bytes(
                a["ntet"], a["n"], a["n_groups"], a["itemsize"]
            )
            ref = pallas_footprint_bytes(
                a["ntet"], a["n"], a["n_groups"], a["itemsize"]
            )
            if abs(est - ref) > VMEM_TOL * ref:
                out.append(_finding(
                    "cost.vmem.pallas",
                    f"kernel_vmem_bytes estimates {est} B but the "
                    f"analytic tile footprint is {ref} B (>"
                    f"{VMEM_TOL:.0%} apart) — the auto-fallback budget "
                    "estimator drifted from the kernel's real VMEM "
                    "layout",
                ))
        for axis, exps in sorted(entry.get("scaling", {}).items()):
            gate = SCALING_MAX[axis]
            bad = {k: v for k, v in sorted(exps.items()) if v > gate}
            if bad:
                desc = ", ".join(
                    f"{k}~O(size^{v})" for k, v in bad.items()
                )
                out.append(_finding(
                    f"cost.scaling.{axis}.{fam}",
                    f"superlinear growth in {axis}: {desc} exceeds the "
                    f"{gate} gate — an accidental quadratic broadcast "
                    "or a lost fusion scales with the ladder",
                ))
    return out


def _within(old, new, tol) -> bool:
    if old == new:
        return True
    return abs(new - old) <= tol * max(abs(old), abs(new), 1)


def diff_cost(current: dict, baseline: dict) -> list[Finding]:
    """Diff a fresh capture against the committed PERF_CONTRACTS.json
    within the per-metric tolerance bands.  Intentional changes
    regenerate with ``scripts/lint.py --write-perf-contracts``."""
    out: list[Finding] = []
    if current["environment"] != baseline.get("environment"):
        out.append(_finding(
            "cost.environment.all",
            f"capture environment {current['environment']} != baseline "
            f"{baseline.get('environment')} — resource signatures are "
            "environment-pinned (scripts/lint.py sets the canonical "
            "one)",
        ))
        return out
    if current["ladder"] != baseline.get("ladder"):
        out.append(_finding(
            "cost.ladder.all",
            f"shape ladder changed: baseline "
            f"{baseline.get('ladder')} -> current {current['ladder']} "
            "— regenerate PERF_CONTRACTS.json",
        ))
        return out
    cur_f, base_f = current["families"], baseline.get("families", {})
    for fam in sorted(set(cur_f) | set(base_f)):
        if fam not in base_f:
            out.append(_finding(
                f"cost.family.added.{fam}",
                "family captured but absent from PERF_CONTRACTS.json "
                "— regenerate the baseline",
            ))
            continue
        if fam not in cur_f:
            out.append(_finding(
                f"cost.family.removed.{fam}",
                "family in PERF_CONTRACTS.json but no longer captured",
            ))
            continue
        cur_rungs = {r for r in ("base", "top") if r in cur_f[fam]}
        base_rungs = {r for r in ("base", "top") if r in base_f[fam]}
        if cur_rungs != base_rungs:
            out.append(_finding(
                f"cost.drift.rungs.{fam}",
                f"captured rungs {sorted(cur_rungs)} != baseline "
                f"{sorted(base_rungs)} — regenerate "
                "PERF_CONTRACTS.json",
            ))
        for rung in sorted(cur_rungs & base_rungs):
            cm = cur_f[fam][rung]["metrics"]
            bm = base_f[fam][rung]["metrics"]
            # The base rung keeps the short historical symbol; the top
            # rung is tagged so one drifted metric at both sizes reads
            # as two distinct findings.
            tag = "" if rung == "base" else f"{rung}."
            for metric, tol in sorted(DRIFT_TOL.items()):
                if not _within(
                    bm.get(metric, 0), cm.get(metric, 0), tol
                ):
                    pct = (
                        100.0
                        * (cm.get(metric, 0) - bm.get(metric, 0))
                        / max(abs(bm.get(metric, 0)), 1)
                    )
                    out.append(_finding(
                        f"cost.drift.{tag}{metric}.{fam}",
                        f"{metric} drifted {bm.get(metric, 0)} -> "
                        f"{cm.get(metric, 0)} ({pct:+.1f}%) at the "
                        f"{rung} rung, outside the ±{tol:.0%} band",
                    ))
        cs = cur_f[fam].get("scaling", {})
        bs = base_f[fam].get("scaling", {})
        for axis in sorted(set(cs) | set(bs)):
            ce, be = cs.get(axis, {}), bs.get(axis, {})
            for metric in sorted(set(ce) | set(be)):
                if abs(ce.get(metric, 0.0) - be.get(metric, 0.0)) > (
                    SCALING_TOL
                ):
                    out.append(_finding(
                        f"cost.drift.scaling.{axis}.{metric}.{fam}",
                        f"{axis} exponent of {metric} drifted "
                        f"{be.get(metric)} -> {ce.get(metric)} "
                        f"(>±{SCALING_TOL} band)",
                    ))
    return out


# --------------------------------------------------------------------- #
# AOT round-trip contract (the serving program bank's donation gate)
# --------------------------------------------------------------------- #
# The two program families the serving bank (serving/bank.py) persists
# as serialized executables.  The gate proves the round trip keeps the
# donation/1+1 contract the jit path compiles with — the resolution of
# the deserialized-executables-drop-the-aliasing-plan finding that
# fresh_compile() exists to sidestep for captures.
AOT_FAMILIES = ("megastep", "trace_packed")


def check_aot(traced=None, compiled=None) -> list[Finding]:
    """``cost.donation.aot``: serialize -> deserialize the base-rung
    serving families and run the bank's load-time validator
    (serving/bank.validate_loaded) against the loaded executables.
    The AOT path a warm server dispatches must be provably as donated
    (and as host-callback-free) as the jit path; a jax/jaxlib change
    that loses the aliasing plan in serialization fails HERE, on CPU,
    before it silently doubles serving memory.  A family that stops
    serializing at all is the same named finding — the bank would
    degrade every warm start to full compile cost.

    ``compiled`` (family -> executable) reuses base-rung compiles a
    :func:`capture` run already paid for (``keep_compiled``); absent
    families are traced/compiled here."""
    from jax.experimental.serialize_executable import (
        deserialize_and_load,
        serialize,
    )

    from ..serving.bank import alias_marks, validate_loaded

    compiled = dict(compiled or {})
    tr = dict(traced or {})
    missing = [
        f for f in AOT_FAMILIES if f not in tr and f not in compiled
    ]
    if missing:
        tr.update(C.build_traced(missing))
    out: list[Finding] = []
    for fam in AOT_FAMILIES:
        exe = compiled.get(fam)
        if exe is None:
            exe = fresh_compile(tr[fam].lower())
        expect = alias_marks(exe)
        try:
            payload, in_tree, out_tree = serialize(exe)
        except (ValueError, TypeError) as e:
            out.append(_finding(
                "cost.donation.aot",
                f"{fam} executable does not serialize ({e}) — the "
                "serving bank cannot persist it and every warm start "
                "pays full compile cost",
            ))
            continue
        loaded = deserialize_and_load(payload, in_tree, out_tree)
        for symbol, message in validate_loaded(
            loaded, fam, expect_alias=expect
        ):
            out.append(_finding(symbol, message))
    return out


# --------------------------------------------------------------------- #
# Hardware calibration: joining measured timings to compiled signatures
# --------------------------------------------------------------------- #
# Nominal effective coefficients for PREDICTED seconds when no
# calibration exists yet: the deterministic ranking basis of the
# autotuner's rehearsal mode (tuning/search.py) and the placeholder the
# first hardware capture replaces.  Order-of-magnitude v5p-ish figures;
# their absolute accuracy is irrelevant to rehearsal ranking (only the
# flop/byte/dispatch trade-off ordering matters) and the calibrated
# per-shape-class values supersede them wherever a TUNING.json entry
# exists.
NOMINAL_COEFFS = {
    "flops_per_s": 2.0e11,   # effective f32 throughput
    "bytes_per_s": 5.0e10,   # effective HBM bandwidth
    "dispatch_s": 5.0e-5,    # per-program-launch overhead
}


def predict_seconds(metrics: dict, coeffs: dict, *, dispatches: float = 0.0) -> float:
    """Roofline-style predicted wall seconds of one compiled program
    from its :func:`compile_metrics` signature: compute time + memory
    time + (optionally) launch overhead.  With per-shape-class FITTED
    coefficients (:func:`calibrate_points`, persisted in TUNING.json)
    this is the compile-time contracts' bridge from a flop/byte drift
    to a predicted hardware regression between capture windows.  A
    coefficient :func:`calibrate_points` could not fit (its explicit
    None fallback on degenerate point sets) contributes no term."""
    t = dispatches * (coeffs.get("dispatch_s") or 0.0)
    f = coeffs.get("flops_per_s")
    if f:
        t += metrics["flops"] / f
    b = coeffs.get("bytes_per_s")
    if b:
        t += metrics["bytes_accessed"] / b
    return t


def calibrate_points(points: list[dict]) -> dict | None:
    """Fit effective-throughput / effective-bandwidth coefficients from
    measured (flops, bytes_accessed, seconds) points of one shape class
    — the autotuner's calibration join (every timed candidate is one
    point; the compiled signatures come from :func:`compile_metrics`
    over the exact programs that were timed).

    Model: ``t ≈ flops·x + bytes·y`` with x = 1/flops_per_s,
    y = 1/bytes_per_s, solved by 2×2 least squares.  A degenerate or
    unphysical fit (singular system, non-positive coefficient — common
    when every candidate has near-identical signatures) falls back to
    the single-term fit that explains the timings best, with the other
    coefficient reported as None.  Returns None with no points."""
    import math

    pts = [
        (float(p["flops"]), float(p["bytes_accessed"]),
         float(p["seconds"]))
        for p in points
        if p.get("seconds") and p["seconds"] > 0
    ]
    if not pts:
        return None

    def _one_term(idx):
        # t ≈ v·x  →  x = Σ v·t / Σ v²  (least squares through origin)
        num = sum(p[idx] * p[2] for p in pts)
        den = sum(p[idx] * p[idx] for p in pts)
        return (num / den) if den > 0 and num > 0 else None

    def _rmse(x, y):
        err = [
            (f * (x or 0.0) + b * (y or 0.0) - t) ** 2
            for f, b, t in pts
        ]
        return math.sqrt(sum(err) / len(err))

    x = y = None
    if len(pts) >= 2:
        sff = sum(f * f for f, _, _ in pts)
        sbb = sum(b * b for _, b, _ in pts)
        sfb = sum(f * b for f, b, _ in pts)
        sft = sum(f * t for f, _, t in pts)
        sbt = sum(b * t for _, b, t in pts)
        det = sff * sbb - sfb * sfb
        if det > 0 and abs(det) > 1e-12 * max(sff * sbb, 1.0):
            x = (sft * sbb - sbt * sfb) / det
            y = (sbt * sff - sft * sfb) / det
    if x is None or y is None or x <= 0 or y <= 0:
        xf, yb = _one_term(0), _one_term(1)
        cand = []
        if xf is not None:
            cand.append((xf, None))
        if yb is not None:
            cand.append((None, yb))
        if not cand:
            return None
        x, y = min(cand, key=lambda c: _rmse(*c))
    return {
        "flops_per_s": (1.0 / x) if x else None,
        "bytes_per_s": (1.0 / y) if y else None,
        "rmse_s": _rmse(x, y),
        "points": len(pts),
        "model": "seconds = flops/flops_per_s + bytes/bytes_per_s",
    }


def load_perf_contracts(path) -> dict:
    with open(path) as fh:
        return json.load(fh)


def write_perf_contracts(path, cap: dict | None = None, **kw) -> dict:
    from ..utils.checkpoint import atomic_write_json

    cap = cap or capture(**kw)
    # Committed baseline: atomic write (PUMI008) — a torn regeneration
    # must never masquerade as the real capture.
    atomic_write_json(path, cap)
    return cap
