"""Effect-ordering protocol analyzer (graft-check layer 4).

The crash-safety surface is a set of ORDERING promises: the two-phase
sharded checkpoint commits its manifest LAST; the scheduler journals a
job's terminal record BEFORE deleting its checkpoint; a signal flush
uninstalls its own handlers BEFORE chaining the previous one.  Each of
these was caught (or nearly missed) in review as a hand-verified
property of one function body — the exact kind of invariant a refactor
silently reorders.  This module makes them machine-checked:

  * **Effect points** are recognized by CALLEE on the AST — e.g. a
    call whose head is ``_flush_journal`` / ``*.journal.flush`` is the
    effect ``journal.flush``; ``atomic_write_bytes(manifest_path,…)``
    is ``manifest.commit``; ``os.remove``/``shutil.rmtree`` on a
    checkpoint path is ``checkpoint.delete``.  A call handed a nested
    worker def (the executor pattern ``ex.map(_write, …)``) carries
    the worker's effects at the call site.
  * **Protocols** (the declarations below) bind happens-before
    constraints to the functions that OWN them —
    ``TallyScheduler._finish``/``._poison``/``._quantum``/``._preempt``
    /``._signal_flush``, ``SchedulerJournal.flush``/``write_flux``,
    ``save_sharded_checkpoint``, ``CheckpointStore.save``/``._rotate``,
    ``ResilientRunner._on_signal``, ``FleetRouter.submit``/``._place``
    (the fleet's idempotency-record-before-accept and
    assignment-record-before-dispatch) — and are verified along ALL paths
    of the function's CFG (if/else branches, loops at 0/1 iterations,
    try bodies and handlers; a path that ends in return/raise stops).
  * Constraint kinds: ``before`` (on any path containing the *after*
    effect, the *before* effect precedes it — with ``required`` the
    *after* effect may never appear unpreceded), ``require`` (the
    effect must exist in the function at all), ``forbid`` (it must
    not — e.g. no raw write inside the journal's atomic flush).

The committed capture (``PROTOCOLS.json``) pins the discovered effect
inventory per protocol and is diffed exactly like CONTRACTS.json:
drift in what a crash-safety function DOES is a named finding until
the baseline is intentionally regenerated with
``scripts/lint.py --write-protocols``, and a capture from another
environment is refused outright (cross-env refusal semantics shared
with the contract layers).

Findings carry ``rule="PROTO"`` and route to this layer's
LINT_BASELINE.json entries by that prefix.  CFG approximations (loops
bounded at one iteration, exceptions modeled at statement granularity)
are deliberately conservative for the straight-line, small functions
that own these protocols.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import textwrap

from . import Finding
from .astlint import (
    PACKAGE,
    PackageIndex,
    _dotted,
    _parse,
    _scope_file_bindings,
    collect_sources,
    raw_write_head,
)

PROTOCOLS_FILE = "PROTOCOLS.json"
PROTOCOLS_SCHEMA = 1

#: Cap on enumerated CFG paths per function — the owning functions are
#: small; hitting the cap means the CFG grew beyond what hand-audits
#: ever covered, which is itself worth a finding.
MAX_PATHS = 512


def _finding(symbol: str, message: str, path: str = PROTOCOLS_FILE,
             line: int = 0) -> Finding:
    return Finding(
        rule="PROTO", path=path, line=line, symbol=symbol,
        message=message,
    )


# --------------------------------------------------------------------- #
# Effect recognition
# --------------------------------------------------------------------- #
#: last call-chain component → effect name (context-free heads).
_SIMPLE_EFFECTS = {
    "_flush_journal": "journal.flush",
    "write_flux": "flux.persist",
    "_remove_checkpoint": "checkpoint.delete",
    "remove_sidefiles": "checkpoint.delete",
    "save_checkpoint": "checkpoint.save",
    "_journal_checkpoint": "checkpoint.save",
    "save_sharded_checkpoint": "checkpoint.save",
    "_write_checkpoint": "checkpoint.save",
    "checkpoint": "checkpoint.save",
    "install_preemption_handlers": "handler.install",
    "_install_signal_handlers": "handler.install",
    "uninstall_preemption_handlers": "handler.uninstall",
    "_uninstall_signal_handlers": "handler.uninstall",
    "resume_previous_handler": "handler.resume",
    "_rotate": "generation.rotate",
    "fsync_dir": "dir.fsync",
    "atomic_savez": "atomic.write",
    "atomic_write_json": "atomic.write",
    # Fleet routing (serving/fleet.py): the FLEET.json flush and the
    # two router actions its write-ahead orderings fence.
    "_flush_fleet": "fleet.record",
    "_place": "job.place",
    "_dispatch_job": "job.dispatch",
    # Supervisor eviction (serving/supervisor.py): the FLEET.json
    # eviction record and the two drain flavors it must precede.
    "record_eviction": "eviction.record",
    "drain_member": "member.drain",
    "drain_member_from_journal": "member.drain",
    # SLO advisory (obs/slo.py → serving/supervisor.py): the
    # FLEET.json breach record and the quarantine it must precede.
    "record_breach": "breach.record",
    "_quarantine": "member.quarantine",
}

#: fully-dotted deletion heads (``remove`` alone would match
#: ``list.remove``).
_DELETE_HEADS = frozenset({"os.remove", "os.unlink", "shutil.rmtree"})


def _arg_text(call: ast.Call, i: int) -> str:
    if len(call.args) <= i:
        return ""
    try:
        return ast.unparse(call.args[i]).lower()
    except Exception:
        return ""


def classify_call(call: ast.Call, opened: set[str],
                  buffers: set[str]) -> str | None:
    """The effect one call performs, or None.  ``opened``/``buffers``
    are the scope's file bindings for the raw-write classifier."""
    d = _dotted(call.func)
    if d is None:
        return None
    last = d.split(".")[-1]
    if d.endswith("journal.flush"):
        return "journal.flush"
    if d.endswith("store.save"):
        return "checkpoint.save"
    if last in ("atomic_write_bytes", "_atomic_write_bytes"):
        if "manifest" in _arg_text(call, 0):
            return "manifest.commit"
        return "atomic.write"
    if d in _DELETE_HEADS:
        a = _arg_text(call, 0)
        if "manifest" in a:
            return "manifest.uncommit"
        if "checkpoint" in a or "ckpt" in a:
            return "checkpoint.delete"
        return "generation.delete"
    if last in _SIMPLE_EFFECTS:
        return _SIMPLE_EFFECTS[last]
    if raw_write_head(call, opened, buffers) is not None:
        return "raw.write"
    return None


# --------------------------------------------------------------------- #
# Protocol declarations
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class Protocol:
    """One declared happens-before protocol, owned by one function."""

    name: str
    path: str
    function: str  # qualname within the module
    constraints: tuple[dict, ...]
    #: local effect label → base effect name (so ``terminal.record``
    #: can name the journal flush of a terminal-outcome function).
    aliases: tuple[tuple[str, str], ...] = ()
    rationale: str = ""


PROTOCOLS: tuple[Protocol, ...] = (
    Protocol(
        name="terminal-record-before-checkpoint-delete",
        path=f"{PACKAGE}/serving/scheduler.py",
        function="TallyScheduler._finish",
        aliases=(("terminal.record", "journal.flush"),),
        constraints=(
            {"kind": "require", "effect": "terminal.record"},
            {"kind": "before", "before": "terminal.record",
             "after": "checkpoint.delete", "required": True},
            {"kind": "before", "before": "flux.persist",
             "after": "terminal.record", "required": False},
        ),
        rationale=(
            "A finished job's terminal record (flux name included) "
            "must be journaled BEFORE its checkpoint side-files are "
            "deleted: a crash between the two may cost a redundant "
            "file, never the finished work.  Reversed, the crash "
            "window re-runs (or loses) a completed job — the exact "
            "bug PR 14's review caught by hand."
        ),
    ),
    Protocol(
        name="poison-record-before-checkpoint-delete",
        path=f"{PACKAGE}/serving/scheduler.py",
        function="TallyScheduler._poison",
        aliases=(("terminal.record", "journal.flush"),),
        constraints=(
            {"kind": "require", "effect": "terminal.record"},
            {"kind": "before", "before": "terminal.record",
             "after": "checkpoint.delete", "required": True},
        ),
        rationale=(
            "Poisoning is a terminal outcome like completion: the "
            "journal must mark the job done before its checkpoint is "
            "removed, or a crash in between recovers the job as "
            "pending with no checkpoint — replaying a job the server "
            "already declared poisoned."
        ),
    ),
    Protocol(
        name="quantum-checkpoint-before-journal-flush",
        path=f"{PACKAGE}/serving/scheduler.py",
        function="TallyScheduler._quantum",
        constraints=(
            {"kind": "before", "before": "checkpoint.save",
             "after": "journal.flush", "required": True},
        ),
        rationale=(
            "Write-ahead discipline: the quantum-boundary checkpoint "
            "is written BEFORE the journal flush that references it.  "
            "Flushed first, a crash leaves a journal pointing at a "
            "checkpoint that does not exist (recovery then replays "
            "from move 0 — correct but a silently widened loss "
            "window)."
        ),
    ),
    Protocol(
        name="preempt-checkpoint-before-journal-flush",
        path=f"{PACKAGE}/serving/scheduler.py",
        function="TallyScheduler._preempt",
        constraints=(
            {"kind": "before", "before": "checkpoint.save",
             "after": "journal.flush", "required": True},
        ),
        rationale=(
            "A preempted job's checkpoint must be on disk before the "
            "journal records the preemption — same write-ahead edge "
            "as the quantum boundary."
        ),
    ),
    Protocol(
        name="scheduler-uninstall-before-resume",
        path=f"{PACKAGE}/serving/scheduler.py",
        function="TallyScheduler._signal_flush",
        constraints=(
            {"kind": "require", "effect": "handler.uninstall"},
            {"kind": "before", "before": "handler.uninstall",
             "after": "handler.resume", "required": True},
        ),
        rationale=(
            "The signal flush must restore the previous handlers "
            "BEFORE resuming (chaining/exiting through) them: dying "
            "through the chain with our handler still installed "
            "leaves a stale handler a later signal routes into a "
            "dead scheduler — the PR 14 stale-handler clobber."
        ),
    ),
    Protocol(
        name="runner-uninstall-before-resume",
        path=f"{PACKAGE}/resilience/runner.py",
        function="ResilientRunner._on_signal",
        constraints=(
            {"kind": "require", "effect": "handler.uninstall"},
            {"kind": "before", "before": "handler.uninstall",
             "after": "handler.resume", "required": True},
        ),
        rationale=(
            "Same stale-handler clobber as the scheduler flush: the "
            "runner's preemption flush uninstalls its own handlers "
            "before behaving as the process would have without them."
        ),
    ),
    Protocol(
        name="manifest-commit-last",
        path=f"{PACKAGE}/utils/checkpoint.py",
        function="save_sharded_checkpoint",
        aliases=(("shard.write", "checkpoint.save"),),
        constraints=(
            {"kind": "require", "effect": "manifest.commit"},
            {"kind": "before", "before": "shard.write",
             "after": "manifest.commit", "required": True},
            {"kind": "before", "before": "manifest.uncommit",
             "after": "shard.write", "required": False},
        ),
        rationale=(
            "Two-phase commit: every shard is written (phase 1) "
            "before MANIFEST.json is committed (phase 2), and a "
            "pre-existing manifest is removed before any shard is "
            "touched.  A manifest committed early names shards that "
            "may be half-written — the Frankenstein restore the "
            "sharded layout exists to prevent."
        ),
    ),
    Protocol(
        name="store-rotate-after-write",
        path=f"{PACKAGE}/resilience/store.py",
        function="CheckpointStore.save",
        constraints=(
            {"kind": "require", "effect": "checkpoint.save"},
            {"kind": "before", "before": "checkpoint.save",
             "after": "generation.rotate", "required": True},
        ),
        rationale=(
            "The keep-N rotation runs only after the new generation "
            "is durably written: rotating first can delete the last "
            "good generation before its replacement exists."
        ),
    ),
    Protocol(
        name="store-rotation-fsync",
        path=f"{PACKAGE}/resilience/store.py",
        function="CheckpointStore._rotate",
        constraints=(
            {"kind": "require", "effect": "dir.fsync"},
            {"kind": "before", "before": "generation.delete",
             "after": "dir.fsync", "required": False},
        ),
        rationale=(
            "Rotation deletions must be made durable with a directory "
            "fsync (the PR 4 fix): without it a power cut can "
            "resurrect a rotated-out generation while losing the "
            "newest rename, handing find_latest a stale view."
        ),
    ),
    Protocol(
        name="journal-document-atomic",
        path=f"{PACKAGE}/serving/journal.py",
        function="SchedulerJournal.flush",
        constraints=(
            {"kind": "require", "effect": "atomic.write"},
            {"kind": "forbid", "effect": "raw.write"},
        ),
        rationale=(
            "The JOBS.json document is the single source of truth a "
            "recovery reads — it must only ever be produced by the "
            "atomic tmp+fsync+rename writer; any raw write path here "
            "reintroduces torn-journal states the whole design rules "
            "out."
        ),
    ),
    Protocol(
        name="idempotency-record-before-accept",
        path=f"{PACKAGE}/serving/fleet.py",
        function="FleetRouter.submit",
        constraints=(
            {"kind": "require", "effect": "fleet.record"},
            {"kind": "before", "before": "fleet.record",
             "after": "job.place", "required": True},
        ),
        rationale=(
            "The FLEET.json acceptance record (idempotency key map + "
            "request payload) is flushed BEFORE the job is placed on "
            "any member.  Placed first, a crash in between runs a job "
            "the router never journaled accepting — the client's "
            "retried POST then starts a SECOND execution of the same "
            "work, the exact double-run the idempotent ingress exists "
            "to rule out."
        ),
    ),
    Protocol(
        name="assignment-record-before-dispatch",
        path=f"{PACKAGE}/serving/fleet.py",
        function="FleetRouter._place",
        constraints=(
            {"kind": "require", "effect": "fleet.record"},
            {"kind": "require", "effect": "job.dispatch"},
            {"kind": "before", "before": "fleet.record",
             "after": "job.dispatch", "required": True},
        ),
        rationale=(
            "The FLEET.json assignment record is flushed BEFORE the "
            "member's scheduler sees the job.  A crash between the "
            "two leaves an assignment whose member journal does not "
            "know the job — recovery re-dispatches it from the "
            "journaled request.  Reversed, the crash window leaves a "
            "job some member owns that the router cannot attribute: "
            "on restart the router would place it AGAIN elsewhere "
            "(double-run), and migration's adopt-before-drop overlap "
            "would have no arbiter naming which copy survives."
        ),
    ),
    Protocol(
        name="eviction-record-before-drain",
        path=f"{PACKAGE}/serving/supervisor.py",
        function="FleetSupervisor._evict",
        constraints=(
            {"kind": "require", "effect": "eviction.record"},
            {"kind": "require", "effect": "member.drain"},
            {"kind": "before", "before": "eviction.record",
             "after": "member.drain", "required": True},
        ),
        rationale=(
            "The FLEET.json eviction record is flushed BEFORE the "
            "member's jobs are drained onto survivors.  A supervisor "
            "crash mid-drain then leaves a journaled eviction whose "
            "drain recovery replays from the member's on-disk "
            "journal (assignments arbitrate the already-moved "
            "copies).  Reversed, a crash after the drain but before "
            "the record leaves re-placed jobs under a member the "
            "routing journal still calls healthy — recovery would "
            "rebuild its device state and re-adopt jobs that now "
            "live (and run) elsewhere: the double-run the eviction "
            "machinery exists to rule out."
        ),
    ),
    Protocol(
        name="breach-record-before-quarantine",
        path=f"{PACKAGE}/serving/supervisor.py",
        function="FleetSupervisor._advise_slo",
        constraints=(
            {"kind": "require", "effect": "breach.record"},
            {"kind": "require", "effect": "member.quarantine"},
            {"kind": "before", "before": "breach.record",
             "after": "member.quarantine", "required": True},
        ),
        rationale=(
            "An SLO-driven quarantine is advisory, not observed: no "
            "probe failed, the member was convicted by burn-rate "
            "attribution (obs/slo.py).  The FLEET.json breach record "
            "is flushed BEFORE the quarantine takes effect, so a "
            "supervisor crash mid-advice leaves a journal that says "
            "WHY the member stopped taking placements — the operator "
            "(and fleetview --check) can audit the conviction.  "
            "Reversed, a crash after the quarantine but before the "
            "record leaves a member mysteriously sidelined with no "
            "journaled cause: an unexplained capacity loss the "
            "observability plane exists to rule out."
        ),
    ),
    Protocol(
        name="journal-flux-atomic",
        path=f"{PACKAGE}/serving/journal.py",
        function="SchedulerJournal.write_flux",
        constraints=(
            {"kind": "require", "effect": "atomic.write"},
            {"kind": "forbid", "effect": "raw.write"},
        ),
        rationale=(
            "Persisted fluxes are results that outlive the process; "
            "they ride the same atomic writer as the journal "
            "document (serialize to an in-memory buffer, then one "
            "atomic byte write)."
        ),
    ),
)

PROTOCOLS_BY_NAME = {p.name: p for p in PROTOCOLS}


# --------------------------------------------------------------------- #
# Effect extraction + CFG path enumeration
# --------------------------------------------------------------------- #
class _FnContext:
    def __init__(self, fn: ast.AST, aliases: dict[str, str]):
        #: flipped when path enumeration hits MAX_PATHS — the ordering
        #: checks then covered only a prefix of the CFG, which must
        #: surface as a finding, never as a silent clean.
        self.truncated = False
        # nested worker defs (the executor pattern): name -> def node
        self.nested = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        self.opened, self.buffers = _scope_file_bindings(
            list(ast.walk(fn))
        )
        # reverse alias map: base effect -> local label
        self.relabel = {base: label for label, base in aliases.items()}

    def effect_of(self, call: ast.Call) -> str | None:
        eff = classify_call(call, self.opened, self.buffers)
        return self.relabel.get(eff, eff) if eff is not None else None


def _expr_effects(node, ctx: _FnContext, _seen=None) -> list[tuple]:
    """(effect, lineno) of every call under ``node`` in source order,
    including the effects of nested worker defs passed as call
    arguments (``ex.map(_write, …)`` performs ``_write``'s effects)."""
    if _seen is None:
        _seen = set()
    calls = [
        n
        for n in ast.walk(node)
        if isinstance(n, ast.Call)
    ]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    out: list[tuple] = []
    for call in calls:
        eff = ctx.effect_of(call)
        if eff is not None:
            out.append((eff, call.lineno))
        for arg in list(call.args) + [k.value for k in call.keywords]:
            if (
                isinstance(arg, ast.Name)
                and arg.id in ctx.nested
                and arg.id not in _seen
            ):
                worker = ctx.nested[arg.id]
                out.extend(
                    _expr_effects(
                        ast.Module(body=worker.body, type_ignores=[]),
                        ctx, _seen | {arg.id},
                    )
                )
    return out


def _cap(paths: list, ctx: "_FnContext") -> list:
    seen = set()
    out = []
    for p in paths:
        key = (tuple(e for e, _ in p[0]), p[1])
        if key in seen:
            continue
        if len(out) >= MAX_PATHS:
            # A DISTINCT path was dropped: the checks below cover only
            # a prefix of the CFG — flagged, never silently clean.
            ctx.truncated = True
            break
        seen.add(key)
        out.append(p)
    return out


def _seq_paths(stmts, ctx) -> list[tuple[tuple, str | None]]:
    """Paths through a statement list: list of (effects, terminator)
    where terminator is None, "return" (return/raise) or "loopjump"
    (break/continue — converted back to fallthrough at the loop)."""
    paths: list[tuple[tuple, str | None]] = [((), None)]
    for stmt in stmts:
        new = []
        for eff, term in paths:
            if term is not None:
                new.append((eff, term))
                continue
            for e2, t2 in _stmt_paths(stmt, ctx):
                new.append((eff + e2, t2))
        paths = _cap(new, ctx)
    return paths


def _stmt_paths(stmt, ctx) -> list[tuple[tuple, str | None]]:
    if isinstance(stmt, (ast.Return, ast.Raise)):
        eff = tuple(_expr_effects(stmt, ctx))
        return [(eff, "return")]
    if isinstance(stmt, (ast.Break, ast.Continue)):
        return [((), "loopjump")]
    if isinstance(stmt, ast.If):
        test = tuple(_expr_effects(stmt.test, ctx))
        out = []
        for branch in (stmt.body, stmt.orelse or []):
            for eff, term in _seq_paths(branch, ctx):
                out.append((test + eff, term))
        return _cap(out, ctx)
    if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
        head = tuple(
            _expr_effects(
                stmt.iter if hasattr(stmt, "iter") else stmt.test, ctx
            )
        )
        out = [(head, None)]  # zero iterations
        for eff, term in _seq_paths(stmt.body, ctx):
            # one iteration; break/continue fall through the loop
            out.append((head + eff, None if term == "loopjump" else term))
        for eff, term in _seq_paths(stmt.orelse or [], ctx):
            out.append((head + eff, term))
        return _cap(out, ctx)
    if isinstance(stmt, ast.Try):
        out = list(_seq_paths(stmt.body + (stmt.orelse or []), ctx))
        for handler in stmt.handlers:
            out.extend(_seq_paths(handler.body, ctx))
        if stmt.finalbody:
            final = _seq_paths(stmt.finalbody, ctx)
            merged = []
            for eff, term in out:
                for fe, ft in final:
                    merged.append((eff + fe, ft or term))
            out = merged
        return _cap(out, ctx)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        head = tuple(
            e
            for item in stmt.items
            for e in _expr_effects(item.context_expr, ctx)
        )
        return _cap(
            [(head + eff, term) for eff, term in _seq_paths(stmt.body, ctx)],
            ctx,
        )
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return [((), None)]  # a def is not an execution of its body
    return [(tuple(_expr_effects(stmt, ctx)), None)]


def function_paths(fn, aliases: dict[str, str]) -> tuple[list, bool]:
    """All (bounded) effect paths through ``fn`` — (paths, truncated):
    each path a tuple of (effect, lineno); ``truncated`` True when the
    MAX_PATHS bound dropped a distinct path (the caller must surface
    it — a partially-checked protocol is not a clean one)."""
    ctx = _FnContext(fn, aliases)
    paths = [eff for eff, _term in _seq_paths(fn.body, ctx)]
    return paths, ctx.truncated


def function_effects(fn, aliases: dict[str, str]) -> dict[str, int]:
    """Order-free effect inventory of ``fn`` (the capture's drift
    unit): effect → occurrence count over unique call sites."""
    ctx = _FnContext(fn, aliases)
    sites = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            eff = ctx.effect_of(node)
            if eff is not None:
                sites.add((eff, node.lineno, node.col_offset))
    out: dict[str, int] = {}
    for eff, _l, _c in sites:
        out[eff] = out.get(eff, 0) + 1
    return out


# --------------------------------------------------------------------- #
# Checking
# --------------------------------------------------------------------- #
def build_index(root) -> PackageIndex:
    """The shared astlint index over the real tree."""
    return index_from_sources(collect_sources(root))


def index_from_sources(sources: dict[str, str]) -> PackageIndex:
    return PackageIndex({p: _parse(p, s) for p, s in sources.items()})


def _locate(index: PackageIndex, proto: Protocol):
    return index.defs.get((proto.path, proto.function))


def _check_protocol(index: PackageIndex, proto: Protocol) -> list[Finding]:
    fn = _locate(index, proto)
    if fn is None:
        return [
            _finding(
                f"missing.{proto.name}",
                f"protocol owner {proto.path}:{proto.function} not "
                "found — the function moved or was renamed; update "
                "the protocol declaration (analysis/protolint.py) "
                "and regenerate PROTOCOLS.json",
                path=proto.path,
            )
        ]
    aliases = dict(proto.aliases)
    paths, truncated = function_paths(fn, aliases)
    inventory = function_effects(fn, aliases)
    out: list[Finding] = []
    if truncated:
        out.append(
            _finding(
                f"paths.{proto.name}",
                f"{proto.function} exceeded the {MAX_PATHS}-path CFG "
                "bound — the ordering constraints were checked on a "
                "prefix only; split the function (it has outgrown "
                "what any review could audit) or raise MAX_PATHS",
                path=proto.path, line=fn.lineno,
            )
        )
    for c in proto.constraints:
        if c["kind"] == "require":
            if c["effect"] not in inventory:
                out.append(
                    _finding(
                        f"require.{proto.name}",
                        f"{proto.function} no longer performs "
                        f"'{c['effect']}' — {proto.rationale}",
                        path=proto.path, line=fn.lineno,
                    )
                )
        elif c["kind"] == "forbid":
            if c["effect"] in inventory:
                out.append(
                    _finding(
                        f"forbid.{proto.name}",
                        f"{proto.function} performs forbidden "
                        f"'{c['effect']}' — {proto.rationale}",
                        path=proto.path, line=fn.lineno,
                    )
                )
        elif c["kind"] == "before":
            out.extend(
                _check_before(proto, fn, paths, c)
            )
    return out


def _check_before(proto: Protocol, fn, paths, c) -> list[Finding]:
    before, after = c["before"], c["after"]
    required = bool(c.get("required"))
    for path_effects in paths:
        seen_before = False
        for i, (eff, line) in enumerate(path_effects):
            if eff == before:
                seen_before = True
            elif eff == after:
                # (i) any *before* occurring later on this path is a
                # reorder; (ii) with ``required``, an *after* with no
                # *before* yet is an unpreceded effect.
                later = [
                    (e, ln)
                    for e, ln in path_effects[i + 1:]
                    if e == before
                ]
                if later:
                    return [
                        _finding(
                            f"order.{proto.name}",
                            f"'{after}' at line {line} precedes "
                            f"'{before}' at line {later[0][1]} on a "
                            f"path through {proto.function} — the "
                            f"declared happens-before is "
                            f"'{before}' -> '{after}'. "
                            f"{proto.rationale}",
                            path=proto.path, line=line,
                        )
                    ]
                if required and not seen_before:
                    return [
                        _finding(
                            f"order.{proto.name}",
                            f"'{after}' at line {line} is reachable "
                            f"with no preceding '{before}' on a path "
                            f"through {proto.function}. "
                            f"{proto.rationale}",
                            path=proto.path, line=line,
                        )
                    ]
    return []


def check(index: PackageIndex) -> list[Finding]:
    """Verify every declared protocol against the indexed tree."""
    out: list[Finding] = []
    for proto in PROTOCOLS:
        out.extend(_check_protocol(index, proto))
    out.sort(key=lambda f: (f.path, f.line, f.symbol))
    return out


def check_sources(sources: dict[str, str]) -> list[Finding]:
    """Convenience for tests: check a {relpath: source} mapping."""
    return check(index_from_sources(sources))


# --------------------------------------------------------------------- #
# The committed capture (PROTOCOLS.json)
# --------------------------------------------------------------------- #
def environment() -> dict:
    from .contracts import environment as _env

    return _env()


def capture(index: PackageIndex) -> dict:
    protocols = {}
    for proto in PROTOCOLS:
        fn = _locate(index, proto)
        protocols[proto.name] = {
            "path": proto.path,
            "function": proto.function,
            "constraints": [dict(c) for c in proto.constraints],
            "effects": (
                function_effects(fn, dict(proto.aliases))
                if fn is not None else None
            ),
        }
    return {
        "schema": PROTOCOLS_SCHEMA,
        "environment": environment(),
        "protocols": protocols,
    }


def load_protocols(path) -> dict:
    with open(path) as fh:
        return json.load(fh)


def write_protocols(path, cap: dict) -> None:
    from ..utils.checkpoint import atomic_write_json

    atomic_write_json(path, cap)


def diff_baseline(current: dict, baseline: dict) -> list[Finding]:
    """Diff a fresh capture against the committed PROTOCOLS.json —
    cross-environment captures are refused outright (the CONTRACTS
    semantics), and any effect-inventory drift is a named finding
    until the baseline is intentionally regenerated."""
    out: list[Finding] = []
    if baseline.get("schema") != PROTOCOLS_SCHEMA:
        out.append(
            _finding(
                "schema.all",
                f"PROTOCOLS.json schema {baseline.get('schema')!r} != "
                f"{PROTOCOLS_SCHEMA} — regenerate with "
                "scripts/lint.py --write-protocols",
            )
        )
        return out
    if current["environment"] != baseline.get("environment"):
        out.append(
            _finding(
                "environment.all",
                f"capture environment {current['environment']} != "
                f"baseline {baseline.get('environment')} — protocol "
                "captures must be checked under the canonical lint "
                "environment (scripts/lint.py pins it)",
            )
        )
        return out
    cur, base = current["protocols"], baseline.get("protocols", {})
    for name in sorted(set(cur) | set(base)):
        if name not in base:
            out.append(
                _finding(
                    f"protocol.added.{name}",
                    "protocol declared but absent from "
                    "PROTOCOLS.json — regenerate the baseline",
                )
            )
            continue
        if name not in cur:
            out.append(
                _finding(
                    f"protocol.removed.{name}",
                    "protocol in PROTOCOLS.json but no longer "
                    "declared — regenerate the baseline (and say why "
                    "the ordering promise is gone)",
                )
            )
            continue
        for field in ("path", "function", "constraints", "effects"):
            if cur[name].get(field) != base[name].get(field):
                out.append(
                    _finding(
                        f"drift.{name}",
                        f"{field} drifted: baseline "
                        f"{base[name].get(field)!r} -> current "
                        f"{cur[name].get(field)!r} — an intentional "
                        "change regenerates with --write-protocols",
                    )
                )
                break
    return out


# --------------------------------------------------------------------- #
# --explain
# --------------------------------------------------------------------- #
_OVERVIEW = """\
protocol analyzer (graft-check layer 4, analysis/protolint.py)

Rationale: the crash-safety surface is a set of effect-ORDERING
promises (manifest committed last, terminal record journaled before
checkpoint delete, handlers uninstalled before chaining) that reviews
verified by hand.  The analyzer recognizes named effect points by
callee and verifies declared happens-before constraints along all CFG
paths of the owning functions, diffing the effect inventory against
the committed PROTOCOLS.json (cross-environment captures refused).

Example finding: PROTO [order.terminal-record-before-checkpoint-delete]
after TallyScheduler._finish deletes the checkpoint before flushing
the terminal journal record.

Fix pattern: restore the declared order (write-ahead: record first,
delete after); if the protocol itself changed intentionally, update
the declaration in analysis/protolint.py and regenerate with
scripts/lint.py --write-protocols.

Declared protocols:
"""


def explain(name: str) -> str | None:
    """Rationale + constraints + fix pattern for ``protocol`` (the
    overview) or one protocol by name."""
    key = name.strip().lower()
    if key in ("proto", "protocol", "protocols"):
        lines = [_OVERVIEW]
        for p in PROTOCOLS:
            lines.append(f"  {p.name}  ({p.path}:{p.function})")
        return "\n".join(lines)
    proto = PROTOCOLS_BY_NAME.get(key)
    if proto is None:
        return None
    cons = "\n".join(f"  {c}" for c in proto.constraints)
    return (
        f"{proto.name}\nOwner: {proto.path}:{proto.function}\n"
        f"Rationale: {textwrap.fill(proto.rationale, 70)}\n"
        f"Constraints:\n{cons}\n"
        "Fix pattern: restore the declared effect order in the owning "
        "function; for an intentional protocol change, edit the "
        "declaration in analysis/protolint.py and regenerate "
        "PROTOCOLS.json with scripts/lint.py --write-protocols."
    )
