"""jaxpr/HLO contract analyzer (layer 2).

The repo's hardest-won invariants are properties of the COMPILED
programs, not of any single source line: a steady-state move is one H2D
and one D2H (so the program itself must contain zero transfers and zero
host callbacks), the flux accumulator is donated (so the compiled
program must carry an input/output alias), an f32 config never touches
f64 on device, the megastep move loop is a ``scan`` (not degraded to a
dynamic ``while`` that XLA cannot pipeline), and the tally scatter count
is fixed.  Runtime tests witness these only by executing a failure;
here they are asserted against the *abstract trace* — ``jax.jit(...)
.trace(...)`` + ``.lower()`` — of the five public program families:

  trace         the legacy single-chip walk step (ops/walk.py trace)
  trace_packed  the packed-staging step (1+1 contract's compiled half)
  megastep      K device-sourced moves fused into one program
  partitioned   the packed partitioned step (shard_map over the mesh)
  pallas        the Mosaic kernel path (interpret mode off-TPU)

``capture()`` extracts a structural signature per family (primitive
counts, donated-argument count, f64 aval census, input/output avals);
``check_structural()`` asserts the invariants that must hold
regardless of history; ``diff_baseline()`` compares a capture against
the committed ``CONTRACTS.json`` so ANY structural drift — a new
transfer, a lost donation, an extra scatter, a while where a scan was —
fails CI with a named invariant.  Regenerate intentionally with
``python scripts/lint.py --write-contracts`` (and say why in the PR).

Signatures depend on the runtime environment (x64 widens counter
dtypes, the device count shapes the partitioned mesh), so captures
record it and ``diff_baseline`` refuses to compare across environments
— ``scripts/lint.py`` pins cpu / 8 virtual devices / x64 off.
"""
from __future__ import annotations

import collections
import json

import numpy as np

from . import Finding

CONTRACTS_FILE = "CONTRACTS.json"

# Problem size: small enough to abstract-trace in milliseconds, big
# enough to exercise every structural feature (two materials, two
# groups, walk-loop + compaction-free path, 8-way partition).  The
# cost-model layer (analysis/costmodel.py) re-traces the same problem
# at a ladder of (n, cells) rungs; the defaults here are its base rung
# and the shape CONTRACTS.json is pinned at.
_N = 16
_CELLS = 2  # box subdivisions per axis -> ntet = 6 * cells**3
_G = 2
_MAX_CROSSINGS = 64
_N_PARTS = 8

_CALLBACK_PRIMS = (
    "pure_callback",
    "io_callback",
    "debug_callback",
    "outside_call",
)
_TRANSFER_PRIMS = ("device_put",)


def environment() -> dict:
    import jax

    return {
        "backend": jax.default_backend(),
        "x64": bool(jax.config.jax_enable_x64),
        "n_devices": jax.device_count(),
    }


# --------------------------------------------------------------------- #
# Signature extraction
# --------------------------------------------------------------------- #
def _iter_subjaxprs(params):
    for p in params.values():
        for q in p if isinstance(p, (list, tuple)) else (p,):
            if hasattr(q, "jaxpr"):  # ClosedJaxpr
                yield q.jaxpr
            elif hasattr(q, "eqns"):  # raw Jaxpr (shard_map et al.)
                yield q


def _walk_jaxpr(jaxpr):
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        yield j
        for e in j.eqns:
            stack.extend(_iter_subjaxprs(e.params))


def _dtype_name(dt) -> str:
    try:
        return np.dtype(dt).name
    except TypeError:  # extended dtypes (PRNG key arrays)
        return str(dt)


def _is_f64(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    return dt is not None and _dtype_name(dt) == "float64"


def _aval_str(aval) -> str:
    dt = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dt is None:
        return str(aval)
    return f"{_dtype_name(dt)}[{','.join(map(str, shape or ()))}]"


def extract_signature(traced) -> dict:
    """Structural signature of one ``jax.jit(...).trace(...)`` result."""
    closed = traced.jaxpr
    jaxpr = closed.jaxpr
    prims: collections.Counter = collections.Counter()
    f64_avals = 0
    convert_to_f64 = 0
    for j in _walk_jaxpr(jaxpr):
        for v in list(j.invars) + list(j.constvars):
            if _is_f64(getattr(v, "aval", None)):
                f64_avals += 1
        for e in j.eqns:
            prims[e.primitive.name] += 1
            nd = e.params.get("new_dtype")
            if (
                e.primitive.name == "convert_element_type"
                and nd is not None
                and _dtype_name(nd) == "float64"
            ):
                convert_to_f64 += 1
            for v in e.outvars:
                if _is_f64(getattr(v, "aval", None)):
                    f64_avals += 1
    text = traced.lower().as_text()
    donated = text.count("tf.aliasing_output") + text.count(
        "jax.buffer_donor"
    )
    return {
        "inputs": [_aval_str(v.aval) for v in jaxpr.invars],
        "outputs": [_aval_str(v.aval) for v in jaxpr.outvars],
        "donated_args": donated,
        "f64_avals": f64_avals,
        "convert_to_f64": convert_to_f64,
        "prims": dict(sorted(prims.items())),
    }


# --------------------------------------------------------------------- #
# The five program families at a canonical tiny problem
# --------------------------------------------------------------------- #
def _problem(dtype, n=_N, cells=_CELLS):
    import jax.numpy as jnp

    from ..mesh.box import build_box_arrays
    from ..mesh.core import TetMesh

    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, cells, cells, cells)
    centroids = coords[t2v].mean(axis=1)
    class_id = np.where(centroids[:, 0] < 0.5, 1, 2).astype(np.int32)
    mesh = TetMesh.from_numpy(coords, t2v, class_id=class_id, dtype=dtype)
    rng = np.random.default_rng(7)
    arrs = dict(
        origin=jnp.asarray(rng.uniform(0.2, 0.8, (n, 3)), dtype),
        dest=jnp.asarray(rng.uniform(0.2, 0.8, (n, 3)), dtype),
        elem=jnp.zeros(n, jnp.int32),
        in_flight=jnp.ones(n, bool),
        weight=jnp.ones(n, dtype),
        group=jnp.zeros(n, jnp.int32),
        material_id=jnp.full(n, -1, jnp.int32),
        flux=jnp.zeros((mesh.tet2tet.shape[0], _G, 2), dtype),
    )
    return mesh, arrs


def _walk_statics():
    return dict(
        initial=False,
        max_crossings=_MAX_CROSSINGS,
        tolerance=1e-6,
        n_groups=_G,
        tally_scatter="pair",
        stats=True,
        integrity=True,
    )


def build_traced(families=None, dtype=None, n=_N, cells=_CELLS) -> dict:
    """Abstract-trace the requested program families (all by default).

    Returns {family: jax._src.stages.Traced}.  Pure tracing + lowering:
    no backend compile, no execution — safe and fast (<1 s) anywhere.
    ``n`` / ``cells`` size the problem (the cost-model layer sweeps a
    shape ladder through them; the defaults are the contracts rung).
    """
    import jax
    import jax.numpy as jnp

    from ..ops import staging, walk

    dtype = dtype or jnp.float32
    mesh, a = _problem(dtype, n=n, cells=cells)
    want = set(families or ("trace", "trace_packed", "megastep",
                            "partitioned", "pallas"))
    out = {}
    statics = _walk_statics()
    if "trace" in want:
        out["trace"] = walk._trace_jit.trace(
            mesh, a["origin"], a["dest"], a["elem"], a["in_flight"],
            a["weight"], a["group"], a["material_id"], a["flux"],
            **statics,
        )
    if "trace_packed" in want:
        stager = staging.HostStager()
        rec = staging.pack_move_record(
            stager, np.asarray(a["dest"]), np.ones(n),
            np.zeros(n, np.int64), np.ones(n, bool), dtype,
        )
        out["trace_packed"] = walk._trace_packed_jit.trace(
            mesh, a["origin"], a["elem"], a["material_id"],
            jnp.asarray(rec), a["flux"], None, a["weight"], a["group"],
            **statics,
        )
    if "megastep" in want:
        m = dict(statics)
        m.pop("initial")
        out["megastep"] = walk._megastep_jit.trace(
            mesh, a["origin"], a["elem"], a["material_id"], a["weight"],
            a["group"], a["in_flight"],
            jnp.arange(n, dtype=jnp.int32), a["flux"],
            jnp.int32(0), jax.random.PRNGKey(13),
            jnp.asarray([4.0, 9.0], dtype), jnp.asarray([0.3, 0.5], dtype),
            n_moves=4, survival_weight=0.2, downscatter=0.1,
            eps_near=1e-6, **m,
        )
    if "partitioned" in want:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.walk_partitioned import make_partitioned_step
        from ..parallel.mesh_partition import partition_mesh
        from ..parallel.particle_sharding import make_device_mesh

        if jax.device_count() < _N_PARTS:
            raise RuntimeError(
                f"the partitioned contract needs {_N_PARTS} devices "
                f"(got {jax.device_count()}); run through "
                "scripts/lint.py, which pins "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8"
            )
        part = partition_mesh(mesh, _N_PARTS)
        dmesh = make_device_mesh(_N_PARTS)
        step = make_partitioned_step(
            dmesh, part, n_groups=_G, max_crossings=_MAX_CROSSINGS,
            tolerance=1e-6, packed_io=True, integrity=True,
            tally_scatter="pair",
        )
        sh = NamedSharding(dmesh, P("p"))
        # Per-part staging capacity scales with the lane count so the
        # cost ladder sees a growing record; at the default n it is
        # exactly the historical 8 (CONTRACTS.json stays pinned).
        cap = partitioned_cap(n)
        carrier = staging.np_carrier(np.dtype(dtype))
        rec = jax.device_put(
            jnp.zeros((_N_PARTS * cap, staging.PART_IN_COLS),
                      carrier.name), sh,
        )
        pflux = jax.device_put(
            jnp.zeros((_N_PARTS, part.max_local, _G, 2), dtype), sh
        )
        out["partitioned"] = step.trace(rec, pflux)
    if "pallas" in want:
        # The facade path: trace_impl(kernel="pallas") through the SAME
        # jitted wrapper, interpret mode forced so the capture is
        # platform-independent (ops/walk_pallas.py defaults to
        # interpret off-TPU anyway).
        out["pallas"] = walk._trace_jit.trace(
            mesh, a["origin"], a["dest"], a["elem"], a["in_flight"],
            a["weight"], a["group"], a["material_id"], a["flux"],
            kernel="pallas", **statics,
        )
    return out


def partitioned_cap(n: int) -> int:
    """Per-part staging-record capacity for an ``n``-lane partitioned
    trace; floor 8 keeps the default rung identical to the historical
    capture."""
    return max(8, (2 * n) // _N_PARTS)


def capture(families=None, traced=None) -> dict:
    """Extract the structural signatures.

    ``traced`` reuses an existing :func:`build_traced` result (the lint
    runner shares one base-rung trace between the contracts and
    cost-model layers instead of re-tracing the five programs).
    """
    if traced is None:
        traced = build_traced(families)
    return {
        "environment": environment(),
        "families": {
            name: extract_signature(tr)
            for name, tr in sorted(traced.items())
        },
    }


# --------------------------------------------------------------------- #
# Invariants
# --------------------------------------------------------------------- #
def _finding(invariant: str, family: str, message: str) -> Finding:
    return Finding(
        rule="CONTRACT",
        path=CONTRACTS_FILE,
        line=0,
        symbol=f"{invariant}.{family}",
        message=message,
    )


def check_structural(sigs: dict) -> list[Finding]:
    """History-independent invariants every family must satisfy.

    These fire even with no baseline at all — they are the compiled
    half of contracts the facades promise:

      io.callbacks    zero host callbacks in-program (a callback is a
                      hidden per-dispatch host sync — the 1+1 transfer
                      contract would silently become 1+1+N).
      io.transfers    zero ``device_put`` primitives in-program (same
                      contract, H2D side).
      donation        the flux accumulator's donation survived to the
                      lowered module (``tf.aliasing_output`` /
                      ``jax.buffer_donor`` on at least one argument) —
                      a dropped donation doubles accumulator HBM and
                      breaks the re-arm contract.
      dtype.f32_purity  an f32-config program contains no f64 aval and
                      no convert_element_type to f64.
      structure.walk_loop   trace/trace_packed contain the walk
                      ``while`` loop.
      structure.scan  the megastep's move loop is a ``scan`` — XLA
                      pipelines a static trip count; degrading to a
                      dynamic ``while`` is a silent perf cliff.
      structure.scatter  the XLA walk bodies keep their scatter-add
                      tally writes (losing them means the tally moved
                      off the fused path).
      structure.pallas_call  the pallas family actually lowers to one
                      ``pallas_call`` (a silent fallback to the XLA
                      body would fake every parity test green).
      structure.shard_map  the partitioned step still shard_maps over
                      the device mesh.
    """
    out: list[Finding] = []
    for fam, sig in sigs["families"].items():
        prims = sig["prims"]
        ncb = sum(prims.get(p, 0) for p in _CALLBACK_PRIMS)
        if ncb:
            out.append(_finding(
                "io.callbacks", fam,
                f"{ncb} host-callback primitive(s) inside the compiled "
                "program — each one is a hidden per-dispatch host sync",
            ))
        ntr = sum(prims.get(p, 0) for p in _TRANSFER_PRIMS)
        if ntr:
            out.append(_finding(
                "io.transfers", fam,
                f"{ntr} device_put primitive(s) inside the compiled "
                "program — transfers must stay in the staging layer, "
                "outside the program",
            ))
        if sig["donated_args"] < 1:
            out.append(_finding(
                "donation", fam,
                "no donated argument survived lowering — the flux "
                "accumulator must be donated (input_output_alias / "
                "buffer_donor)",
            ))
        if sig["f64_avals"] or sig["convert_to_f64"]:
            out.append(_finding(
                "dtype.f32_purity", fam,
                f"{sig['f64_avals']} float64 aval(s) and "
                f"{sig['convert_to_f64']} convert_element_type->f64 in "
                "an f32-config program",
            ))
        if fam in ("trace", "trace_packed") and not prims.get("while"):
            out.append(_finding(
                "structure.walk_loop", fam,
                "the walk while-loop is gone from the program",
            ))
        if fam == "megastep":
            if not prims.get("scan"):
                out.append(_finding(
                    "structure.scan", fam,
                    "the fused move loop is no longer a scan — a "
                    "dynamic while defeats XLA's static trip-count "
                    "pipelining",
                ))
        if fam in ("trace", "trace_packed", "megastep") and not prims.get(
            "scatter-add"
        ):
            out.append(_finding(
                "structure.scatter", fam,
                "no scatter-add left in the walk body — the tally "
                "write moved off the fused path",
            ))
        if fam == "pallas" and prims.get("pallas_call", 0) != 1:
            out.append(_finding(
                "structure.pallas_call", fam,
                f"expected exactly 1 pallas_call, found "
                f"{prims.get('pallas_call', 0)} — the kernel path "
                "silently fell back",
            ))
        if fam == "partitioned" and not prims.get("shard_map"):
            out.append(_finding(
                "structure.shard_map", fam,
                "the partitioned step no longer shard_maps over the "
                "device mesh",
            ))
    return out


def diff_baseline(current: dict, baseline: dict) -> list[Finding]:
    """Compare a fresh capture against the committed CONTRACTS.json.

    Any difference is a named finding; intentional changes regenerate
    the baseline with ``scripts/lint.py --write-contracts``.
    """
    out: list[Finding] = []
    if current["environment"] != baseline.get("environment"):
        out.append(_finding(
            "environment", "all",
            f"capture environment {current['environment']} != baseline "
            f"{baseline.get('environment')} — contracts must be "
            "checked under the canonical lint environment "
            "(scripts/lint.py pins it)",
        ))
        return out
    cur_f, base_f = current["families"], baseline.get("families", {})
    for fam in sorted(set(cur_f) | set(base_f)):
        if fam not in base_f:
            out.append(_finding(
                "family.added", fam,
                "program family captured but absent from "
                "CONTRACTS.json — regenerate the baseline",
            ))
            continue
        if fam not in cur_f:
            out.append(_finding(
                "family.removed", fam,
                "program family in CONTRACTS.json but no longer "
                "captured",
            ))
            continue
        c, b = cur_f[fam], base_f[fam]
        for field in ("inputs", "outputs", "donated_args", "f64_avals",
                      "convert_to_f64"):
            if c[field] != b[field]:
                out.append(_finding(
                    f"signature.{field}", fam,
                    f"{field} drifted: baseline {b[field]!r} -> "
                    f"current {c[field]!r}",
                ))
        cp, bp = c["prims"], b["prims"]
        for prim in sorted(set(cp) | set(bp)):
            if cp.get(prim, 0) != bp.get(prim, 0):
                out.append(_finding(
                    f"prims.{prim}", fam,
                    f"primitive count drifted: {prim} "
                    f"{bp.get(prim, 0)} -> {cp.get(prim, 0)}",
                ))
    return out


def load_contracts(path) -> dict:
    with open(path) as fh:
        return json.load(fh)


def write_contracts(path, cap: dict | None = None) -> dict:
    from ..utils.checkpoint import atomic_write_json

    cap = cap or capture()
    # The committed capture is state every later lint run diffs
    # against — atomic write (PUMI008), so an interrupted regeneration
    # can never leave a torn baseline under the real name.
    atomic_write_json(path, cap)
    return cap
