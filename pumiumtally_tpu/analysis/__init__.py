"""Static analysis of the codebase and its compiled programs.

Fourteen PRs of invariants — the 1 H2D + 1 D2H per move/megastep
contract, donated-buffer discipline, bitwise XLA↔Pallas parity, f32
dtype hygiene, the lock protocols of the threaded observers, and the
durability/ordering promises of the crash-safety surface — were until
now pinned only by runtime tests (and chaos campaigns) that must
*execute* a failure to see it.  This package makes them machine-checked
properties of the code and of the lowered programs themselves, in four
layers:

  * :mod:`analysis.astlint` — an AST lint engine with codebase-specific
    rules (PUMI001..PUMI011): host syncs inside traced bodies, transfers
    outside the approved staging modules, use-after-donate, trace-time
    nondeterminism, stray float64 on device paths, jit static-argnum
    hygiene, a ``# guarded by: <lock>`` concurrency lint over the
    threaded surface (FlightRecorder / watchdog / HostStager / exporter),
    and the layer-4 codebase rules: raw persistent writes outside the
    atomic-write modules (PUMI008), signal-handler safety (PUMI009),
    unguarded thread-shared state (PUMI010), and swallowed retryables
    (PUMI011).  The traced-body rules also cover ``scripts/`` and
    ``bench.py``; the journal-owning scripts additionally get
    PUMI008/PUMI009.
  * :mod:`analysis.contracts` — abstract-traces the public program
    families (trace, trace_packed, megastep, the partitioned packed
    step, the Pallas kernel in interpret mode) to jaxpr + lowered
    StableHLO and asserts structural invariants: zero host callbacks and
    zero in-program transfers (the 1+1 contract's compiled half),
    donation aliases actually present, f32 dtype purity, scan-not-while
    control flow, expected scatter counts — then diffs the extracted
    signatures against the committed ``CONTRACTS.json`` baseline so any
    structural drift fails CI with a named invariant.
  * :mod:`analysis.costmodel` — COMPILES the same five families over a
    small shape ladder (still CPU-only, no execution) and gates the
    resource signatures XLA's cost/memory analysis exposes: f64 flop
    census, donation/peak-memory bounds derived from the donated flux +
    per-lane state, the Pallas VMEM-estimator contract mirror, and
    fitted scaling exponents in n_particles / ntet (an accidental
    O(n^2) broadcast becomes a named failure such as
    ``cost.scaling.n_particles.megastep``) — then diffs against the
    committed ``PERF_CONTRACTS.json`` within per-metric tolerance
    bands.  Hardware-free perf regression gates for every program
    family.
  * :mod:`analysis.protolint` — the effect-ordering protocol analyzer
    (layer 4's second half): named effect points (``checkpoint.save``,
    ``journal.flush``, ``manifest.commit``, ``checkpoint.delete``,
    ``handler.install/uninstall``, ``terminal.record``) are recognized
    by callee, and declared happens-before protocols are verified along
    all CFG paths of the functions that own them —
    ``TallyScheduler._finish``/``._poison``, ``SchedulerJournal``,
    ``CheckpointStore``, ``save_sharded_checkpoint``, the signal
    flushes — then diffed against the committed ``PROTOCOLS.json``
    (cross-environment captures refused, regenerable with
    ``--write-protocols``).  The ordering bugs PR 14's reviews caught
    by hand (terminal-record-before-checkpoint-delete, the
    stale-handler clobber) are named, machine-checked findings forever.

``scripts/lint.py`` runs all four layers with the
``LINT_BASELINE.json`` suppression file (every suppression carries a
justification string, and a STALE entry is itself a failure unless
``--allow-stale``); the ``static-analysis`` and ``perf-contracts`` CI
steps fail on any non-baselined finding.
"""
from __future__ import annotations

import dataclasses
import json


@dataclasses.dataclass(frozen=True)
class Finding:
    """One static-analysis finding.

    ``symbol`` is the enclosing ``Class.method`` / function qualname (or
    ``"<module>"``) — baseline suppressions match on (rule, path, symbol)
    so they survive unrelated line-number drift.
    """

    rule: str
    path: str
    line: int
    symbol: str
    message: str

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} [{self.symbol}] "
            f"{self.message}"
        )


def load_baseline(path) -> list[dict]:
    """Read a LINT_BASELINE.json suppression file.

    Schema: ``{"suppressions": [{"rule", "path", "symbol",
    "justification"}, ...]}``.  Every entry MUST carry a non-empty
    justification — an unexplained suppression is itself a finding.
    """
    with open(path) as fh:
        data = json.load(fh)
    entries = data.get("suppressions", [])
    for e in entries:
        for key in ("rule", "path", "symbol", "justification"):
            if not str(e.get(key, "")).strip():
                raise ValueError(
                    f"baseline entry {e!r} is missing a non-empty "
                    f"{key!r} — every suppression must name what it "
                    "hides and why"
                )
    return entries


def apply_baseline(findings: list[Finding], entries: list[dict]):
    """Split findings into (kept, suppressed) and report unused entries.

    Returns ``(kept, suppressed, unused_entries)``.  Unused entries are
    reported so a fixed finding retires its suppression instead of
    leaving a stale hole the next regression could slip through.
    """
    used = [False] * len(entries)

    def matches(e, f):
        return (
            e["rule"] == f.rule
            and e["path"] == f.path
            and e["symbol"] == f.symbol
        )

    kept, suppressed = [], []
    for f in findings:
        hit = None
        for i, e in enumerate(entries):
            if matches(e, f):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = True
            suppressed.append(f)
    unused = [e for i, e in enumerate(entries) if not used[i]]
    return kept, suppressed, unused
