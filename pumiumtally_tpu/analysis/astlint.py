"""AST lint engine with codebase-specific rules (layer 1 + the
codebase half of layer 4).

The rules encode invariants that runtime tests can only witness by
executing a failure; here they are properties of the source tree:

  PUMI001 host-sync-in-traced     ``float()`` / ``.item()`` /
      ``np.asarray`` / ``jax.device_get`` applied to traced values
      inside a traced body — a silent device sync (or a tracer error)
      on the hot path.
  PUMI002 transfer-outside-staging  ``jax.device_put`` /
      ``jax.device_get`` outside the approved staging modules: the
      1 H2D + 1 D2H move contract means transfers are a structural
      property of a handful of files, and a transfer anywhere else is a
      contract hole.
  PUMI003 use-after-donate        a buffer name is passed at a donated
      argnum/argname of a jitted program and then read again — XLA may
      already have scribbled over it.
  PUMI004 nondeterminism-in-traced  ``time.*`` / ``random.*`` /
      ``np.random.*`` / ``datetime.now`` inside a traced body: frozen at
      trace time into the compiled program, different per retrace —
      breaks bitwise replay (checkpoint resume, retry re-arm).
  PUMI005 f64-on-device-path      ``jnp.float64`` (or a "float64"
      dtype literal / ``np.float64`` in a traced body) outside
      ``integrity/audit.py`` — the f32 production configs must stay
      f64-free on device (the shadow audit is the one sanctioned f64
      surface).
  PUMI006 jit-static-hygiene      ``jax.jit(...)`` constructed inside a
      loop (a fresh wrapper and cache entry per iteration), or a
      jitted callable fed a loop induction variable at a STATIC
      argnum/argname (one recompile per iteration).
  PUMI007 guarded-by              attributes annotated
      ``# guarded by: <lock>`` must only be touched under ``with
      <lock>:`` outside ``__init__``; locals annotated
      ``# guarded by: <event> (event)`` must be written only by worker
      closures that ``<event>.set()`` and read only after
      ``<event>.wait(...)``.

Layer-4 codebase rules (the durability & concurrency half; the
effect-ordering protocols live in :mod:`analysis.protolint`):

  PUMI008 raw-durable-write       ``open(..., "w")`` / ``np.save`` /
      ``json.dump`` / ``Path.write_*`` outside the approved
      atomic-write modules (``utils/checkpoint.py``,
      ``serving/journal.py``, ``serving/bank.py``,
      ``resilience/store.py``, ``tuning/db.py``) — a raw write can
      tear under crash/ENOSPC, and torn state is exactly what the
      crash-safety layer exists to rule out.
  PUMI009 signal-handler-safety   handler bodies reachable from
      ``utils/signals.install_preemption_handlers`` must not flush the
      journal without the mid-dispatch deferral guard, take locks
      annotated ``# guarded by:``, or call into jit dispatch; every
      install needs a matching uninstall, and a handler that chains
      the previous handler must uninstall its own first.
  PUMI010 unguarded-thread-shared  state written from functions
      reachable from ``threading.Thread`` targets / executor workers
      without a ``# guarded by:`` annotation — PUMI007 only enforces
      *annotated* state; this closes the inference gap.
  PUMI011 swallowed-retryable     an ``except`` catching a RETRYABLE /
      ``Transient*`` type must re-raise, route through
      ``ResilienceCoordinator.classify``, or count the swallow into a
      metric — silently absorbing a retryable error erases the
      resilience layer's signal.

The traced-body notion is a package-wide fixpoint: functions handed to
``jax.jit`` / ``lax.scan`` / ``while_loop`` / ``fori_loop`` / ``cond`` /
``switch`` / ``vmap`` / ``shard_map`` / ``pallas_call`` /
``checkify.checkify`` (as decorator or argument) are traced, every
function a traced function calls (resolved through module-level defs and
intra-package imports, including function-local imports) is traced, and
nested defs inherit the enclosing function's tracedness.

The fixpoint also covers ``scripts/*.py`` and ``bench.py`` (they jit
package functions and their own bodies, and their absolute
``pumiumtally_tpu.*`` imports resolve into the package index), but only
the value-safety rule subset applies there — PUMI001 host syncs,
PUMI003 use-after-donate (bench.py builds donating jits of its own),
PUMI004 nondeterminism, PUMI005 f64 — because scripts legitimately
stage their own device transfers (PUMI002's approved-module list is a
*package* contract) and throwaway per-config jits in microbenches are
the point of the file (PUMI006).

Findings are suppressed per (rule, path, symbol) through
``LINT_BASELINE.json`` (analysis.apply_baseline) — justification
required.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

from . import Finding

PACKAGE = "pumiumtally_tpu"

# Modules allowed to issue jax.device_put / jax.device_get: the staging
# layer itself, the facades that own the 1+1 move contract, the sharding
# / checkpoint plumbing, and device-table construction.  A transfer
# anywhere else is a new, unaccounted host<->device edge.
APPROVED_TRANSFER_MODULES = frozenset(
    {
        f"{PACKAGE}/ops/staging.py",
        f"{PACKAGE}/ops/source.py",
        f"{PACKAGE}/ops/walk_partitioned.py",
        f"{PACKAGE}/api.py",
        f"{PACKAGE}/parallel/partitioned_api.py",
        f"{PACKAGE}/parallel/particle_sharding.py",
        f"{PACKAGE}/utils/checkpoint.py",
        f"{PACKAGE}/models/pipeline.py",
        # The per-chip health probe stages a tiny round-trip array on
        # every device by design (resilience taxonomy: a dead chip
        # fails the put) — a deliberate, accounted transfer edge.
        f"{PACKAGE}/resilience/coordinator.py",
    }
)

# The one module allowed to hold float64 on purpose: the shadow-audit
# reference walker is DEFINED as an f64 NumPy oracle.
F64_EXEMPT_MODULES = frozenset({f"{PACKAGE}/integrity/audit.py"})

# Modules allowed to perform raw persistent writes: they ARE the
# atomic-write layer (tmp + fsync + rename) every other module must
# route durable state through.  A raw write anywhere else can tear
# under crash/ENOSPC — the exact failure mode the crash-safety surface
# (journal, two-phase checkpoints) exists to rule out.
APPROVED_DURABLE_MODULES = frozenset(
    {
        f"{PACKAGE}/utils/checkpoint.py",
        f"{PACKAGE}/serving/journal.py",
        f"{PACKAGE}/serving/bank.py",
        f"{PACKAGE}/resilience/store.py",
        f"{PACKAGE}/tuning/db.py",
    }
)

# Rule subset applied to sources OUTSIDE the package tree (scripts/,
# bench.py): the traced-body contracts travel with the jitted code
# wherever it is launched from, and use-after-donate corrupts data no
# matter who built the donating jit (bench.py does); the
# transfer-placement and jit-hygiene rules are package-structure
# contracts and stay package-scoped.
SCRIPT_RULES = frozenset({"PUMI001", "PUMI003", "PUMI004", "PUMI005"})

# Scripts that OWN crash-safety surface: serve.py writes result JSON
# beside the journal it resumes from, chaos_serve.py orchestrates the
# kill/restart campaign around signal-sensitive subprocesses — they
# additionally get the durability + signal-handler rules on top of the
# value-safety subset.
JOURNAL_SCRIPTS = frozenset({
    "scripts/serve.py", "scripts/chaos_serve.py",
    "scripts/chaos_fleet.py",
})
JOURNAL_SCRIPT_RULES = SCRIPT_RULES | frozenset({"PUMI008", "PUMI009"})


def rules_for_path(path: str) -> frozenset | None:
    """The rule subset applied to ``path`` (None = every rule)."""
    if path.startswith(f"{PACKAGE}/"):
        return None
    if path in JOURNAL_SCRIPTS:
        return JOURNAL_SCRIPT_RULES
    return SCRIPT_RULES

# Call heads whose function-valued arguments become traced.
_TRACING_HEADS_LAST = frozenset(
    {"jit", "pallas_call", "shard_map", "vmap", "pmap", "checkify"}
)
_TRACING_HEADS_LAX = frozenset(
    {
        "scan",
        "while_loop",
        "fori_loop",
        "cond",
        "switch",
        "map",
        "associative_scan",
        "custom_root",
    }
)

_HOST_SYNC_FUNCS = frozenset({"float", "int", "bool"})
_HOST_SYNC_ATTRS = frozenset({"item", "tolist", "to_py", "__array__"})
_HOST_SYNC_NP = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
)
_DEVICE_GET = frozenset({"jax.device_get", "device_get"})
_DEVICE_PUT = frozenset({"jax.device_put", "device_put"})

_NONDET_PREFIXES = (
    "time.",
    "random.",
    "np.random.",
    "numpy.random.",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.now",
    "os.urandom",
    "uuid.",
    "secrets.",
)

_GUARD_RE = re.compile(r"#\s*guarded by:\s*(?P<lock>[^#]+?)\s*$")
_EVENT_SUFFIX_RE = re.compile(r"\(event\)\s*$")


def _walk_shallow(fn):
    """Walk a function body WITHOUT descending into nested defs: each
    def is analyzed as its own scope (it has its own entry in
    ``PackageIndex.defs``), so a deep walk would double-report and
    cross-taint sibling scopes.  Lambdas stay in scope — they share the
    enclosing function's locals."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            stack.extend(ast.iter_child_nodes(node))


def _dotted(node) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class Module:
    path: str
    tree: ast.Module
    lines: list[str]
    comments: dict[int, str] = field(default_factory=dict)


def _parse(path: str, source: str) -> Module:
    tree = ast.parse(source, filename=path)
    comments: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
            io.StringIO(source).readline
        ):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return Module(path, tree, source.splitlines(), comments)


# --------------------------------------------------------------------- #
# Package index: defs, imports, traced-function fixpoint
# --------------------------------------------------------------------- #
def _module_of_import(cur_path: str, level: int, module: str | None,
                      known: set[str]) -> str | None:
    """Resolve a (possibly relative) import to a known package relpath
    (``a/b.py`` or ``a/b/__init__.py``), else None."""
    if level == 0:
        base = (module or "").split(".")
        if base and base[0] != PACKAGE.split("/")[0]:
            return None
        parts = base
    else:
        here = cur_path.split("/")[:-1]  # directory of current module
        up = level - 1
        if up:
            here = here[: len(here) - up] if up <= len(here) else []
        parts = here + ([p for p in (module or "").split(".") if p])
    cand = "/".join(parts) + ".py"
    if cand in known:
        return cand
    cand = "/".join(parts) + "/__init__.py"
    if cand in known:
        return cand
    return None


class PackageIndex:
    """Cross-module name resolution + the traced-function fixpoint."""

    def __init__(self, modules: dict[str, Module]):
        self.modules = modules
        known = set(modules)
        # (path, qualname) -> def node
        self.defs: dict[tuple[str, str], ast.AST] = {}
        # path -> {local name -> ("def", qualname) |
        #          ("name", path2, remote_name) | ("mod", path2)}
        self.scope: dict[str, dict] = {}
        self.parents: dict[str, dict[ast.AST, ast.AST]] = {}
        for path, mod in modules.items():
            env: dict = {}
            parent: dict[ast.AST, ast.AST] = {}
            for node in ast.walk(mod.tree):
                for child in ast.iter_child_nodes(node):
                    parent[child] = node
            self.parents[path] = parent
            for node in ast.walk(mod.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    q = self._qualname(path, node, parent)
                    self.defs[(path, q)] = node
                    if "." not in q:
                        env[node.name] = ("def", q)
                elif isinstance(node, ast.ImportFrom):
                    tgt = _module_of_import(
                        path, node.level, node.module, known
                    )
                    for alias in node.names:
                        name = alias.asname or alias.name
                        if tgt is None:
                            continue
                        # `from . import staging` resolves the NAME as a
                        # submodule when one exists.
                        sub = _module_of_import(
                            path, node.level,
                            f"{node.module}.{alias.name}"
                            if node.module else alias.name,
                            known,
                        )
                        if sub is not None:
                            env.setdefault(name, ("mod", sub))
                        else:
                            env.setdefault(
                                name, ("name", tgt, alias.name)
                            )
                elif isinstance(node, ast.Import):
                    pass  # absolute external imports — not package code
            self.scope[path] = env
        self.traced: set[tuple[str, str]] = set()
        self._seed_traced()
        self._propagate()

    # -- qualnames ---------------------------------------------------- #
    def _qualname(self, path, node, parent) -> str:
        parts = [node.name]
        cur = parent.get(node)
        while cur is not None:
            if isinstance(
                cur,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                parts.append(cur.name)
            cur = parent.get(cur)
        return ".".join(reversed(parts))

    def qualname(self, path, node) -> str:
        return self._qualname(path, node, self.parents[path])

    def enclosing_symbol(self, path, node) -> str:
        cur = node
        parent = self.parents[path]
        while cur is not None:
            if isinstance(
                cur,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                return self._qualname(path, cur, parent)
            cur = parent.get(cur)
        return "<module>"

    # -- traced fixpoint ---------------------------------------------- #
    def _is_tracing_head(self, func) -> bool:
        d = _dotted(func)
        if d is None:
            # jax.jit(...)(x) etc — head is itself a call; the inner
            # call was already seen by ast.walk.
            return False
        last = d.split(".")[-1]
        if last in _TRACING_HEADS_LAST:
            return True
        if last in _TRACING_HEADS_LAX:
            head = d.split(".")[0]
            return head in ("lax", "jax") or d.startswith("jax.lax.")
        return False

    def _callable_args(self, call: ast.Call):
        for a in list(call.args) + [k.value for k in call.keywords]:
            yield a
            # functools.partial(fn, ...) / partial(fn, ...)
            if isinstance(a, ast.Call):
                d = _dotted(a.func) or ""
                if d.split(".")[-1] == "partial" and a.args:
                    yield a.args[0]

    def _resolve(self, path: str, name_node,
                 local_env: dict | None = None):
        """Resolve a Name/Attribute to a (path, qualname) def key."""
        if isinstance(name_node, ast.Name):
            name = name_node.id
            for env in (local_env or {},):
                if name in env:
                    return env[name]
            entry = self.scope[path].get(name)
            if entry is None:
                return None
            if entry[0] == "def":
                return ("def@", path, entry[1])
            if entry[0] == "name":
                _, p2, remote = entry
                if (p2, remote) in self.defs:
                    return ("def@", p2, remote)
                return None
            return None
        if isinstance(name_node, ast.Attribute):
            base = name_node.value
            if isinstance(base, ast.Name):
                entry = self.scope[path].get(base.id)
                if entry and entry[0] == "mod":
                    p2 = entry[1]
                    if (p2, name_node.attr) in self.defs:
                        return ("def@", p2, name_node.attr)
        return None

    def _local_defs_env(self, path, fn) -> dict:
        env = {}
        for node in ast.walk(fn):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node is not fn:
                env[node.name] = (
                    "def@", path, self.qualname(path, node)
                )
        return env

    def _mark(self, key):
        if key and key[0] == "def@":
            self.traced.add((key[1], key[2]))

    def _seed_traced(self):
        for path, mod in self.modules.items():
            for node in ast.walk(mod.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for dec in node.decorator_list:
                        head = dec.func if isinstance(
                            dec, ast.Call
                        ) else dec
                        d = _dotted(head) or ""
                        if d.split(".")[-1] in _TRACING_HEADS_LAST:
                            self.traced.add(
                                (path, self.qualname(path, node))
                            )
                        if isinstance(dec, ast.Call) and d.split(
                            "."
                        )[-1] == "partial":
                            inner = dec.args[0] if dec.args else None
                            di = _dotted(inner) or ""
                            if di.split(".")[-1] in _TRACING_HEADS_LAST:
                                self.traced.add(
                                    (path, self.qualname(path, node))
                                )
                elif isinstance(node, ast.Call) and self._is_tracing_head(
                    node.func
                ):
                    enc = self._enclosing_fn(path, node)
                    local = (
                        self._local_defs_env(path, enc) if enc else {}
                    )
                    for a in self._callable_args(node):
                        self._mark(self._resolve(path, a, local))

    def _enclosing_fn(self, path, node):
        cur = node
        parent = self.parents[path]
        while cur is not None:
            cur = parent.get(cur)
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                return cur
        return None

    def _propagate(self):
        """Close traced-ness over lexical nesting and the call graph."""
        changed = True
        while changed:
            changed = False
            # Lexical: nested defs of traced functions are traced.
            for (path, q) in list(self.traced):
                prefix = q + "."
                for (p2, q2) in self.defs:
                    if p2 == path and q2.startswith(prefix):
                        if (p2, q2) not in self.traced:
                            self.traced.add((p2, q2))
                            changed = True
            # Call graph: callees of traced functions are traced.
            for (path, q) in list(self.traced):
                fn = self.defs.get((path, q))
                if fn is None:
                    continue
                local = self._local_defs_env(path, fn)
                local.update(self._fn_import_env(path, fn))
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        key = self._resolve(path, node.func, local)
                        if (
                            key
                            and key[0] == "def@"
                            and (key[1], key[2]) not in self.traced
                        ):
                            self.traced.add((key[1], key[2]))
                            changed = True

    def _fn_import_env(self, path, fn) -> dict:
        """Function-local `from .x import y` imports (idiomatic here for
        cycle avoidance) resolved like module-level ones."""
        env = {}
        known = set(self.modules)
        for node in ast.walk(fn):
            if isinstance(node, ast.ImportFrom):
                tgt = _module_of_import(
                    path, node.level, node.module, known
                )
                if tgt is None:
                    continue
                for alias in node.names:
                    name = alias.asname or alias.name
                    if (tgt, alias.name) in self.defs:
                        env[name] = ("def@", tgt, alias.name)
        return env

    def is_traced(self, path, fn_node) -> bool:
        return (path, self.qualname(path, fn_node)) in self.traced


# --------------------------------------------------------------------- #
# Per-function taint (positional params + derived locals)
# --------------------------------------------------------------------- #
def _taint_set(fn: ast.FunctionDef) -> set[str]:
    """Names in ``fn`` that (syntactically) carry traced array values:
    POSITIONAL parameters and anything assigned from an expression that
    mentions a tainted name or calls into jnp/lax/jax.  Keyword-only
    parameters are the codebase's static-knob convention (every jit
    static_argname is kw-only) and stay untainted."""
    tainted = {
        a.arg
        for a in list(fn.args.args) + list(fn.args.posonlyargs)
        if a.arg not in ("self", "cls")
    }
    if fn.args.vararg:
        tainted.add(fn.args.vararg.arg)

    # Static-at-trace-time metadata: reading .shape/.dtype/... of a
    # traced array (or len() of it) yields a Python value, not a traced
    # one — without this, ``n = origin.shape[0]`` would taint ``n`` and
    # every static size computed from it.
    _STATIC_ATTRS = {"shape", "ndim", "dtype", "itemsize", "weak_type"}
    _STATIC_CALLS = {"len", "jnp.finfo", "jnp.iinfo", "jnp.dtype",
                     "np.finfo", "np.iinfo", "np.dtype", "isinstance",
                     "getattr", "hasattr", "type"}

    def expr_tainted(e) -> bool:
        if isinstance(e, ast.Attribute) and e.attr in _STATIC_ATTRS:
            return False
        if isinstance(e, ast.Call):
            d = _dotted(e.func) or ""
            if d in _STATIC_CALLS:
                return False
            if d.split(".")[0] in ("jnp", "lax") or d.startswith(
                "jax."
            ):
                return True
        if isinstance(e, ast.Name):
            return e.id in tainted
        return any(
            expr_tainted(sub) for sub in ast.iter_child_nodes(e)
        )

    changed = True
    while changed:
        changed = False
        for node in _walk_shallow(fn):
            tgt_names: list[str] = []
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            tgt_names.append(sub.id)
            elif isinstance(node, ast.AugAssign) and expr_tainted(
                node.value
            ):
                if isinstance(node.target, ast.Name):
                    tgt_names.append(node.target.id)
            elif isinstance(node, ast.For) and expr_tainted(node.iter):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        tgt_names.append(sub.id)
            for n in tgt_names:
                if n not in tainted:
                    tainted.add(n)
                    changed = True
    return tainted


def _is_tainted_ref(node, tainted: set[str]) -> bool:
    """Direct reference to a tainted value: a tainted Name or an
    attribute chain rooted at one (``result.done``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in tainted


# --------------------------------------------------------------------- #
# Rules
# --------------------------------------------------------------------- #
def _rule_host_sync(index: PackageIndex, out: list[Finding]):
    for (path, q), fn in index.defs.items():
        if (path, q) in index.traced:
            tainted = _taint_set(fn)
            for node in _walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                msg = None
                if d in _DEVICE_GET:
                    msg = (
                        f"{d}() inside traced body — a host sync "
                        "compiled into the program (or a tracer leak)"
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _HOST_SYNC_FUNCS
                    and node.args
                    and _is_tainted_ref(node.args[0], tainted)
                ):
                    msg = (
                        f"{node.func.id}() on traced value "
                        f"'{ast.unparse(node.args[0])}' inside traced "
                        "body — blocks on device readback"
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_SYNC_ATTRS
                    and _is_tainted_ref(node.func.value, tainted)
                ):
                    msg = (
                        f".{node.func.attr}() on traced value "
                        f"'{ast.unparse(node.func.value)}' inside "
                        "traced body — blocks on device readback"
                    )
                elif (
                    d in _HOST_SYNC_NP
                    and node.args
                    and _is_tainted_ref(node.args[0], tainted)
                ):
                    msg = (
                        f"{d}() on traced value "
                        f"'{ast.unparse(node.args[0])}' inside traced "
                        "body — materializes the array on host"
                    )
                if msg:
                    out.append(
                        Finding(
                            "PUMI001", path, node.lineno, q, msg
                        )
                    )


def _rule_transfers(index: PackageIndex, out: list[Finding]):
    for path, mod in index.modules.items():
        if path in APPROVED_TRANSFER_MODULES:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in _DEVICE_PUT or d in _DEVICE_GET:
                    out.append(
                        Finding(
                            "PUMI002",
                            path,
                            node.lineno,
                            index.enclosing_symbol(path, node),
                            f"{d}() outside the approved staging "
                            "modules — every host<->device edge must "
                            "live in the staging/facade layer so the "
                            "1 H2D + 1 D2H move contract stays "
                            "structural",
                        )
                    )


@dataclass
class _DonationSpec:
    """Donated params of one jitted callable, by position and name."""

    argnums: tuple[int, ...] = ()
    argnames: tuple[str, ...] = ()


def _collect_donating(index: PackageIndex) -> dict[tuple[str, str], _DonationSpec]:
    """Module-level ``X = jax.jit(fn, donate_arg...)`` assignments, plus
    simple same-module wrappers ``def w(*a, **kw): return X(...)``."""
    donating: dict[tuple[str, str], _DonationSpec] = {}
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            call = node.value
            d = _dotted(call.func) or ""
            if d.split(".")[-1] != "jit":
                continue
            spec = _DonationSpec()
            wrapped = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    spec = _DonationSpec(
                        tuple(
                            e.value
                            for e in ast.walk(kw.value)
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int)
                        ),
                        spec.argnames,
                    )
                elif kw.arg == "donate_argnames":
                    spec = _DonationSpec(
                        spec.argnums,
                        tuple(
                            e.value
                            for e in ast.walk(kw.value)
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        ),
                    )
            if not (spec.argnums or spec.argnames):
                continue
            # donate_argnames -> positional indices through the wrapped
            # def's signature when resolvable in-package.
            wkey = index._resolve(path, wrapped) if wrapped else None
            if wkey and wkey[0] == "def@":
                wfn = index.defs[(wkey[1], wkey[2])]
                params = [
                    a.arg
                    for a in list(wfn.args.posonlyargs)
                    + list(wfn.args.args)
                ]
                nums = set(spec.argnums)
                for nm in spec.argnames:
                    if nm in params:
                        nums.add(params.index(nm))
                spec = _DonationSpec(
                    tuple(sorted(nums)), spec.argnames
                )
            donating[(path, node.targets[0].id)] = spec
    # Pass-through wrappers: `def trace(*args, **kwargs): return
    # _trace_jit(*args, ...)` inherits the jit's donation spec.
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            rets = [
                s
                for s in ast.walk(node)
                if isinstance(s, ast.Return) and s.value is not None
            ]
            for r in rets:
                if isinstance(r.value, ast.Call):
                    d = _dotted(r.value.func)
                    if d and (path, d) in donating:
                        donating.setdefault(
                            (path, node.name), donating[(path, d)]
                        )
    return donating


def _rule_use_after_donate(index: PackageIndex, out: list[Finding]):
    donating = _collect_donating(index)

    def site_spec(path, call, local_env) -> _DonationSpec | None:
        d = _dotted(call.func)
        if d is None:
            return None
        if (path, d) in donating:
            return donating[(path, d)]
        # imported name from another module
        entry = index.scope[path].get(d.split(".")[0])
        if entry and entry[0] == "name":
            _, p2, remote = entry
            if (p2, remote) in donating and "." not in d:
                return donating[(p2, remote)]
        return None

    for (path, q), fn in index.defs.items():
        events = []  # (lineno, kind, name)
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Call):
                spec = site_spec(path, node, None)
                if spec is None:
                    continue
                donated_exprs = []
                for i in spec.argnums:
                    if i < len(node.args):
                        nm = _dotted(node.args[i])
                        if nm:
                            donated_exprs.append(nm)
                for kw in node.keywords:
                    if kw.arg in spec.argnames:
                        nm = _dotted(kw.value)
                        if nm:
                            donated_exprs.append(nm)
                # The donation takes effect once the call completes:
                # anchor at the call's LAST line so the call's own
                # multi-line argument list never self-reports.
                for nm in donated_exprs:
                    events.append(
                        (node.end_lineno or node.lineno, "donate", nm)
                    )
        if not events:
            continue
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Name):
                nm = node.id
            elif isinstance(node, ast.Attribute):
                nm = _dotted(node)
                if nm is None:
                    continue
            else:
                continue
            if isinstance(node.ctx, ast.Store):
                events.append((node.lineno, "store", nm))
            elif isinstance(node.ctx, ast.Load):
                events.append((node.lineno, "load", nm))
        events.sort(key=lambda e: (e[0], {"donate": 1, "store": 2,
                                          "load": 0}[e[1]]))
        live_donated: dict[str, int] = {}
        reported = set()
        for lineno, kind, nm in events:
            if kind == "donate":
                live_donated[nm] = lineno
            elif kind == "store":
                live_donated.pop(nm, None)
            elif kind == "load" and nm in live_donated:
                if lineno > live_donated[nm] and nm not in reported:
                    reported.add(nm)
                    out.append(
                        Finding(
                            "PUMI003",
                            path,
                            lineno,
                            q,
                            f"'{nm}' read after being donated at line "
                            f"{live_donated[nm]} — the buffer may "
                            "already be aliased by the program's "
                            "output; re-bind it from the result",
                        )
                    )


def _rule_nondeterminism(index: PackageIndex, out: list[Finding]):
    for (path, q), fn in index.defs.items():
        if (path, q) not in index.traced:
            continue
        for node in _walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            if any(
                d.startswith(p) or d == p.rstrip(".")
                for p in _NONDET_PREFIXES
            ):
                out.append(
                    Finding(
                        "PUMI004",
                        path,
                        node.lineno,
                        q,
                        f"{d}() inside traced body — the value is "
                        "frozen at trace time and differs per retrace, "
                        "breaking bitwise replay (checkpoint resume, "
                        "retry re-arm); thread RNG keys / counters "
                        "through the program inputs instead",
                    )
                )


_DTYPE_CALL_HEADS = frozenset(
    {
        "array",
        "asarray",
        "zeros",
        "ones",
        "full",
        "empty",
        "arange",
        "astype",
        "dtype",
        "zeros_like",
        "ones_like",
        "full_like",
        "convert_element_type",
    }
)


_DTYPE_DISPATCH_RE = re.compile(r"float64|uint64|uint32|itemsize|x64")


def _in_dtype_dispatch(parents, node) -> bool:
    """True when the usage sits under an ``if``/ternary whose test is a
    dtype/carrier-width dispatch (``if dtype == jnp.float64:``,
    ``... if rec.dtype == jnp.uint32 else ...``) — the codebase's
    sanctioned pattern for dtype-polymorphic helpers, where the f64
    branch only executes for f64 configs."""
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.If, ast.IfExp)):
            try:
                if _DTYPE_DISPATCH_RE.search(ast.unparse(cur.test)):
                    return True
            except Exception:
                pass
        cur = parents.get(cur)
    return False


def _rule_f64(index: PackageIndex, out: list[Finding]):
    for path, mod in index.modules.items():
        if path in F64_EXEMPT_MODULES:
            continue
        # jnp.float64 anywhere in the package (device dtype by
        # construction); np.float64 / "float64" literals only inside
        # traced bodies (host-side f64 staging is legitimate).
        for node in ast.walk(mod.tree):
            d = _dotted(node) if isinstance(node, ast.Attribute) else None
            if d in ("jnp.float64", "jax.numpy.float64"):
                if _in_dtype_dispatch(index.parents[path], node):
                    continue
                out.append(
                    Finding(
                        "PUMI005",
                        path,
                        node.lineno,
                        index.enclosing_symbol(path, node),
                        f"{d} creates a float64 device array — the "
                        "f32 production config must stay f64-free on "
                        "device (integrity/audit.py is the sanctioned "
                        "f64 surface)",
                    )
                )
    # np.float64 / "float64" literals: traced bodies only (host-side
    # f64 staging is legitimate).
    for (path, q), fn in index.defs.items():
        if path in F64_EXEMPT_MODULES or (path, q) not in index.traced:
            continue
        for node in _walk_shallow(fn):
            if _in_dtype_dispatch(index.parents[path], node):
                continue
            if isinstance(node, ast.Attribute):
                if _dotted(node) in ("np.float64", "numpy.float64"):
                    out.append(
                        Finding(
                            "PUMI005", path, node.lineno, q,
                            "np.float64 inside traced body — "
                            "promotes the device path to f64",
                        )
                    )
            elif isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d.split(".")[-1] not in _DTYPE_CALL_HEADS:
                    continue
                for a in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if _const_str(a) == "float64":
                        out.append(
                            Finding(
                                "PUMI005", path, node.lineno, q,
                                f'"float64" dtype literal in '
                                f"{d}() inside traced body",
                            )
                        )


def _rule_jit_hygiene(index: PackageIndex, out: list[Finding]):
    # Static-argnum specs of module-level jits (donating or not).
    statics: dict[tuple[str, str], tuple[int, ...]] = {}
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            d = _dotted(node.value.func) or ""
            if d.split(".")[-1] != "jit":
                continue
            nums: set[int] = set()
            for kw in node.value.keywords:
                if kw.arg == "static_argnums":
                    nums |= {
                        e.value
                        for e in ast.walk(kw.value)
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    }
            if nums:
                statics[(path, node.targets[0].id)] = tuple(
                    sorted(nums)
                )

    for path, mod in index.modules.items():
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            loop_vars = set()
            if isinstance(loop, ast.For):
                for sub in ast.walk(loop.target):
                    if isinstance(sub, ast.Name):
                        loop_vars.add(sub.id)
            for node in ast.walk(loop):
                if node is loop or not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func) or ""
                if d.split(".")[-1] == "jit" and d in (
                    "jit",
                    "jax.jit",
                ):
                    out.append(
                        Finding(
                            "PUMI006",
                            path,
                            node.lineno,
                            index.enclosing_symbol(path, node),
                            "jax.jit(...) constructed inside a loop — "
                            "a fresh wrapper (and for local callables "
                            "a fresh cache entry, i.e. a recompile) "
                            "per iteration; hoist the jit out of the "
                            "loop",
                        )
                    )
                    continue
                key = (path, d)
                if key in statics and loop_vars:
                    for i in statics[key]:
                        if i < len(node.args) and isinstance(
                            node.args[i], ast.Name
                        ) and node.args[i].id in loop_vars:
                            out.append(
                                Finding(
                                    "PUMI006",
                                    path,
                                    node.lineno,
                                    index.enclosing_symbol(
                                        path, node
                                    ),
                                    f"loop variable "
                                    f"'{node.args[i].id}' passed at "
                                    f"STATIC argnum {i} of jitted "
                                    f"'{d}' — one recompile per "
                                    "iteration",
                                )
                            )


# --------------------------------------------------------------------- #
# PUMI007: # guarded by: <lock> concurrency lint
# --------------------------------------------------------------------- #
def _guard_annotations(mod: Module):
    """Map line number → lock expression for every ``# guarded by:``
    comment in the module; the callers associate each with the
    assignment statement on that line (a ``self.X = ...`` attribute or,
    with the ``(event)`` suffix, a guarded local)."""
    annotated_lines: dict[int, str] = {}
    for lineno, comment in mod.comments.items():
        m = _GUARD_RE.search(comment)
        if m:
            annotated_lines[lineno] = m.group("lock").strip()
    return annotated_lines


def _with_lock_stack(parents, node) -> list[str]:
    """Lock expressions of every enclosing ``with`` block."""
    locks = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                try:
                    locks.append(ast.unparse(item.context_expr))
                except Exception:
                    pass
        cur = parents.get(cur)
    return locks


def _class_attr_guards(mod: Module, cls: ast.ClassDef) -> dict[str, str]:
    """``self.<attr>`` → lock expression for every annotated attribute
    assignment inside ``cls`` (shared by PUMI007's enforcement and
    PUMI010's is-it-annotated-at-all check)."""
    annotated = _guard_annotations(mod)
    attr_guards: dict[str, str] = {}
    if not annotated:
        return attr_guards
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        lock = annotated.get(node.lineno)
        if lock is None:
            continue
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
        )
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                attr_guards[t.attr] = lock
    return attr_guards


def _rule_guarded_by(index: PackageIndex, out: list[Finding]):
    """PUMI007 — declared lock protocols, enforced.

    Rationale: the threaded surface (FlightRecorder, HostStager,
    exporter, watchdog) declares its discipline as ``# guarded by:
    <lock>`` comments; an access outside ``with <lock>:`` is a data
    race a test only sees when the interleaving cooperates.
    Example finding: ``self._records`` annotated ``# guarded by:
    self._lock`` appended without the lock held.
    Fix pattern: wrap the access in ``with <lock>:`` (or, for
    event-guarded handoffs, add the missing ``set()``/``wait()`` edge).
    """
    for path, mod in index.modules.items():
        annotated = _guard_annotations(mod)
        if not annotated:
            continue
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attr_guards = _class_attr_guards(mod, cls)
            if attr_guards:
                _check_attr_guards(
                    index, path, cls, attr_guards, out
                )
        # Event-guarded locals: annotations on plain local assignments
        # inside any function ("<name> (event)").
        for fn_key, fn in index.defs.items():
            if fn_key[0] != path:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                lock = annotated.get(node.lineno)
                if lock is None or not _EVENT_SUFFIX_RE.search(lock):
                    continue
                event = _EVENT_SUFFIX_RE.sub("", lock).strip()
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        _check_event_guard(
                            index, path, fn_key[1], fn, t.id,
                            event, node.lineno, out,
                        )


def _check_attr_guards(index, path, cls, attr_guards, out):
    parents = index.parents[path]
    for method in cls.body:
        if not isinstance(
            method, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        if method.name in ("__init__", "__del__"):
            # Construction precedes thread visibility; finalizers run
            # after every worker is joined.
            continue
        q = index.qualname(path, method)
        for node in ast.walk(method):
            if not (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in attr_guards
            ):
                continue
            lock = attr_guards[node.attr]
            held = _with_lock_stack(parents, node)
            if lock not in held:
                out.append(
                    Finding(
                        "PUMI007",
                        path,
                        node.lineno,
                        q,
                        f"self.{node.attr} is annotated "
                        f"'# guarded by: {lock}' but is accessed "
                        f"outside 'with {lock}:'",
                    )
                )


def _check_event_guard(index, path, q, fn, local, event, ann_line, out):
    """Writes to ``local`` inside nested defs must also call
    ``<event>.set()`` there; reads of ``local`` in the outer body must
    come after an ``<event>.wait(...)`` call."""
    nested = [
        n
        for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not fn
    ]
    in_nested = set()
    for nf in nested:
        for sub in ast.walk(nf):
            in_nested.add(id(sub))

    def writes_local(node):
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ):
            return (
                node.value.id == local
                and isinstance(node.ctx, ast.Store)
            )
        return (
            isinstance(node, ast.Name)
            and node.id == local
            and isinstance(node.ctx, ast.Store)
        )

    def calls(tree, dotted_suffix):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d == dotted_suffix:
                    yield node

    for nf in nested:
        if any(writes_local(n) for n in ast.walk(nf)):
            if not any(calls(nf, f"{event}.set")):
                out.append(
                    Finding(
                        "PUMI007",
                        path,
                        nf.lineno,
                        q,
                        f"worker '{nf.name}' writes "
                        f"'{local}' (guarded by {event}) without "
                        f"calling {event}.set() — the reader's "
                        "happens-before edge is missing",
                    )
                )
    wait_lines = [
        c.lineno
        for c in calls(fn, f"{event}.wait")
        if id(c) not in in_nested
    ]
    first_wait = min(wait_lines) if wait_lines else None
    for node in ast.walk(fn):
        if id(node) in in_nested or not isinstance(node, ast.Name):
            continue
        if (
            node.id == local
            and isinstance(node.ctx, ast.Load)
            and node.lineno > ann_line
            and (first_wait is None or node.lineno <= first_wait)
        ):
            out.append(
                Finding(
                    "PUMI007",
                    path,
                    node.lineno,
                    q,
                    f"'{local}' (guarded by {event}) read before "
                    f"{event}.wait(...) — the worker may still be "
                    "writing it",
                )
            )


# --------------------------------------------------------------------- #
# Shared layer-4 machinery: raw-write classification + reachability
# --------------------------------------------------------------------- #
#: Write heads that serialize straight to a path: head dotted name →
#: index of the file/path argument.
_RAW_WRITE_HEADS = {
    "np.save": 0, "numpy.save": 0,
    "np.savez": 0, "numpy.savez": 0,
    "np.savez_compressed": 0, "numpy.savez_compressed": 0,
    "np.savetxt": 0, "numpy.savetxt": 0,
    "json.dump": 1, "pickle.dump": 1,
}
_PATH_WRITE_ATTRS = frozenset({"write_text", "write_bytes"})


def _open_mode(call: ast.Call) -> str | None:
    mode = None
    if len(call.args) >= 2:
        mode = _const_str(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = _const_str(kw.value)
    return mode


def _scope_file_bindings(nodes) -> tuple[set[str], set[str]]:
    """(names bound from ``open(...)``, names bound from in-memory
    buffers like ``io.BytesIO()``/``StringIO()``) within one scope —
    derivative writes through them are attributed to the ``open`` (or
    are in-memory and durable-irrelevant), not double-reported."""
    opened: set[str] = set()
    buffers: set[str] = set()
    def note(name, value):
        if not isinstance(value, ast.Call):
            return
        d = _dotted(value.func) or ""
        last = d.split(".")[-1]
        if last in ("open", "fdopen"):
            opened.add(name)
        elif last in ("BytesIO", "StringIO"):
            buffers.add(name)
    for node in nodes:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    note(t.id, node.value)
        elif isinstance(node, ast.withitem):
            if isinstance(node.optional_vars, ast.Name):
                note(node.optional_vars.id, node.context_expr)
        elif isinstance(node, ast.With):
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name):
                    note(item.optional_vars.id, item.context_expr)
    return opened, buffers


def raw_write_head(call: ast.Call, opened: set[str],
                   buffers: set[str]) -> str | None:
    """Classify one call as a raw persistent write; returns the head
    description, or None.  ``opened``/``buffers`` are the scope's file
    bindings (``_scope_file_bindings``): writes through an already-
    reported ``open`` handle or into an in-memory buffer are skipped."""
    d = _dotted(call.func)
    if d is None:
        return None
    last = d.split(".")[-1]
    if last == "open" and d in ("open", "io.open"):
        mode = _open_mode(call)
        if mode is not None and any(c in mode for c in "wax"):
            return f'open(..., "{mode}")'
        return None
    if d in _RAW_WRITE_HEADS:
        i = _RAW_WRITE_HEADS[d]
        arg = call.args[i] if len(call.args) > i else None
        if isinstance(arg, ast.Name) and arg.id in (opened | buffers):
            return None
        if isinstance(arg, ast.Call):
            inner = (_dotted(arg.func) or "").split(".")[-1]
            if inner in ("open", "fdopen", "BytesIO", "StringIO"):
                # json.dump(obj, open(p, "w")) is ONE write — the
                # inline open reports it (or it's an in-memory buffer).
                return None
        return f"{d}()"
    if isinstance(call.func, ast.Attribute) and (
        call.func.attr in _PATH_WRITE_ATTRS
    ):
        return f".{call.func.attr}()"
    return None


def _enclosing_class(index: PackageIndex, path, node) -> ast.ClassDef | None:
    cur = node
    parent = index.parents[path]
    while cur is not None:
        cur = parent.get(cur)
        if isinstance(cur, ast.ClassDef):
            return cur
    return None


def _class_method(cls: ast.ClassDef | None, name: str):
    if cls is None:
        return None
    for stmt in cls.body:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and stmt.name == name:
            return stmt
    return None


def _resolve_callable(index: PackageIndex, path, expr, cls,
                      local_env=None):
    """Resolve a callable expression to (path, fn_node, class) — a
    ``self.X`` method of ``cls``, a local/module def, or an imported
    package def.  None when not statically resolvable."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        m = _class_method(cls, expr.attr)
        return (path, m, cls) if m is not None else None
    key = index._resolve(path, expr, local_env)
    if key and key[0] == "def@":
        fn = index.defs.get((key[1], key[2]))
        if fn is not None:
            return (key[1], fn, _enclosing_class(index, key[1], fn))
    return None


def _reachable_callables(index: PackageIndex, start):
    """Transitive closure of statically-resolvable calls from ``start``
    = (path, fn_node, class): self-methods, module defs, and imported
    package defs.  The layer-4 rules walk this instead of the traced
    fixpoint — signal handlers and thread workers are HOST code."""
    seen: dict = {}
    stack = [start]
    while stack:
        path, fn, cls = stack.pop()
        qkey = (path, index.qualname(path, fn))
        if qkey in seen:
            continue
        seen[qkey] = (path, fn, cls)
        local = index._local_defs_env(path, fn)
        local.update(index._fn_import_env(path, fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                r = _resolve_callable(index, path, node.func, cls, local)
                if r is not None:
                    stack.append(r)
    return list(seen.values())


def _collect_jit_wrappers(index: PackageIndex) -> set[tuple[str, str]]:
    """(path, name) of every module-level ``X = ...jit(...)`` — calling
    one is a compiled-program dispatch."""
    wrappers: set[tuple[str, str]] = set()
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and (_dotted(node.value.func) or "").split(".")[-1]
                == "jit"
            ):
                wrappers.add((path, node.targets[0].id))
    return wrappers


# --------------------------------------------------------------------- #
# PUMI008: raw persistent writes outside the atomic-write modules
# --------------------------------------------------------------------- #
def _rule_raw_durable_write(index: PackageIndex, out: list[Finding]):
    """PUMI008 — durable state must ride the atomic writers.

    Rationale: the crash-safety layer (journal, two-phase checkpoints,
    AOT bank) is built on tmp+fsync+rename writes; a raw
    ``open(..., "w")`` / ``np.save`` / ``json.dump`` / ``Path.write_*``
    anywhere else can leave a TORN file under the real name on
    crash/ENOSPC — and a restart then reads garbage where the recovery
    path expected committed state.
    Example finding: ``json.dump(state, open(path, "w"))`` in a module
    outside utils/checkpoint.py, serving/journal.py, serving/bank.py,
    resilience/store.py, tuning/db.py.
    Fix pattern: route the write through
    ``utils.checkpoint.atomic_write_bytes`` / ``atomic_savez`` (or
    baseline a genuinely one-shot, re-creatable export with a
    justification).
    """
    def scan_scope(path, nodes, symbol_of):
        opened, buffers = _scope_file_bindings(nodes)
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            head = raw_write_head(node, opened, buffers)
            if head is None:
                continue
            out.append(
                Finding(
                    "PUMI008", path, node.lineno, symbol_of(node),
                    f"{head} outside the approved atomic-write modules "
                    "— a raw write can tear under crash/ENOSPC; route "
                    "durable state through utils/checkpoint.py's "
                    "atomic writers (tmp+fsync+rename), or baseline a "
                    "one-shot re-creatable export with a justification",
                )
            )

    for path, mod in index.modules.items():
        if path in APPROVED_DURABLE_MODULES:
            continue
        # Module-level statements, plus class-body statements (run at
        # import time); defs are scanned below through index.defs.
        scan_scope(
            path, list(_walk_shallow(mod.tree)),
            lambda node, path=path: index.enclosing_symbol(path, node),
        )
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                scan_scope(
                    path, list(_walk_shallow(cls)),
                    lambda node, path=path: index.enclosing_symbol(
                        path, node
                    ),
                )
    for (path, q), fn in index.defs.items():
        if path in APPROVED_DURABLE_MODULES:
            continue
        scan_scope(path, list(_walk_shallow(fn)), lambda node, q=q: q)


# --------------------------------------------------------------------- #
# PUMI009: signal-handler safety
# --------------------------------------------------------------------- #
def _handler_has_deferral_guard(handler_fn) -> bool:
    """The sanctioned mid-dispatch idiom: an ``if`` that parks the
    signum (``self._pending_signal = signum``) and returns, so the
    flush runs at a consistent quantum/move boundary instead of inside
    a half-completed dispatch."""
    params = [
        a.arg
        for a in list(handler_fn.args.posonlyargs)
        + list(handler_fn.args.args)
        if a.arg not in ("self", "cls")
    ]
    signum = params[0] if params else None
    if signum is None:
        return False
    for node in ast.walk(handler_fn):
        if not isinstance(node, ast.If):
            continue
        body_nodes = [n for s in node.body for n in ast.walk(s)]
        stores = any(
            isinstance(n, ast.Assign)
            and isinstance(n.value, ast.Name)
            and n.value.id == signum
            and any(
                isinstance(t, (ast.Attribute, ast.Name))
                for t in n.targets
            )
            for n in body_nodes
        )
        returns = any(isinstance(n, ast.Return) for n in body_nodes)
        if stores and returns:
            return True
    return False


def _rule_signal_handler_safety(index: PackageIndex, out: list[Finding]):
    """PUMI009 — preemption-signal handlers stay async-signal-safe.

    Rationale: a SIGTERM/SIGINT handler interrupts the main thread at
    an ARBITRARY bytecode boundary.  Flushing the journal from there
    without the deferral guard can interleave with a half-finished
    flush on the interrupted frame; taking a ``# guarded by:`` lock
    can deadlock against the thread it interrupted; dispatching a
    compiled program can wedge inside the runtime.  And an install
    without a matching uninstall leaves a STALE handler that a later
    signal routes into a dead supervisor (the PR 14 clobber bug class).
    Example finding: a handler reachable from
    ``install_preemption_handlers`` calling ``self._flush_journal()``
    with no ``if self._in_step: self._pending_signal = signum; return``
    guard.
    Fix pattern: add the deferral guard (park the signum, flush at the
    next quantum/move boundary); keep locks and jit dispatch out of
    handler-reachable code; pair every install with an uninstall on
    every exit path, uninstalling before chaining the previous handler.
    """
    jit_wrappers = _collect_jit_wrappers(index)
    locks_by_module = {
        path: {
            lock
            for lock in _guard_annotations(mod).values()
            if not _EVENT_SUFFIX_RE.search(lock)
        }
        for path, mod in index.modules.items()
    }

    def calls_uninstall(fn, cls) -> bool:
        """Direct uninstall, or one level through a self-method."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            last = d.split(".")[-1]
            if last == "uninstall_preemption_handlers":
                return True
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
            ):
                m = _class_method(cls, node.func.attr)
                if m is not None and any(
                    isinstance(n, ast.Call)
                    and (_dotted(n.func) or "").split(".")[-1]
                    == "uninstall_preemption_handlers"
                    for n in ast.walk(m)
                ):
                    return True
        return False

    for path, mod in index.modules.items():
        if path == f"{PACKAGE}/utils/signals.py":
            continue  # the plumbing itself, not a supervisor
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and (_dotted(node.func) or "").split(".")[-1]
                == "install_preemption_handlers"
            ):
                continue
            cls = _enclosing_class(index, path, node)
            install_symbol = index.enclosing_symbol(path, node)
            # Matching uninstall must exist in the installing scope.
            scope = cls if cls is not None else mod.tree
            if not any(
                isinstance(n, ast.Call)
                and (_dotted(n.func) or "").split(".")[-1]
                == "uninstall_preemption_handlers"
                for n in ast.walk(scope)
            ):
                out.append(
                    Finding(
                        "PUMI009", path, node.lineno, install_symbol,
                        "install_preemption_handlers without any "
                        "matching uninstall_preemption_handlers in "
                        f"{'class ' + cls.name if cls else 'the module'}"
                        " — the handler outlives its supervisor and a "
                        "later signal routes into dead state",
                    )
                )
            handler_expr = node.args[0] if node.args else None
            if handler_expr is None:
                continue
            resolved = _resolve_callable(
                index, path, handler_expr, cls
            )
            if resolved is None:
                continue
            handler_fn = resolved[1]
            guarded = _handler_has_deferral_guard(handler_fn)
            for p2, fn, cls2 in _reachable_callables(index, resolved):
                q2 = index.qualname(p2, fn)
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            try:
                                expr = ast.unparse(item.context_expr)
                            except Exception:
                                continue
                            if expr in locks_by_module.get(p2, ()):
                                out.append(
                                    Finding(
                                        "PUMI009", p2, sub.lineno, q2,
                                        f"signal-handler path takes "
                                        f"'{expr}' (a '# guarded by:' "
                                        "lock) — the interrupted "
                                        "thread may hold it: deadlock",
                                    )
                                )
                    if not isinstance(sub, ast.Call):
                        continue
                    d = _dotted(sub.func) or ""
                    last = d.split(".")[-1]
                    if (
                        last == "_flush_journal"
                        or d.endswith("journal.flush")
                    ) and not guarded:
                        out.append(
                            Finding(
                                "PUMI009", p2, sub.lineno, q2,
                                "signal-handler path flushes the "
                                "journal but the installed handler "
                                "has no mid-dispatch deferral guard "
                                "(park the signum and flush at the "
                                "next quantum/move boundary)",
                            )
                        )
                    local = index._local_defs_env(p2, fn)
                    local.update(index._fn_import_env(p2, fn))
                    key = index._resolve(p2, sub.func, local)
                    is_jit_call = (
                        key is not None
                        and key[0] == "def@"
                        and (key[1], key[2]) in index.traced
                    ) or (
                        isinstance(sub.func, ast.Name)
                        and (p2, sub.func.id) in jit_wrappers
                    )
                    if is_jit_call:
                        out.append(
                            Finding(
                                "PUMI009", p2, sub.lineno, q2,
                                f"signal-handler path calls '{d}' "
                                "which dispatches a compiled program "
                                "— a handler wedged inside the "
                                "runtime cannot be recovered",
                            )
                        )
                    if last == "resume_previous_handler" and (
                        not calls_uninstall(fn, cls2)
                    ):
                        out.append(
                            Finding(
                                "PUMI009", p2, sub.lineno, q2,
                                "resume_previous_handler without "
                                "uninstalling this supervisor's "
                                "handlers first — dying through the "
                                "chain leaves a stale handler "
                                "installed for the next signal",
                            )
                        )


# --------------------------------------------------------------------- #
# PUMI010: thread-shared state without a guard annotation
# --------------------------------------------------------------------- #
def _thread_entry_points(index: PackageIndex):
    """(path, target_def, class) for every statically-resolvable
    ``threading.Thread(target=...)`` and executor ``submit``/``map``
    worker."""
    entries = []
    for path, mod in index.modules.items():
        for (p2, q), fn in index.defs.items():
            if p2 != path:
                continue
            shallow = list(_walk_shallow(fn))
            executors = set()
            for node in shallow:
                if isinstance(node, ast.Assign):
                    if isinstance(node.value, ast.Call) and (
                        _dotted(node.value.func) or ""
                    ).split(".")[-1] == "ThreadPoolExecutor":
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                executors.add(t.id)
                elif isinstance(node, ast.With):
                    for item in node.items:
                        if (
                            isinstance(item.context_expr, ast.Call)
                            and (_dotted(item.context_expr.func) or "")
                            .split(".")[-1] == "ThreadPoolExecutor"
                            and isinstance(
                                item.optional_vars, ast.Name
                            )
                        ):
                            executors.add(item.optional_vars.id)
            cls = _enclosing_class(index, path, fn)
            local = index._local_defs_env(path, fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func) or ""
                last = d.split(".")[-1]
                target_expr = None
                if last == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target_expr = kw.value
                elif (
                    last in ("submit", "map")
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in executors
                    and node.args
                ):
                    target_expr = node.args[0]
                if target_expr is None:
                    continue
                resolved = _resolve_callable(
                    index, path, target_expr, cls, local
                )
                if resolved is not None:
                    entries.append(resolved)
    return entries


def _rule_thread_shared_state(index: PackageIndex, out: list[Finding]):
    """PUMI010 — thread-shared state must be annotated.

    Rationale: PUMI007 enforces the lock discipline of ANNOTATED
    state; state a worker thread writes WITHOUT an annotation is
    invisible to it — the inference gap a racing write slips through.
    Anything written from code reachable from a ``threading.Thread``
    target (or an executor worker) must either carry ``# guarded by:
    <lock>`` (PUMI007 then enforces the lock) or be provably
    thread-confined (local to the worker).
    Example finding: a watchdog worker writing ``self._last_beat``
    when no assignment of ``_last_beat`` is annotated.
    Fix pattern: annotate the attribute's assignment with
    ``# guarded by: <lock>`` and take that lock at every access — or
    restructure so the worker publishes through an Event-guarded
    handoff (PUMI007's ``(event)`` form).
    """
    for resolved in _thread_entry_points(index):
        tpath, tfn, _tcls = resolved
        # Worker closures: stores to enclosing-scope locals need the
        # event-guard annotation (or any guard comment on the line
        # that binds them in the enclosing function).
        parents = index.parents[tpath]
        encl = parents.get(tfn)
        while encl is not None and not isinstance(
            encl, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            encl = parents.get(encl)
        outer_names: dict[str, bool] = {}  # name -> annotated?
        if encl is not None:
            mod = index.modules[tpath]
            annotated_lines = _guard_annotations(mod)
            for node in _walk_shallow(encl):
                if isinstance(node, ast.Assign):
                    ann = node.lineno in annotated_lines
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            outer_names[t.id] = (
                                outer_names.get(t.id, False) or ann
                            )
        for p2, fn, cls2 in _reachable_callables(index, resolved):
            q2 = index.qualname(p2, fn)
            if q2.split(".")[-1] == "__init__":
                continue
            mod2 = index.modules[p2]
            guards = (
                _class_attr_guards(mod2, cls2)
                if cls2 is not None else {}
            )
            # A plain-name rebind in the worker creates a WORKER-LOCAL
            # unless the worker declares it nonlocal — only then (or on
            # subscript mutation, which reads the closure cell) is the
            # enclosing function's state actually shared.
            nonlocals = {
                name
                for sub in ast.walk(fn)
                if isinstance(sub, ast.Nonlocal)
                for name in sub.names
            }
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AugAssign):
                    targets = [node.target]
                for t in targets:
                    base = t
                    shares_cell = False
                    if isinstance(base, ast.Subscript):
                        base = base.value
                        shares_cell = True  # mutates the shared object
                    elif isinstance(base, ast.Name):
                        shares_cell = base.id in nonlocals
                    if (
                        isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                        and base.attr not in guards
                    ):
                        out.append(
                            Finding(
                                "PUMI010", p2, t.lineno, q2,
                                f"self.{base.attr} is written on a "
                                "thread-worker path but carries no "
                                "'# guarded by:' annotation — "
                                "annotate it (PUMI007 then enforces "
                                "the lock) or make it worker-local",
                            )
                        )
                    elif (
                        p2 == tpath
                        and fn is tfn
                        and encl is not None
                        and isinstance(base, ast.Name)
                        and shares_cell
                        and isinstance(
                            getattr(t, "ctx", ast.Store()), ast.Store
                        )
                        and outer_names.get(base.id) is False
                    ):
                        out.append(
                            Finding(
                                "PUMI010", p2, t.lineno, q2,
                                f"worker closure writes '{base.id}' "
                                "shared with the enclosing function "
                                "but no '# guarded by:' annotation "
                                "covers it — declare the handoff "
                                "(e.g. '# guarded by: <event> "
                                "(event)') so PUMI007 can check the "
                                "happens-before edge",
                            )
                        )


# --------------------------------------------------------------------- #
# PUMI011: swallowed retryable exceptions
# --------------------------------------------------------------------- #
_RETRYABLE_EXC_NAMES = frozenset(
    {
        "RETRYABLE",
        "InjectedTransientFault",
        "TransientIntegrityViolation",
        "DispatchTimeoutError",
        "JaxRuntimeError",
        "_JaxRuntimeError",
    }
)


def _rule_swallowed_retryable(index: PackageIndex, out: list[Finding]):
    """PUMI011 — retryable failures must stay visible.

    Rationale: the resilience layer's whole contract is that
    RETRYABLE / ``Transient*`` errors are CLASSIFIED and replayed (or
    counted) — an ``except`` that silently absorbs one erases the
    signal: no retry, no rollback, no metric, and the chaos campaigns
    can no longer prove the failure was handled.
    Example finding: ``except InjectedTransientFault: pass``.
    Fix pattern: re-raise after local cleanup, route the exception
    through ``ResilienceCoordinator.classify`` and act on the verdict,
    or count the deliberate swallow into a ``pumi_*`` metric
    (``counter.inc(...)``) inside a bounded retry loop.
    """
    for path, mod in index.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                continue
            names = {
                (sub.id if isinstance(sub, ast.Name) else sub.attr)
                for sub in ast.walk(node.type)
                if isinstance(sub, (ast.Name, ast.Attribute))
            }
            retryable = {
                n
                for n in names
                if n in _RETRYABLE_EXC_NAMES
                or n.startswith("Transient")
            }
            if not retryable:
                continue
            body_nodes = [
                n for s in node.body for n in ast.walk(s)
            ]
            reraises = any(
                isinstance(n, ast.Raise) for n in body_nodes
            )
            classifies = any(
                isinstance(n, ast.Call)
                and (_dotted(n.func) or "").split(".")[-1]
                == "classify"
                for n in body_nodes
            )
            counts = any(
                isinstance(n, ast.Call)
                and (_dotted(n.func) or "").split(".")[-1] == "inc"
                for n in body_nodes
            )
            if not (reraises or classifies or counts):
                out.append(
                    Finding(
                        "PUMI011", path, node.lineno,
                        index.enclosing_symbol(path, node),
                        f"except clause catches retryable "
                        f"{sorted(retryable)} and swallows it — "
                        "re-raise, route through "
                        "ResilienceCoordinator.classify, or count "
                        "the deliberate swallow into a pumi_* "
                        "metric inside a bounded loop",
                    )
                )


# --------------------------------------------------------------------- #
# Entry points
# --------------------------------------------------------------------- #
_RULES = (
    _rule_host_sync,
    _rule_transfers,
    _rule_use_after_donate,
    _rule_nondeterminism,
    _rule_f64,
    _rule_jit_hygiene,
    _rule_guarded_by,
    _rule_raw_durable_write,
    _rule_signal_handler_safety,
    _rule_thread_shared_state,
    _rule_swallowed_retryable,
)


def lint_index(index: PackageIndex) -> list[Finding]:
    """Run every rule over an already-built index (shared with the
    protocol layer by scripts/lint.py, so one full run parses and
    fixpoints the tree exactly once)."""
    out: list[Finding] = []
    for rule in _RULES:
        rule(index, out)

    def keep(f: Finding) -> bool:
        subset = rules_for_path(f.path)
        return subset is None or f.rule in subset

    out = [f for f in out if keep(f)]
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_sources(sources: dict[str, str]) -> list[Finding]:
    """Lint a {relpath: source} mapping (the test fixtures' entry).

    Paths outside the package tree (scripts, bench) participate fully
    in the index and the traced fixpoint, but only their subset's
    findings are reported (``rules_for_path``: the value-safety
    ``SCRIPT_RULES``, plus PUMI008/PUMI009 for the journal-owning
    ``JOURNAL_SCRIPTS``)."""
    modules = {p: _parse(p, s) for p, s in sources.items()}
    return lint_index(PackageIndex(modules))


def collect_sources(root) -> dict[str, str]:
    """{relpath: source} for the linted tree: the package, scripts/,
    and bench.py (shared with :mod:`analysis.protolint`, which builds
    its index over the same file set)."""
    root = Path(root)
    sources = {}
    for p in sorted((root / PACKAGE).rglob("*.py")):
        rel = p.relative_to(root).as_posix()
        sources[rel] = p.read_text()
    for p in sorted((root / "scripts").glob("*.py")):
        sources[p.relative_to(root).as_posix()] = p.read_text()
    bench = root / "bench.py"
    if bench.exists():
        sources["bench.py"] = bench.read_text()
    return sources


def lint_package(root) -> list[Finding]:
    """Lint every module of the package tree under ``root`` (the repo
    checkout: ``root/pumiumtally_tpu/**/*.py``) plus the launch surface
    — ``root/scripts/*.py`` and ``root/bench.py`` — under their
    ``rules_for_path`` subsets."""
    return lint_sources(collect_sources(root))


#: Rule id → rule function; ``explain`` renders the docstring
#: (rationale / example finding / fix pattern) for self-serve CI
#: failures via ``scripts/lint.py --explain <RULE>``.
RULES_BY_ID = {
    "PUMI001": _rule_host_sync,
    "PUMI002": _rule_transfers,
    "PUMI003": _rule_use_after_donate,
    "PUMI004": _rule_nondeterminism,
    "PUMI005": _rule_f64,
    "PUMI006": _rule_jit_hygiene,
    "PUMI007": _rule_guarded_by,
    "PUMI008": _rule_raw_durable_write,
    "PUMI009": _rule_signal_handler_safety,
    "PUMI010": _rule_thread_shared_state,
    "PUMI011": _rule_swallowed_retryable,
}

#: One-line summaries for rules whose functions predate the structured
#: docstrings — ``explain`` falls back to the module docstring's
#: catalogue entry for these.
_MODULE_DOC_RULES = re.compile(
    r"^  (?P<rule>PUMI\d{3}) .*?(?=^  PUMI|\Z)", re.M | re.S
)


def explain(rule: str) -> str | None:
    """Human-readable rationale + example + fix pattern for one rule
    id, pulled from the rule function's docstring (falling back to the
    module docstring's catalogue entry).  None for unknown rules."""
    rule = rule.strip().upper()
    fn = RULES_BY_ID.get(rule)
    if fn is None:
        return None
    import textwrap

    doc = fn.__doc__ or ""
    first, _, rest = doc.partition("\n")
    doc = (first.strip() + "\n" + textwrap.dedent(rest)).strip()
    if "Rationale" in doc:
        return f"{rule}\n{doc}"
    for m in _MODULE_DOC_RULES.finditer(__doc__ or ""):
        if m.group("rule") == rule:
            return textwrap.dedent(m.group(0)).strip()
    return f"{rule}\n{doc}"
