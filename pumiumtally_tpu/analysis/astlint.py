"""AST lint engine with codebase-specific rules (layer 1).

The rules encode invariants that runtime tests can only witness by
executing a failure; here they are properties of the source tree:

  PUMI001 host-sync-in-traced     ``float()`` / ``.item()`` /
      ``np.asarray`` / ``jax.device_get`` applied to traced values
      inside a traced body — a silent device sync (or a tracer error)
      on the hot path.
  PUMI002 transfer-outside-staging  ``jax.device_put`` /
      ``jax.device_get`` outside the approved staging modules: the
      1 H2D + 1 D2H move contract means transfers are a structural
      property of a handful of files, and a transfer anywhere else is a
      contract hole.
  PUMI003 use-after-donate        a buffer name is passed at a donated
      argnum/argname of a jitted program and then read again — XLA may
      already have scribbled over it.
  PUMI004 nondeterminism-in-traced  ``time.*`` / ``random.*`` /
      ``np.random.*`` / ``datetime.now`` inside a traced body: frozen at
      trace time into the compiled program, different per retrace —
      breaks bitwise replay (checkpoint resume, retry re-arm).
  PUMI005 f64-on-device-path      ``jnp.float64`` (or a "float64"
      dtype literal / ``np.float64`` in a traced body) outside
      ``integrity/audit.py`` — the f32 production configs must stay
      f64-free on device (the shadow audit is the one sanctioned f64
      surface).
  PUMI006 jit-static-hygiene      ``jax.jit(...)`` constructed inside a
      loop (a fresh wrapper and cache entry per iteration), or a
      jitted callable fed a loop induction variable at a STATIC
      argnum/argname (one recompile per iteration).
  PUMI007 guarded-by              attributes annotated
      ``# guarded by: <lock>`` must only be touched under ``with
      <lock>:`` outside ``__init__``; locals annotated
      ``# guarded by: <event> (event)`` must be written only by worker
      closures that ``<event>.set()`` and read only after
      ``<event>.wait(...)``.

The traced-body notion is a package-wide fixpoint: functions handed to
``jax.jit`` / ``lax.scan`` / ``while_loop`` / ``fori_loop`` / ``cond`` /
``switch`` / ``vmap`` / ``shard_map`` / ``pallas_call`` /
``checkify.checkify`` (as decorator or argument) are traced, every
function a traced function calls (resolved through module-level defs and
intra-package imports, including function-local imports) is traced, and
nested defs inherit the enclosing function's tracedness.

The fixpoint also covers ``scripts/*.py`` and ``bench.py`` (they jit
package functions and their own bodies, and their absolute
``pumiumtally_tpu.*`` imports resolve into the package index), but only
the value-safety rule subset applies there — PUMI001 host syncs,
PUMI003 use-after-donate (bench.py builds donating jits of its own),
PUMI004 nondeterminism, PUMI005 f64 — because scripts legitimately
stage their own device transfers (PUMI002's approved-module list is a
*package* contract) and throwaway per-config jits in microbenches are
the point of the file (PUMI006).

Findings are suppressed per (rule, path, symbol) through
``LINT_BASELINE.json`` (analysis.apply_baseline) — justification
required.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

from . import Finding

PACKAGE = "pumiumtally_tpu"

# Modules allowed to issue jax.device_put / jax.device_get: the staging
# layer itself, the facades that own the 1+1 move contract, the sharding
# / checkpoint plumbing, and device-table construction.  A transfer
# anywhere else is a new, unaccounted host<->device edge.
APPROVED_TRANSFER_MODULES = frozenset(
    {
        f"{PACKAGE}/ops/staging.py",
        f"{PACKAGE}/ops/source.py",
        f"{PACKAGE}/ops/walk_partitioned.py",
        f"{PACKAGE}/api.py",
        f"{PACKAGE}/parallel/partitioned_api.py",
        f"{PACKAGE}/parallel/particle_sharding.py",
        f"{PACKAGE}/utils/checkpoint.py",
        f"{PACKAGE}/models/pipeline.py",
        # The per-chip health probe stages a tiny round-trip array on
        # every device by design (resilience taxonomy: a dead chip
        # fails the put) — a deliberate, accounted transfer edge.
        f"{PACKAGE}/resilience/coordinator.py",
    }
)

# The one module allowed to hold float64 on purpose: the shadow-audit
# reference walker is DEFINED as an f64 NumPy oracle.
F64_EXEMPT_MODULES = frozenset({f"{PACKAGE}/integrity/audit.py"})

# Rule subset applied to sources OUTSIDE the package tree (scripts/,
# bench.py): the traced-body contracts travel with the jitted code
# wherever it is launched from, and use-after-donate corrupts data no
# matter who built the donating jit (bench.py does); the
# transfer-placement and jit-hygiene rules are package-structure
# contracts and stay package-scoped.
SCRIPT_RULES = frozenset({"PUMI001", "PUMI003", "PUMI004", "PUMI005"})

# Call heads whose function-valued arguments become traced.
_TRACING_HEADS_LAST = frozenset(
    {"jit", "pallas_call", "shard_map", "vmap", "pmap", "checkify"}
)
_TRACING_HEADS_LAX = frozenset(
    {
        "scan",
        "while_loop",
        "fori_loop",
        "cond",
        "switch",
        "map",
        "associative_scan",
        "custom_root",
    }
)

_HOST_SYNC_FUNCS = frozenset({"float", "int", "bool"})
_HOST_SYNC_ATTRS = frozenset({"item", "tolist", "to_py", "__array__"})
_HOST_SYNC_NP = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
)
_DEVICE_GET = frozenset({"jax.device_get", "device_get"})
_DEVICE_PUT = frozenset({"jax.device_put", "device_put"})

_NONDET_PREFIXES = (
    "time.",
    "random.",
    "np.random.",
    "numpy.random.",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.now",
    "os.urandom",
    "uuid.",
    "secrets.",
)

_GUARD_RE = re.compile(r"#\s*guarded by:\s*(?P<lock>[^#]+?)\s*$")
_EVENT_SUFFIX_RE = re.compile(r"\(event\)\s*$")


def _walk_shallow(fn):
    """Walk a function body WITHOUT descending into nested defs: each
    def is analyzed as its own scope (it has its own entry in
    ``PackageIndex.defs``), so a deep walk would double-report and
    cross-taint sibling scopes.  Lambdas stay in scope — they share the
    enclosing function's locals."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            stack.extend(ast.iter_child_nodes(node))


def _dotted(node) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class Module:
    path: str
    tree: ast.Module
    lines: list[str]
    comments: dict[int, str] = field(default_factory=dict)


def _parse(path: str, source: str) -> Module:
    tree = ast.parse(source, filename=path)
    comments: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
            io.StringIO(source).readline
        ):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return Module(path, tree, source.splitlines(), comments)


# --------------------------------------------------------------------- #
# Package index: defs, imports, traced-function fixpoint
# --------------------------------------------------------------------- #
def _module_of_import(cur_path: str, level: int, module: str | None,
                      known: set[str]) -> str | None:
    """Resolve a (possibly relative) import to a known package relpath
    (``a/b.py`` or ``a/b/__init__.py``), else None."""
    if level == 0:
        base = (module or "").split(".")
        if base and base[0] != PACKAGE.split("/")[0]:
            return None
        parts = base
    else:
        here = cur_path.split("/")[:-1]  # directory of current module
        up = level - 1
        if up:
            here = here[: len(here) - up] if up <= len(here) else []
        parts = here + ([p for p in (module or "").split(".") if p])
    cand = "/".join(parts) + ".py"
    if cand in known:
        return cand
    cand = "/".join(parts) + "/__init__.py"
    if cand in known:
        return cand
    return None


class PackageIndex:
    """Cross-module name resolution + the traced-function fixpoint."""

    def __init__(self, modules: dict[str, Module]):
        self.modules = modules
        known = set(modules)
        # (path, qualname) -> def node
        self.defs: dict[tuple[str, str], ast.AST] = {}
        # path -> {local name -> ("def", qualname) |
        #          ("name", path2, remote_name) | ("mod", path2)}
        self.scope: dict[str, dict] = {}
        self.parents: dict[str, dict[ast.AST, ast.AST]] = {}
        for path, mod in modules.items():
            env: dict = {}
            parent: dict[ast.AST, ast.AST] = {}
            for node in ast.walk(mod.tree):
                for child in ast.iter_child_nodes(node):
                    parent[child] = node
            self.parents[path] = parent
            for node in ast.walk(mod.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    q = self._qualname(path, node, parent)
                    self.defs[(path, q)] = node
                    if "." not in q:
                        env[node.name] = ("def", q)
                elif isinstance(node, ast.ImportFrom):
                    tgt = _module_of_import(
                        path, node.level, node.module, known
                    )
                    for alias in node.names:
                        name = alias.asname or alias.name
                        if tgt is None:
                            continue
                        # `from . import staging` resolves the NAME as a
                        # submodule when one exists.
                        sub = _module_of_import(
                            path, node.level,
                            f"{node.module}.{alias.name}"
                            if node.module else alias.name,
                            known,
                        )
                        if sub is not None:
                            env.setdefault(name, ("mod", sub))
                        else:
                            env.setdefault(
                                name, ("name", tgt, alias.name)
                            )
                elif isinstance(node, ast.Import):
                    pass  # absolute external imports — not package code
            self.scope[path] = env
        self.traced: set[tuple[str, str]] = set()
        self._seed_traced()
        self._propagate()

    # -- qualnames ---------------------------------------------------- #
    def _qualname(self, path, node, parent) -> str:
        parts = [node.name]
        cur = parent.get(node)
        while cur is not None:
            if isinstance(
                cur,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                parts.append(cur.name)
            cur = parent.get(cur)
        return ".".join(reversed(parts))

    def qualname(self, path, node) -> str:
        return self._qualname(path, node, self.parents[path])

    def enclosing_symbol(self, path, node) -> str:
        cur = node
        parent = self.parents[path]
        while cur is not None:
            if isinstance(
                cur,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                return self._qualname(path, cur, parent)
            cur = parent.get(cur)
        return "<module>"

    # -- traced fixpoint ---------------------------------------------- #
    def _is_tracing_head(self, func) -> bool:
        d = _dotted(func)
        if d is None:
            # jax.jit(...)(x) etc — head is itself a call; the inner
            # call was already seen by ast.walk.
            return False
        last = d.split(".")[-1]
        if last in _TRACING_HEADS_LAST:
            return True
        if last in _TRACING_HEADS_LAX:
            head = d.split(".")[0]
            return head in ("lax", "jax") or d.startswith("jax.lax.")
        return False

    def _callable_args(self, call: ast.Call):
        for a in list(call.args) + [k.value for k in call.keywords]:
            yield a
            # functools.partial(fn, ...) / partial(fn, ...)
            if isinstance(a, ast.Call):
                d = _dotted(a.func) or ""
                if d.split(".")[-1] == "partial" and a.args:
                    yield a.args[0]

    def _resolve(self, path: str, name_node,
                 local_env: dict | None = None):
        """Resolve a Name/Attribute to a (path, qualname) def key."""
        if isinstance(name_node, ast.Name):
            name = name_node.id
            for env in (local_env or {},):
                if name in env:
                    return env[name]
            entry = self.scope[path].get(name)
            if entry is None:
                return None
            if entry[0] == "def":
                return ("def@", path, entry[1])
            if entry[0] == "name":
                _, p2, remote = entry
                if (p2, remote) in self.defs:
                    return ("def@", p2, remote)
                return None
            return None
        if isinstance(name_node, ast.Attribute):
            base = name_node.value
            if isinstance(base, ast.Name):
                entry = self.scope[path].get(base.id)
                if entry and entry[0] == "mod":
                    p2 = entry[1]
                    if (p2, name_node.attr) in self.defs:
                        return ("def@", p2, name_node.attr)
        return None

    def _local_defs_env(self, path, fn) -> dict:
        env = {}
        for node in ast.walk(fn):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node is not fn:
                env[node.name] = (
                    "def@", path, self.qualname(path, node)
                )
        return env

    def _mark(self, key):
        if key and key[0] == "def@":
            self.traced.add((key[1], key[2]))

    def _seed_traced(self):
        for path, mod in self.modules.items():
            for node in ast.walk(mod.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for dec in node.decorator_list:
                        head = dec.func if isinstance(
                            dec, ast.Call
                        ) else dec
                        d = _dotted(head) or ""
                        if d.split(".")[-1] in _TRACING_HEADS_LAST:
                            self.traced.add(
                                (path, self.qualname(path, node))
                            )
                        if isinstance(dec, ast.Call) and d.split(
                            "."
                        )[-1] == "partial":
                            inner = dec.args[0] if dec.args else None
                            di = _dotted(inner) or ""
                            if di.split(".")[-1] in _TRACING_HEADS_LAST:
                                self.traced.add(
                                    (path, self.qualname(path, node))
                                )
                elif isinstance(node, ast.Call) and self._is_tracing_head(
                    node.func
                ):
                    enc = self._enclosing_fn(path, node)
                    local = (
                        self._local_defs_env(path, enc) if enc else {}
                    )
                    for a in self._callable_args(node):
                        self._mark(self._resolve(path, a, local))

    def _enclosing_fn(self, path, node):
        cur = node
        parent = self.parents[path]
        while cur is not None:
            cur = parent.get(cur)
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                return cur
        return None

    def _propagate(self):
        """Close traced-ness over lexical nesting and the call graph."""
        changed = True
        while changed:
            changed = False
            # Lexical: nested defs of traced functions are traced.
            for (path, q) in list(self.traced):
                prefix = q + "."
                for (p2, q2) in self.defs:
                    if p2 == path and q2.startswith(prefix):
                        if (p2, q2) not in self.traced:
                            self.traced.add((p2, q2))
                            changed = True
            # Call graph: callees of traced functions are traced.
            for (path, q) in list(self.traced):
                fn = self.defs.get((path, q))
                if fn is None:
                    continue
                local = self._local_defs_env(path, fn)
                local.update(self._fn_import_env(path, fn))
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        key = self._resolve(path, node.func, local)
                        if (
                            key
                            and key[0] == "def@"
                            and (key[1], key[2]) not in self.traced
                        ):
                            self.traced.add((key[1], key[2]))
                            changed = True

    def _fn_import_env(self, path, fn) -> dict:
        """Function-local `from .x import y` imports (idiomatic here for
        cycle avoidance) resolved like module-level ones."""
        env = {}
        known = set(self.modules)
        for node in ast.walk(fn):
            if isinstance(node, ast.ImportFrom):
                tgt = _module_of_import(
                    path, node.level, node.module, known
                )
                if tgt is None:
                    continue
                for alias in node.names:
                    name = alias.asname or alias.name
                    if (tgt, alias.name) in self.defs:
                        env[name] = ("def@", tgt, alias.name)
        return env

    def is_traced(self, path, fn_node) -> bool:
        return (path, self.qualname(path, fn_node)) in self.traced


# --------------------------------------------------------------------- #
# Per-function taint (positional params + derived locals)
# --------------------------------------------------------------------- #
def _taint_set(fn: ast.FunctionDef) -> set[str]:
    """Names in ``fn`` that (syntactically) carry traced array values:
    POSITIONAL parameters and anything assigned from an expression that
    mentions a tainted name or calls into jnp/lax/jax.  Keyword-only
    parameters are the codebase's static-knob convention (every jit
    static_argname is kw-only) and stay untainted."""
    tainted = {
        a.arg
        for a in list(fn.args.args) + list(fn.args.posonlyargs)
        if a.arg not in ("self", "cls")
    }
    if fn.args.vararg:
        tainted.add(fn.args.vararg.arg)

    # Static-at-trace-time metadata: reading .shape/.dtype/... of a
    # traced array (or len() of it) yields a Python value, not a traced
    # one — without this, ``n = origin.shape[0]`` would taint ``n`` and
    # every static size computed from it.
    _STATIC_ATTRS = {"shape", "ndim", "dtype", "itemsize", "weak_type"}
    _STATIC_CALLS = {"len", "jnp.finfo", "jnp.iinfo", "jnp.dtype",
                     "np.finfo", "np.iinfo", "np.dtype", "isinstance",
                     "getattr", "hasattr", "type"}

    def expr_tainted(e) -> bool:
        if isinstance(e, ast.Attribute) and e.attr in _STATIC_ATTRS:
            return False
        if isinstance(e, ast.Call):
            d = _dotted(e.func) or ""
            if d in _STATIC_CALLS:
                return False
            if d.split(".")[0] in ("jnp", "lax") or d.startswith(
                "jax."
            ):
                return True
        if isinstance(e, ast.Name):
            return e.id in tainted
        return any(
            expr_tainted(sub) for sub in ast.iter_child_nodes(e)
        )

    changed = True
    while changed:
        changed = False
        for node in _walk_shallow(fn):
            tgt_names: list[str] = []
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            tgt_names.append(sub.id)
            elif isinstance(node, ast.AugAssign) and expr_tainted(
                node.value
            ):
                if isinstance(node.target, ast.Name):
                    tgt_names.append(node.target.id)
            elif isinstance(node, ast.For) and expr_tainted(node.iter):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        tgt_names.append(sub.id)
            for n in tgt_names:
                if n not in tainted:
                    tainted.add(n)
                    changed = True
    return tainted


def _is_tainted_ref(node, tainted: set[str]) -> bool:
    """Direct reference to a tainted value: a tainted Name or an
    attribute chain rooted at one (``result.done``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in tainted


# --------------------------------------------------------------------- #
# Rules
# --------------------------------------------------------------------- #
def _rule_host_sync(index: PackageIndex, out: list[Finding]):
    for (path, q), fn in index.defs.items():
        if (path, q) in index.traced:
            tainted = _taint_set(fn)
            for node in _walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                msg = None
                if d in _DEVICE_GET:
                    msg = (
                        f"{d}() inside traced body — a host sync "
                        "compiled into the program (or a tracer leak)"
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _HOST_SYNC_FUNCS
                    and node.args
                    and _is_tainted_ref(node.args[0], tainted)
                ):
                    msg = (
                        f"{node.func.id}() on traced value "
                        f"'{ast.unparse(node.args[0])}' inside traced "
                        "body — blocks on device readback"
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_SYNC_ATTRS
                    and _is_tainted_ref(node.func.value, tainted)
                ):
                    msg = (
                        f".{node.func.attr}() on traced value "
                        f"'{ast.unparse(node.func.value)}' inside "
                        "traced body — blocks on device readback"
                    )
                elif (
                    d in _HOST_SYNC_NP
                    and node.args
                    and _is_tainted_ref(node.args[0], tainted)
                ):
                    msg = (
                        f"{d}() on traced value "
                        f"'{ast.unparse(node.args[0])}' inside traced "
                        "body — materializes the array on host"
                    )
                if msg:
                    out.append(
                        Finding(
                            "PUMI001", path, node.lineno, q, msg
                        )
                    )


def _rule_transfers(index: PackageIndex, out: list[Finding]):
    for path, mod in index.modules.items():
        if path in APPROVED_TRANSFER_MODULES:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in _DEVICE_PUT or d in _DEVICE_GET:
                    out.append(
                        Finding(
                            "PUMI002",
                            path,
                            node.lineno,
                            index.enclosing_symbol(path, node),
                            f"{d}() outside the approved staging "
                            "modules — every host<->device edge must "
                            "live in the staging/facade layer so the "
                            "1 H2D + 1 D2H move contract stays "
                            "structural",
                        )
                    )


@dataclass
class _DonationSpec:
    """Donated params of one jitted callable, by position and name."""

    argnums: tuple[int, ...] = ()
    argnames: tuple[str, ...] = ()


def _collect_donating(index: PackageIndex) -> dict[tuple[str, str], _DonationSpec]:
    """Module-level ``X = jax.jit(fn, donate_arg...)`` assignments, plus
    simple same-module wrappers ``def w(*a, **kw): return X(...)``."""
    donating: dict[tuple[str, str], _DonationSpec] = {}
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            call = node.value
            d = _dotted(call.func) or ""
            if d.split(".")[-1] != "jit":
                continue
            spec = _DonationSpec()
            wrapped = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    spec = _DonationSpec(
                        tuple(
                            e.value
                            for e in ast.walk(kw.value)
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int)
                        ),
                        spec.argnames,
                    )
                elif kw.arg == "donate_argnames":
                    spec = _DonationSpec(
                        spec.argnums,
                        tuple(
                            e.value
                            for e in ast.walk(kw.value)
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        ),
                    )
            if not (spec.argnums or spec.argnames):
                continue
            # donate_argnames -> positional indices through the wrapped
            # def's signature when resolvable in-package.
            wkey = index._resolve(path, wrapped) if wrapped else None
            if wkey and wkey[0] == "def@":
                wfn = index.defs[(wkey[1], wkey[2])]
                params = [
                    a.arg
                    for a in list(wfn.args.posonlyargs)
                    + list(wfn.args.args)
                ]
                nums = set(spec.argnums)
                for nm in spec.argnames:
                    if nm in params:
                        nums.add(params.index(nm))
                spec = _DonationSpec(
                    tuple(sorted(nums)), spec.argnames
                )
            donating[(path, node.targets[0].id)] = spec
    # Pass-through wrappers: `def trace(*args, **kwargs): return
    # _trace_jit(*args, ...)` inherits the jit's donation spec.
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            rets = [
                s
                for s in ast.walk(node)
                if isinstance(s, ast.Return) and s.value is not None
            ]
            for r in rets:
                if isinstance(r.value, ast.Call):
                    d = _dotted(r.value.func)
                    if d and (path, d) in donating:
                        donating.setdefault(
                            (path, node.name), donating[(path, d)]
                        )
    return donating


def _rule_use_after_donate(index: PackageIndex, out: list[Finding]):
    donating = _collect_donating(index)

    def site_spec(path, call, local_env) -> _DonationSpec | None:
        d = _dotted(call.func)
        if d is None:
            return None
        if (path, d) in donating:
            return donating[(path, d)]
        # imported name from another module
        entry = index.scope[path].get(d.split(".")[0])
        if entry and entry[0] == "name":
            _, p2, remote = entry
            if (p2, remote) in donating and "." not in d:
                return donating[(p2, remote)]
        return None

    for (path, q), fn in index.defs.items():
        events = []  # (lineno, kind, name)
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Call):
                spec = site_spec(path, node, None)
                if spec is None:
                    continue
                donated_exprs = []
                for i in spec.argnums:
                    if i < len(node.args):
                        nm = _dotted(node.args[i])
                        if nm:
                            donated_exprs.append(nm)
                for kw in node.keywords:
                    if kw.arg in spec.argnames:
                        nm = _dotted(kw.value)
                        if nm:
                            donated_exprs.append(nm)
                # The donation takes effect once the call completes:
                # anchor at the call's LAST line so the call's own
                # multi-line argument list never self-reports.
                for nm in donated_exprs:
                    events.append(
                        (node.end_lineno or node.lineno, "donate", nm)
                    )
        if not events:
            continue
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Name):
                nm = node.id
            elif isinstance(node, ast.Attribute):
                nm = _dotted(node)
                if nm is None:
                    continue
            else:
                continue
            if isinstance(node.ctx, ast.Store):
                events.append((node.lineno, "store", nm))
            elif isinstance(node.ctx, ast.Load):
                events.append((node.lineno, "load", nm))
        events.sort(key=lambda e: (e[0], {"donate": 1, "store": 2,
                                          "load": 0}[e[1]]))
        live_donated: dict[str, int] = {}
        reported = set()
        for lineno, kind, nm in events:
            if kind == "donate":
                live_donated[nm] = lineno
            elif kind == "store":
                live_donated.pop(nm, None)
            elif kind == "load" and nm in live_donated:
                if lineno > live_donated[nm] and nm not in reported:
                    reported.add(nm)
                    out.append(
                        Finding(
                            "PUMI003",
                            path,
                            lineno,
                            q,
                            f"'{nm}' read after being donated at line "
                            f"{live_donated[nm]} — the buffer may "
                            "already be aliased by the program's "
                            "output; re-bind it from the result",
                        )
                    )


def _rule_nondeterminism(index: PackageIndex, out: list[Finding]):
    for (path, q), fn in index.defs.items():
        if (path, q) not in index.traced:
            continue
        for node in _walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            if any(
                d.startswith(p) or d == p.rstrip(".")
                for p in _NONDET_PREFIXES
            ):
                out.append(
                    Finding(
                        "PUMI004",
                        path,
                        node.lineno,
                        q,
                        f"{d}() inside traced body — the value is "
                        "frozen at trace time and differs per retrace, "
                        "breaking bitwise replay (checkpoint resume, "
                        "retry re-arm); thread RNG keys / counters "
                        "through the program inputs instead",
                    )
                )


_DTYPE_CALL_HEADS = frozenset(
    {
        "array",
        "asarray",
        "zeros",
        "ones",
        "full",
        "empty",
        "arange",
        "astype",
        "dtype",
        "zeros_like",
        "ones_like",
        "full_like",
        "convert_element_type",
    }
)


_DTYPE_DISPATCH_RE = re.compile(r"float64|uint64|uint32|itemsize|x64")


def _in_dtype_dispatch(parents, node) -> bool:
    """True when the usage sits under an ``if``/ternary whose test is a
    dtype/carrier-width dispatch (``if dtype == jnp.float64:``,
    ``... if rec.dtype == jnp.uint32 else ...``) — the codebase's
    sanctioned pattern for dtype-polymorphic helpers, where the f64
    branch only executes for f64 configs."""
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.If, ast.IfExp)):
            try:
                if _DTYPE_DISPATCH_RE.search(ast.unparse(cur.test)):
                    return True
            except Exception:
                pass
        cur = parents.get(cur)
    return False


def _rule_f64(index: PackageIndex, out: list[Finding]):
    for path, mod in index.modules.items():
        if path in F64_EXEMPT_MODULES:
            continue
        # jnp.float64 anywhere in the package (device dtype by
        # construction); np.float64 / "float64" literals only inside
        # traced bodies (host-side f64 staging is legitimate).
        for node in ast.walk(mod.tree):
            d = _dotted(node) if isinstance(node, ast.Attribute) else None
            if d in ("jnp.float64", "jax.numpy.float64"):
                if _in_dtype_dispatch(index.parents[path], node):
                    continue
                out.append(
                    Finding(
                        "PUMI005",
                        path,
                        node.lineno,
                        index.enclosing_symbol(path, node),
                        f"{d} creates a float64 device array — the "
                        "f32 production config must stay f64-free on "
                        "device (integrity/audit.py is the sanctioned "
                        "f64 surface)",
                    )
                )
    # np.float64 / "float64" literals: traced bodies only (host-side
    # f64 staging is legitimate).
    for (path, q), fn in index.defs.items():
        if path in F64_EXEMPT_MODULES or (path, q) not in index.traced:
            continue
        for node in _walk_shallow(fn):
            if _in_dtype_dispatch(index.parents[path], node):
                continue
            if isinstance(node, ast.Attribute):
                if _dotted(node) in ("np.float64", "numpy.float64"):
                    out.append(
                        Finding(
                            "PUMI005", path, node.lineno, q,
                            "np.float64 inside traced body — "
                            "promotes the device path to f64",
                        )
                    )
            elif isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d.split(".")[-1] not in _DTYPE_CALL_HEADS:
                    continue
                for a in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if _const_str(a) == "float64":
                        out.append(
                            Finding(
                                "PUMI005", path, node.lineno, q,
                                f'"float64" dtype literal in '
                                f"{d}() inside traced body",
                            )
                        )


def _rule_jit_hygiene(index: PackageIndex, out: list[Finding]):
    # Static-argnum specs of module-level jits (donating or not).
    statics: dict[tuple[str, str], tuple[int, ...]] = {}
    for path, mod in index.modules.items():
        for node in mod.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            d = _dotted(node.value.func) or ""
            if d.split(".")[-1] != "jit":
                continue
            nums: set[int] = set()
            for kw in node.value.keywords:
                if kw.arg == "static_argnums":
                    nums |= {
                        e.value
                        for e in ast.walk(kw.value)
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    }
            if nums:
                statics[(path, node.targets[0].id)] = tuple(
                    sorted(nums)
                )

    for path, mod in index.modules.items():
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            loop_vars = set()
            if isinstance(loop, ast.For):
                for sub in ast.walk(loop.target):
                    if isinstance(sub, ast.Name):
                        loop_vars.add(sub.id)
            for node in ast.walk(loop):
                if node is loop or not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func) or ""
                if d.split(".")[-1] == "jit" and d in (
                    "jit",
                    "jax.jit",
                ):
                    out.append(
                        Finding(
                            "PUMI006",
                            path,
                            node.lineno,
                            index.enclosing_symbol(path, node),
                            "jax.jit(...) constructed inside a loop — "
                            "a fresh wrapper (and for local callables "
                            "a fresh cache entry, i.e. a recompile) "
                            "per iteration; hoist the jit out of the "
                            "loop",
                        )
                    )
                    continue
                key = (path, d)
                if key in statics and loop_vars:
                    for i in statics[key]:
                        if i < len(node.args) and isinstance(
                            node.args[i], ast.Name
                        ) and node.args[i].id in loop_vars:
                            out.append(
                                Finding(
                                    "PUMI006",
                                    path,
                                    node.lineno,
                                    index.enclosing_symbol(
                                        path, node
                                    ),
                                    f"loop variable "
                                    f"'{node.args[i].id}' passed at "
                                    f"STATIC argnum {i} of jitted "
                                    f"'{d}' — one recompile per "
                                    "iteration",
                                )
                            )


# --------------------------------------------------------------------- #
# PUMI007: # guarded by: <lock> concurrency lint
# --------------------------------------------------------------------- #
def _guard_annotations(mod: Module):
    """Map line number → lock expression for every ``# guarded by:``
    comment in the module; the callers associate each with the
    assignment statement on that line (a ``self.X = ...`` attribute or,
    with the ``(event)`` suffix, a guarded local)."""
    annotated_lines: dict[int, str] = {}
    for lineno, comment in mod.comments.items():
        m = _GUARD_RE.search(comment)
        if m:
            annotated_lines[lineno] = m.group("lock").strip()
    return annotated_lines


def _with_lock_stack(parents, node) -> list[str]:
    """Lock expressions of every enclosing ``with`` block."""
    locks = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                try:
                    locks.append(ast.unparse(item.context_expr))
                except Exception:
                    pass
        cur = parents.get(cur)
    return locks


def _rule_guarded_by(index: PackageIndex, out: list[Finding]):
    for path, mod in index.modules.items():
        annotated = _guard_annotations(mod)
        if not annotated:
            continue
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attr_guards: dict[str, str] = {}
            event_guards: dict[str, str] = {}
            for node in ast.walk(cls):
                if not isinstance(
                    node, (ast.Assign, ast.AnnAssign)
                ):
                    continue
                lock = annotated.get(node.lineno)
                if lock is None:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        attr_guards[t.attr] = lock
            if attr_guards:
                _check_attr_guards(
                    index, path, cls, attr_guards, out
                )
        # Event-guarded locals: annotations on plain local assignments
        # inside any function ("<name> (event)").
        for fn_key, fn in index.defs.items():
            if fn_key[0] != path:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                lock = annotated.get(node.lineno)
                if lock is None or not _EVENT_SUFFIX_RE.search(lock):
                    continue
                event = _EVENT_SUFFIX_RE.sub("", lock).strip()
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        _check_event_guard(
                            index, path, fn_key[1], fn, t.id,
                            event, node.lineno, out,
                        )


def _check_attr_guards(index, path, cls, attr_guards, out):
    parents = index.parents[path]
    for method in cls.body:
        if not isinstance(
            method, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        if method.name in ("__init__", "__del__"):
            # Construction precedes thread visibility; finalizers run
            # after every worker is joined.
            continue
        q = index.qualname(path, method)
        for node in ast.walk(method):
            if not (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in attr_guards
            ):
                continue
            lock = attr_guards[node.attr]
            held = _with_lock_stack(parents, node)
            if lock not in held:
                out.append(
                    Finding(
                        "PUMI007",
                        path,
                        node.lineno,
                        q,
                        f"self.{node.attr} is annotated "
                        f"'# guarded by: {lock}' but is accessed "
                        f"outside 'with {lock}:'",
                    )
                )


def _check_event_guard(index, path, q, fn, local, event, ann_line, out):
    """Writes to ``local`` inside nested defs must also call
    ``<event>.set()`` there; reads of ``local`` in the outer body must
    come after an ``<event>.wait(...)`` call."""
    nested = [
        n
        for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not fn
    ]
    in_nested = set()
    for nf in nested:
        for sub in ast.walk(nf):
            in_nested.add(id(sub))

    def writes_local(node):
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ):
            return (
                node.value.id == local
                and isinstance(node.ctx, ast.Store)
            )
        return (
            isinstance(node, ast.Name)
            and node.id == local
            and isinstance(node.ctx, ast.Store)
        )

    def calls(tree, dotted_suffix):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d == dotted_suffix:
                    yield node

    for nf in nested:
        if any(writes_local(n) for n in ast.walk(nf)):
            if not any(calls(nf, f"{event}.set")):
                out.append(
                    Finding(
                        "PUMI007",
                        path,
                        nf.lineno,
                        q,
                        f"worker '{nf.name}' writes "
                        f"'{local}' (guarded by {event}) without "
                        f"calling {event}.set() — the reader's "
                        "happens-before edge is missing",
                    )
                )
    wait_lines = [
        c.lineno
        for c in calls(fn, f"{event}.wait")
        if id(c) not in in_nested
    ]
    first_wait = min(wait_lines) if wait_lines else None
    for node in ast.walk(fn):
        if id(node) in in_nested or not isinstance(node, ast.Name):
            continue
        if (
            node.id == local
            and isinstance(node.ctx, ast.Load)
            and node.lineno > ann_line
            and (first_wait is None or node.lineno <= first_wait)
        ):
            out.append(
                Finding(
                    "PUMI007",
                    path,
                    node.lineno,
                    q,
                    f"'{local}' (guarded by {event}) read before "
                    f"{event}.wait(...) — the worker may still be "
                    "writing it",
                )
            )


# --------------------------------------------------------------------- #
# Entry points
# --------------------------------------------------------------------- #
_RULES = (
    _rule_host_sync,
    _rule_transfers,
    _rule_use_after_donate,
    _rule_nondeterminism,
    _rule_f64,
    _rule_jit_hygiene,
    _rule_guarded_by,
)


def lint_sources(sources: dict[str, str]) -> list[Finding]:
    """Lint a {relpath: source} mapping (the test fixtures' entry).

    Paths outside the package tree (scripts, bench) participate fully
    in the index and the traced fixpoint, but only their
    ``SCRIPT_RULES`` findings are reported."""
    modules = {p: _parse(p, s) for p, s in sources.items()}
    index = PackageIndex(modules)
    out: list[Finding] = []
    for rule in _RULES:
        rule(index, out)
    out = [
        f
        for f in out
        if f.path.startswith(f"{PACKAGE}/") or f.rule in SCRIPT_RULES
    ]
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_package(root) -> list[Finding]:
    """Lint every module of the package tree under ``root`` (the repo
    checkout: ``root/pumiumtally_tpu/**/*.py``) plus the launch surface
    — ``root/scripts/*.py`` and ``root/bench.py`` — under the
    ``SCRIPT_RULES`` subset."""
    root = Path(root)
    sources = {}
    for p in sorted((root / PACKAGE).rglob("*.py")):
        rel = p.relative_to(root).as_posix()
        sources[rel] = p.read_text()
    for p in sorted((root / "scripts").glob("*.py")):
        sources[p.relative_to(root).as_posix()] = p.read_text()
    bench = root / "bench.py"
    if bench.exists():
        sources["bench.py"] = bench.read_text()
    return lint_sources(sources)
