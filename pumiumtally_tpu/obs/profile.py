"""Per-quantum device profiling for the serving fleet.

The third observability layer (obs/aggregate.py merges, obs/slo.py
judges, this module explains): turns the counters the scheduler
already maintains into per-member utilization readings, and — when a
burn-rate alert fires — captures a bounded ``jax.profiler`` trace so
the anomaly window is explainable after the fact.

Gauges (on the ROUTER registry, sampled at quantum cadence from each
member's own registry):

  * ``pumi_member_device_utilization{member=}`` — fraction of wall
    time the member spent inside blocked device dispatches since the
    last sample (``pumi_job_device_seconds`` delta / wall delta);
  * ``pumi_member_time_seconds{member=,phase=}`` — cumulative wall
    attribution: ``device`` (inside dispatches), ``dispatch_wait``
    (quantum wall minus device — host-side overhead, retries,
    injected brownout latency), ``queue_wait`` (submit-to-first-
    dispatch, the ``pumi_job_queue_seconds`` histogram's sum);
  * ``pumi_fleet_hbm_high_water_bytes`` — the bank's
    ``memory_analysis`` high-water mark over every program resolved
    for dispatch so far (0 when no resolved executable exposes an
    analysis — deserialized entries do not, the PR 9 finding).

Capture-on-anomaly (off by default): ``PUMI_TPU_PROFILE=anomaly``
arms the hook — the first burn-rate alert opens
``jax.profiler.start_trace(<journal_dir>/profiles/<tag>)`` and the
window closes after ``capture_s`` wall seconds at the next sample
(bounded: one window at a time, never re-armed while active, any
profiler failure is swallowed — observability must never take the
fleet down).  ``PUMI_TPU_PROFILE=off`` (or unset) keeps the hook
cold with zero overhead.
"""
from __future__ import annotations

import os
import time

ENV_PROFILE = "PUMI_TPU_PROFILE"
PROFILE_MODES = ("off", "anomaly")


def profile_mode(mode: str | None = None) -> str:
    """Resolve the capture mode: explicit argument wins, then the
    ``PUMI_TPU_PROFILE`` env var, then ``off``.  Unknown values are
    rejected loudly — a typo must not silently disable capture."""
    if mode is None:
        mode = os.environ.get(ENV_PROFILE, "").strip() or "off"
    mode = str(mode).lower()
    if mode not in PROFILE_MODES:
        raise ValueError(
            f"{ENV_PROFILE}={mode!r}: expected one of {PROFILE_MODES}"
        )
    return mode


class FleetProfiler:
    """Quantum-cadence utilization sampling + anomaly capture."""

    def __init__(self, registry, *, journal_dir: str, bank=None,
                 mode: str | None = None, capture_s: float = 5.0,
                 clock=time.monotonic):
        self.mode = profile_mode(mode)
        self.bank = bank
        self.capture_s = float(capture_s)
        self.profile_dir = os.path.join(str(journal_dir), "profiles")
        self._clock = clock
        self._util_gauge = registry.gauge(
            "pumi_member_device_utilization",
            "fraction of wall time spent inside blocked device "
            "dispatches since the previous profiler sample "
            "(device_seconds delta / wall delta, per member)",
        )
        self._time_gauge = registry.gauge(
            "pumi_member_time_seconds",
            "cumulative wall attribution per member: phase=device "
            "(inside dispatches), phase=dispatch_wait (quantum wall "
            "minus device — host overhead), phase=queue_wait "
            "(submit-to-first-dispatch)",
        )
        self._hbm_gauge = registry.gauge(
            "pumi_fleet_hbm_high_water_bytes",
            "high-water HBM footprint over every bank program "
            "resolved for dispatch (argument+output+temp bytes from "
            "memory_analysis; 0 when no resolved executable exposes "
            "one — deserialized entries do not)",
        )
        self._captures_total = registry.counter(
            "pumi_profile_captures_total",
            "anomaly-triggered jax.profiler capture windows opened",
        )
        # {member index: (t, device_s, quantum_wall_s)} — the deltas
        # behind the utilization gauge.
        self._last: dict[int, tuple] = {}
        self._capture_until: float | None = None
        self._captures: list[dict] = []

    # ------------------------------------------------------------------ #
    # Sampling
    # ------------------------------------------------------------------ #
    @staticmethod
    def _member_counts(label: str, registry) -> tuple:
        """(device_s, quantum_wall_s, queue_wait_s) cumulative from
        one member registry."""
        device = registry.counter("pumi_job_device_seconds").value(
            member=label
        )
        qwall = registry.counter(
            "pumi_quantum_wall_seconds_total"
        ).value(member=label)
        queue = 0.0
        snap = registry.snapshot().get("pumi_job_queue_seconds")
        if snap is not None:
            queue = sum(s["value"]["sum"] for s in snap["series"])
        return float(device), float(qwall), float(queue)

    def sample(self, members) -> None:
        """One quantum-cadence sample over ``[(index, label,
        registry, alive), ...]``."""
        now = self._clock()
        for index, label, registry, alive in members:
            if not alive or registry is None:
                self._util_gauge.set(0.0, member=str(label))
                self._last.pop(index, None)
                continue
            device, qwall, queue = self._member_counts(label, registry)
            prev = self._last.get(index)
            if prev is not None:
                dt = now - prev[0]
                dd = device - prev[1]
                if dt > 0:
                    self._util_gauge.set(
                        max(0.0, dd / dt), member=str(label)
                    )
            self._last[index] = (now, device, qwall)
            self._time_gauge.set(
                device, member=str(label), phase="device"
            )
            self._time_gauge.set(
                max(0.0, qwall - device),
                member=str(label), phase="dispatch_wait",
            )
            self._time_gauge.set(
                queue, member=str(label), phase="queue_wait"
            )
        if self.bank is not None:
            try:
                self._hbm_gauge.set(
                    float(
                        self.bank.memory_analysis()["high_water_bytes"]
                    )
                )
            except Exception:  # pragma: no cover - backend-specific
                pass
        self._maybe_stop_capture(now)

    # ------------------------------------------------------------------ #
    # Capture-on-anomaly
    # ------------------------------------------------------------------ #
    @property
    def capturing(self) -> bool:
        return self._capture_until is not None

    def on_alert(self, alert: dict) -> bool:
        """A burn-rate alert fired: open one bounded profiler window
        (no-op unless ``mode="anomaly"``, and never while a window is
        already open).  Returns True when a capture started."""
        if self.mode != "anomaly" or self.capturing:
            return False
        tag = (
            f"{alert.get('slo', 'alert')}-m{alert.get('member', 'x')}-"
            f"{len(self._captures):03d}"
        )
        target = os.path.join(self.profile_dir, tag)
        try:
            import jax

            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
        except Exception:  # pragma: no cover - profiler availability
            return False
        self._capture_until = self._clock() + self.capture_s
        self._captures.append({
            "tag": tag, "dir": target, "slo": alert.get("slo"),
            "member": alert.get("member"),
        })
        self._captures_total.inc()
        return True

    def _maybe_stop_capture(self, now: float) -> None:
        if self._capture_until is not None and now >= self._capture_until:
            self.stop_capture()

    def stop_capture(self) -> None:
        """Close an open profiler window (idempotent; teardown-safe)."""
        if self._capture_until is None:
            return
        self._capture_until = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # pragma: no cover - profiler availability
            pass

    def status(self) -> dict:
        """The FLEETSTATS.json ``profile`` section."""
        return {
            "mode": self.mode,
            "capturing": self.capturing,
            "captures": list(self._captures),
            "profile_dir": self.profile_dir,
        }
