"""Per-move flight recorder: a bounded in-memory trail of structured
records plus optional JSONL emission.

Every facade move appends one record (walk stats, phase seconds,
migration counts); the recorder keeps the last ``capacity`` in a ring
buffer for ``telemetry()`` and, when ``PUMI_TPU_METRICS=jsonl:/path`` is
set, streams each record to that file through the same JSON machinery as
``PUMI_TPU_LOG_JSON`` (utils/log.emit_metric) — so a crashed run leaves
its whole per-move history on disk, not just whatever the ring held.
"""
from __future__ import annotations

import collections
import threading

from ..utils.log import emit_metric

#: Version stamp for SERVING-PATH flight records (scheduler / journal /
#: bank): recorders constructed with ``schema=FLIGHT_SCHEMA`` stamp
#: every record, so JSONL streams written by mixed-version processes
#: (a killed server and its restarted successor) stay distinguishable.
#: Readers (scripts/teleview.py) tolerate unknown fields.
FLIGHT_SCHEMA = 1


class FlightRecorder:
    def __init__(self, capacity: int = 512, sink: str | None = None,
                 schema: int | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._schema = schema
        # Writers are not single-threaded: the integrity watchdog
        # dispatches from a worker thread and the Prometheus exporter
        # reads concurrently, so sequencing + the ring append happen
        # under a lock (an unlocked _seq increment can duplicate or
        # skip sequence numbers under interleaving).  The annotations
        # are machine-checked by analysis/astlint.py PUMI007.
        self._lock = threading.Lock()
        self._records = collections.deque(maxlen=capacity)  # guarded by: self._lock
        self._seq = 0  # guarded by: self._lock
        # None defers to PUMI_TPU_METRICS at record time (env can change
        # between moves, e.g. under pytest monkeypatch).
        self._sink = sink

    def record(self, kind: str, **fields) -> dict:
        """Append one record; ``kind`` names the event ("move",
        "initial_search", "memory", ...). Returns the stored record.
        Thread-safe: concurrent recorders get unique, gap-free
        sequence numbers."""
        with self._lock:
            rec = {"seq": self._seq, "kind": str(kind), **fields}
            if self._schema is not None:
                rec.setdefault("schema", self._schema)
            self._seq += 1
            self._records.append(rec)
        emit_metric(rec, path=self._sink)
        return rec

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def tail(self, n: int) -> list[dict]:
        if n <= 0:
            return []
        with self._lock:
            return list(self._records)[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    @property
    def total_recorded(self) -> int:
        """Records ever appended (>= len() once the ring wraps)."""
        with self._lock:
            return self._seq
