"""Per-job distributed tracing: spans, events, and the crash black box.

PRs 13-15 built a crash-safe multi-tenant serving layer whose
observability was still per-component: scheduler, journal, AOT bank and
resilience coordinator each emitted their own flight records with no
causal thread tying one job's life together.  This module is that
thread — a lightweight span/event tracer the whole serving path shares:

  * every job gets a ``trace_id`` at submission (persisted in the
    JOBS.json journal, so a job recovered after a server crash
    CONTINUES its trace — the two process lifetimes are linked by the
    id and an explicit ``recovered`` span);
  * every phase of the job's life is one span (``submit`` → ``queued``
    → ``admit`` → ``quantum``/``dispatch`` per scheduling quantum →
    ``retry``/``rollback``/``preempt``/``recovered`` → terminal
    ``job``) with a ``span_id``, a ``parent_id``, wall-clock end
    timestamps and monotonic-clock durations;
  * the AOT bank (resolve/deserialize/compile) and the resilience
    coordinator (classify/probe) emit spans into the SAME trace via the
    ambient binding the scheduler sets around each dispatch, so "where
    did job X's 40 seconds go" is answerable from one stream.

Span records are flat JSON dicts (``schema``/``kind``/``name``/
``trace_id``/``span_id``/``parent_id``/``job_id``/``pid``/``ts``/
``seconds`` + attributes) appended to a bounded ring buffer and —
when a ``sink`` is configured (the scheduler points it at
``<journal_dir>/TRACE.jsonl``) — streamed one JSON line per record
through the same best-effort channel as the flight recorder, so a
crashed process leaves its span history on disk beside the journal
recovery reads.

The crash black box
-------------------
``dump()`` writes the ring's last-N records as one self-contained
postmortem document through the approved atomic-write path
(``utils/checkpoint.atomic_write_json`` — PUMI008).  The scheduler
dumps it on job poisoning, on fatal classification, and from the
SIGTERM/SIGINT boundary flush.  Because that last caller is
signal-handler-reachable (PUMI009), the dump path NEVER takes the
tracer's lock: it snapshots the ring with a plain ``list(deque)``
(atomic under the GIL) so an interrupted appender cannot deadlock it.

Zero cost to physics: the tracer only wraps HOST-side control flow —
it never touches device state, RNG keys, or dispatch arguments — so
served fluxes are bitwise identical with tracing on or off (pinned by
tests/test_obs_trace.py).  ``PUMI_TPU_TRACE=off`` disables emission
entirely for overhead-sensitive runs; the per-span cost is priced in
bench.py's ``BENCH_TRACE_SPANS`` probe.
"""
from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
import uuid

from ..utils.log import emit_metric

#: Version stamp carried by every span/event record and every black-box
#: document, so JSONL streams from mixed-version processes stay
#: distinguishable (readers tolerate unknown fields; see teleview.py).
TRACE_SCHEMA = 1

#: Env knob: "off"/"0" disables span emission (records() stays empty,
#: span()/event() become near-zero-cost no-ops).
ENV_TRACE = "PUMI_TPU_TRACE"

#: Explicit "this span has no parent" marker: pass as ``parent=`` when
#: an emit must NOT inherit the ambient binding's parent (the terminal
#: root span of a trace, emitted while the trace is still bound).
NO_PARENT = "__no_parent__"


def trace_enabled() -> bool:
    return os.environ.get(ENV_TRACE, "").strip().lower() not in (
        "off", "0", "false",
    )


class SpanTracer:
    """Bounded-ring span/event tracer with ambient job binding.

    Single logical writer (the scheduler's serving loop; a watchdog
    worker thread dispatching on its behalf is serialized by the
    blocked caller), concurrent readers (the exporter's ``/trace``
    scrape threads, the signal-path black-box dump).  Appends take
    ``_lock``; the dump path deliberately does not (module docstring).
    """

    def __init__(self, capacity: int = 1024, sink: str | None = None,
                 enabled: bool | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = trace_enabled() if enabled is None else bool(enabled)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=capacity)  # guarded by: self._lock
        self._seq = 0  # guarded by: self._lock
        # None defers to PUMI_TPU_METRICS at emission time (same
        # convention as the flight recorder's sink).
        self._sink = sink
        # Ambient (trace_id, job_id, parent_id) the serving loop binds
        # around each phase so bank/coordinator spans land in the
        # right trace without threading ids through every call.
        self._ctx: tuple | None = None

    # -- identity ------------------------------------------------------- #
    @staticmethod
    def new_trace() -> str:
        """A fresh 16-hex trace id (one per job, for its lifetime
        across every process that serves it)."""
        return uuid.uuid4().hex[:16]

    @staticmethod
    def root_id(trace_id: str) -> str:
        """The DETERMINISTIC id of a trace's root ``job`` span: phases
        emitted by different process lifetimes parent onto the same
        root without coordination."""
        return f"{trace_id}/root"

    def next_id(self) -> str:
        """Allocate one span id — unique across process lifetimes (the
        pid disambiguates two processes appending to one TRACE.jsonl)."""
        with self._lock:
            n = self._seq
            self._seq += 1
        return f"{os.getpid():x}-{n}"

    # -- ambient binding ------------------------------------------------ #
    @contextlib.contextmanager
    def bind(self, trace_id: str, job_id: str | None = None,
             parent_id: str | None = None):
        """Set the ambient trace context for the duration of one
        serving phase; spans emitted without explicit ids (the bank,
        the coordinator) inherit it."""
        prev, self._ctx = self._ctx, (trace_id, job_id, parent_id)
        try:
            yield
        finally:
            self._ctx = prev

    @property
    def current(self) -> tuple:
        """(trace_id, job_id, parent_id) of the ambient binding, or
        (None, None, None)."""
        return self._ctx if self._ctx is not None else (None, None, None)

    # -- emission ------------------------------------------------------- #
    def _emit(self, kind: str, name: str, seconds: float, *,
              trace_id=None, parent=None, job_id=None, span_id=None,
              end_ts=None, attrs=None) -> dict | None:
        if not self.enabled:
            return None
        ctx_trace, ctx_job, ctx_parent = self.current
        parent_id = parent if parent is not None else ctx_parent
        if parent_id == NO_PARENT:
            parent_id = None
        rec = {
            "schema": TRACE_SCHEMA,
            "kind": kind,
            "name": str(name),
            "trace_id": trace_id if trace_id is not None else ctx_trace,
            "span_id": span_id if span_id is not None else self.next_id(),
            "parent_id": parent_id,
            "job_id": job_id if job_id is not None else ctx_job,
            "pid": os.getpid(),
            "ts": round(end_ts if end_ts is not None else time.time(), 6),
            "seconds": round(float(seconds), 6),
        }
        if attrs:
            for k, v in attrs.items():
                rec.setdefault(k, v)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._ring.append(rec)
        emit_metric(rec, path=self._sink)
        return rec

    def event(self, name: str, *, trace_id=None, parent=None,
              job_id=None, **attrs) -> dict | None:
        """One zero-duration point event in a trace."""
        return self._emit(
            "event", name, 0.0, trace_id=trace_id, parent=parent,
            job_id=job_id, attrs=attrs,
        )

    def span_record(self, name: str, seconds: float, *, trace_id=None,
                    parent=None, job_id=None, span_id=None,
                    **attrs) -> dict | None:
        """One completed span of known duration ending now.  Use
        ``span_id=`` to emit onto a pre-allocated id (a parent whose
        children were emitted while it was open) or a deterministic one
        (``root_id``)."""
        return self._emit(
            "span", name, seconds, trace_id=trace_id, parent=parent,
            job_id=job_id, span_id=span_id, attrs=attrs,
        )

    @contextlib.contextmanager
    def span(self, name: str, *, trace_id=None, parent=None,
             job_id=None, **attrs):
        """Context-managed span around a code block.  Yields the
        mutable attribute dict — set result attributes before exit
        (``sp["verdict"] = ...``).  The span is emitted on BOTH normal
        and exception exit (the error is named), so a failing phase
        still appears in the postmortem."""
        if not self.enabled:
            yield attrs
            return
        t0 = time.perf_counter()
        sid = self.next_id()
        try:
            yield attrs
        except BaseException as e:
            attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
            raise
        finally:
            self._emit(
                "span", name, time.perf_counter() - t0,
                trace_id=trace_id, parent=parent, job_id=job_id,
                span_id=sid, attrs=attrs,
            )

    # -- read surfaces -------------------------------------------------- #
    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int) -> list[dict]:
        if n <= 0:
            return []
        with self._lock:
            return list(self._ring)[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- the crash black box -------------------------------------------- #
    def dump(self, path: str, *, reason: str, meta: dict | None = None,
             ) -> dict:
        """Write the ring's records as one atomic postmortem document.

        Signal-handler-reachable (the scheduler's SIGTERM/SIGINT
        boundary flush calls this after the deferral guard admits the
        flush) — so NO lock here: ``list(deque)`` snapshots atomically
        under the GIL, and the write rides the approved atomic-write
        path (tmp+fsync+rename; PUMI008/PUMI009)."""
        from ..utils.checkpoint import atomic_write_json

        doc = {
            "schema": TRACE_SCHEMA,
            "kind": "blackbox",
            "reason": str(reason),
            "pid": os.getpid(),
            "ts": round(time.time(), 6),
            "meta": dict(meta or {}),
            "records": list(self._ring),
        }
        atomic_write_json(path, doc)
        return doc

    # -- chrome://tracing export ---------------------------------------- #
    def chrome(self, records: list[dict] | None = None) -> dict:
        """The ring (or the given records) as a Chrome-trace JSON
        document (``chrome://tracing`` / Perfetto).  Each job gets its
        own track; each span one complete ("X") slice ending at its
        wall timestamp; events become instant ("i") marks.  The FULL
        raw record rides in ``args`` so consumers (teleview.py --job
        over the live endpoint) can reconstruct the causal chain."""
        recs = self.records() if records is None else records
        return chrome_trace(recs)


def chrome_trace(records: list[dict]) -> dict:
    """Span/event records -> Chrome-trace JSON (module docstring
    contract: lossless — raw records ride in each event's ``args``)."""
    spans = [
        r for r in records
        if isinstance(r, dict)
        and r.get("kind") in ("span", "event")
        and isinstance(r.get("ts"), (int, float))
    ]
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(r["ts"] - float(r.get("seconds") or 0.0) for r in spans)
    tracks = sorted({
        str(r.get("job_id") or r.get("trace_id") or "untraced")
        for r in spans
    })
    tid = {k: i + 1 for i, k in enumerate(tracks)}
    events: list[dict] = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": tid[k],
            "cat": "__metadata",
            "args": {"name": k},
        }
        for k in tracks
    ]
    for r in spans:
        track = str(r.get("job_id") or r.get("trace_id") or "untraced")
        sec = float(r.get("seconds") or 0.0)
        args = {
            k: v for k, v in r.items()
            if isinstance(v, (int, float, str, bool)) or v is None
        }
        ev = {
            "name": str(r.get("name", r["kind"])),
            "pid": 1,
            "tid": tid[track],
            "args": args,
        }
        if r["kind"] == "span" and sec > 0:
            ev.update(
                ph="X", ts=(r["ts"] - sec - t0) * 1e6, dur=sec * 1e6
            )
        else:
            ev.update(ph="i", ts=(r["ts"] - t0) * 1e6, s="t")
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
