"""Fleet-level metric aggregation: merge N member registries into one.

The fleet router gives every member scheduler its OWN MetricsRegistry
(serving/fleet.py), so a member's counters are attributable and die
with it cleanly — but nobody watching one scrape can answer "what is
the FLEET's job throughput?".  ``FleetAggregator`` closes that gap: it
merges every member's registry snapshot into fleet-level rollups with
Prometheus-compatible semantics,

  * **counters** are summed across members per label set (the fleet
    total is the only meaningful reading of a monotonic count);
  * **histograms** are bucket-merged per label set (identical bucket
    boundaries everywhere — one DEFAULT_BUCKETS ladder — so cumulative
    counts, sums, and totals add);
  * **gauges** are kept per-member with a ``{member="mK"}`` label (a
    point-in-time level has no meaningful cross-member sum — queue
    depths and health flags must stay attributable).

The merged view is served from the router's exporter as ``/fleetz``
(Prometheus text, same content type as ``/metrics``) and snapshotted
atomically to ``<journal_dir>/FLEETSTATS.json`` at quantum cadence so
a dead router still leaves a last-known fleet picture for
``scripts/fleetview.py`` to reconstruct.

Determinism: merging is independent of member iteration order —
sources are sorted by member label before the fold and every series
list is emitted in sorted-label-key order, so two aggregators over the
same registries render byte-identical text (tests/test_fleet_obs.py
asserts this across shuffled orderings).
"""
from __future__ import annotations

from .registry import _fmt_labels, _label_key

FLEETSTATS_SCHEMA = 1
FLEETSTATS_FILE = "FLEETSTATS.json"


def _merge_hist(a: dict, b: dict) -> dict:
    """Merge two histogram snapshot values ({count, sum, buckets}).
    Bucket maps may differ (custom ladders): union the bounds — a
    bound one side never saw contributes that side's total count at
    +Inf only, which the cumulative render already handles."""
    buckets = dict(a["buckets"])
    for ub, c in b["buckets"].items():
        buckets[ub] = buckets.get(ub, 0) + c
    return {
        "count": a["count"] + b["count"],
        "sum": a["sum"] + b["sum"],
        "buckets": buckets,
    }


class FleetAggregator:
    """Merge member registries into one fleet-level snapshot.

    ``sources`` is a zero-arg callable returning ``[(label, registry),
    ...]`` — a callable, not a static list, so membership changes
    (evictions, deaths) are reflected at the next merge without the
    aggregator holding references to dead schedulers.
    """

    def __init__(self, sources):
        self._sources = sources

    def merge(self) -> dict:
        """{name: {type, help, series: [{labels, value}]}} — the same
        shape as ``MetricsRegistry.snapshot()``, so every structured
        consumer of a single registry can read the fleet rollup."""
        merged: dict[str, dict] = {}
        for label, registry in sorted(
            self._sources(), key=lambda s: str(s[0])
        ):
            for name, fam in registry.snapshot().items():
                out = merged.get(name)
                if out is None:
                    out = merged[name] = {
                        "type": fam["type"],
                        "help": fam["help"],
                        "series": {},
                    }
                elif out["type"] != fam["type"]:
                    # Cross-member type drift: impossible while every
                    # member runs the same code; refuse to fold rather
                    # than serve a lie.
                    raise ValueError(
                        f"fleet metric {name!r}: member {label} "
                        f"registers {fam['type']}, another member "
                        f"registered {out['type']}"
                    )
                if not out["help"] and fam["help"]:
                    out["help"] = fam["help"]
                for entry in fam["series"]:
                    labels = dict(entry["labels"])
                    if fam["type"] == "gauge":
                        # Point-in-time levels stay attributable.
                        labels["member"] = str(label)
                    key = _label_key(labels)
                    prev = out["series"].get(key)
                    if prev is None:
                        out["series"][key] = (labels, entry["value"])
                    elif fam["type"] == "histogram":
                        out["series"][key] = (
                            labels, _merge_hist(prev[1], entry["value"])
                        )
                    else:
                        out["series"][key] = (
                            labels, prev[1] + entry["value"]
                        )
        return {
            name: {
                "type": fam["type"],
                "help": fam["help"],
                "series": [
                    {"labels": labels, "value": value}
                    for _, (labels, value) in sorted(
                        fam["series"].items()
                    )
                ],
            }
            for name, fam in merged.items()
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the merged snapshot — the
        ``/fleetz`` body (mirrors MetricsRegistry.render_prometheus,
        but over the fold instead of a live family table)."""
        return render_snapshot_prometheus(self.merge())


def render_snapshot_prometheus(snap: dict) -> str:
    """Render a snapshot-shaped dict ({name: {type, help, series}}) as
    Prometheus text.  Shared by the aggregator (live ``/fleetz``) and
    fleetview (rendering a FLEETSTATS.json recovered from disk)."""
    lines: list[str] = []
    for name, fam in sorted(snap.items()):
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for entry in fam["series"]:
            labels = entry["labels"]
            if fam["type"] == "histogram":
                v = entry["value"]
                for ub, c in sorted(
                    v["buckets"].items(), key=lambda kv: float(kv[0])
                ):
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, {'le': ub})} {c}"
                    )
                lines.append(
                    f"{name}_bucket"
                    f"{_fmt_labels(labels, {'le': '+Inf'})} "
                    f"{v['count']}"
                )
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} {v['sum']}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {v['count']}"
                )
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {entry['value']}"
                )
    return "\n".join(lines) + ("\n" if lines else "")
