"""Run-wide telemetry subsystem.

Three layers (see each module's docstring):
  * ``walk_stats`` — schema of the on-device per-move stats vector the
    walk kernels fold into their jitted programs (one vector readback
    per move replaces host-side scans);
  * ``registry`` — labeled counters/gauges/histograms with snapshot(),
    Prometheus text exposition, and JSONL emission;
  * ``recorder`` / ``telemetry`` — the per-move flight recorder and the
    facade helper that feeds it (``PumiTally.telemetry()``,
    ``PartitionedTally.telemetry()``);
  * ``aggregate`` / ``slo`` / ``profile`` — the fleet observability
    plane: per-member registry aggregation (``/fleetz`` +
    FLEETSTATS.json), declarative SLOs with multi-window burn-rate
    alerting, and per-quantum device profiling with capture-on-anomaly
    (``PUMI_TPU_PROFILE=anomaly``).

Env knobs: ``PUMI_TPU_METRICS=jsonl:/path`` streams every flight record
to that file; ``PUMI_TPU_LOG_JSON=1`` renders the debug-level copies the
recorder sends through the standard logger as JSON;
``PUMI_TPU_PROM_PORT=<port>`` serves the registry's Prometheus text over
HTTP on a daemon thread (``exporter`` — port 0 picks an ephemeral one).
"""
from .convergence import (
    CONV_FIELDS,
    CONV_IDX,
    CONV_LEN,
    ConvergenceMonitor,
    conv_to_dict,
    reduce_chip_conv,
)
from .aggregate import (
    FLEETSTATS_FILE,
    FLEETSTATS_SCHEMA,
    FleetAggregator,
    render_snapshot_prometheus,
)
from .exporter import MetricsExporter, maybe_start_exporter
from .profile import FleetProfiler, profile_mode
from .recorder import FLIGHT_SCHEMA, FlightRecorder
from .slo import SLO, SLOEvaluator, default_slos
from .trace import (
    NO_PARENT,
    TRACE_SCHEMA,
    SpanTracer,
    chrome_trace,
    trace_enabled,
)
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from .telemetry import TallyTelemetry
from .walk_stats import (
    IDX,
    WALK_STATS_FIELDS,
    WALK_STATS_LEN,
    reduce_chip_stats,
    stats_to_dict,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "FlightRecorder",
    "FLIGHT_SCHEMA",
    "SpanTracer",
    "NO_PARENT",
    "TRACE_SCHEMA",
    "chrome_trace",
    "trace_enabled",
    "TallyTelemetry",
    "MetricsExporter",
    "maybe_start_exporter",
    "FleetAggregator",
    "FLEETSTATS_FILE",
    "FLEETSTATS_SCHEMA",
    "render_snapshot_prometheus",
    "SLO",
    "SLOEvaluator",
    "default_slos",
    "FleetProfiler",
    "profile_mode",
    "WALK_STATS_FIELDS",
    "WALK_STATS_LEN",
    "IDX",
    "stats_to_dict",
    "reduce_chip_stats",
    "CONV_FIELDS",
    "CONV_LEN",
    "CONV_IDX",
    "ConvergenceMonitor",
    "conv_to_dict",
    "reduce_chip_conv",
]
