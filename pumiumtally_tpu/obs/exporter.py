"""Live HTTP endpoint for a MetricsRegistry (plus trace/job surfaces).

A multi-hour soak should be watchable without touching the JSONL metrics
stream: this serves ``MetricsRegistry.render_prometheus()`` over plain
HTTP (stdlib ``http.server`` on a daemon thread — no dependencies, dies
with the process).  Endpoints:

  * ``/metrics`` (and ``/``) — the registry's Prometheus text
    exposition, content-type ``text/plain; version=0.0.4``;
  * ``/healthz`` — ``ok`` (liveness for scrapers/orchestrators);
  * ``/buildz`` — one JSON object identifying the serving process:
    package version, backend, x64 flag, device count (so a scrape
    target can be attributed to a build without shell access);
  * optional EXTRA endpoints registered by the owner — the serving
    scheduler mounts ``/jobs`` (live job-table JSON: state, outcome,
    moves, device-seconds, trace id per job) and ``/trace`` (the span
    tracer's ring as chrome://tracing JSON, loadable in Perfetto and
    consumed by ``scripts/teleview.py --job`` against a live server);
    a fleet router additionally mounts ``/fleet`` (per-member routing
    + liveness JSON) and ``/fleetz`` (the FleetAggregator's merged
    Prometheus rollup of every member registry — obs/aggregate.py).
    ``/buildz`` and 404 bodies enumerate whatever is mounted.
    Endpoint callables may declare one positional parameter to
    receive the parsed query string (``/jobs?limit=50`` caps the job
    table, default 500 newest-first), and may return a pre-rendered
    ``str`` to serve Prometheus text instead of JSON.

Unknown paths answer 404 with a body NAMING the valid endpoints —
a misremembered path should teach, not stonewall.

Started by the facades (and, for wrapped tallies that did not start
one, by ``ResilientRunner``) when ``PUMI_TPU_PROM_PORT`` is set; port 0
binds an ephemeral port (``exporter.port`` reports the real one — the
tests use this).  Binding is best-effort: a taken port logs one warning
and the run continues — observability must never take a run down.
"""
from __future__ import annotations

import inspect
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..utils.log import log_info, log_warn

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

ENV_PORT = "PUMI_TPU_PROM_PORT"


def _accepts_query(fn) -> bool:
    """True when an endpoint callable OPTS IN to the parsed query
    dict by declaring a positional parameter literally named
    ``query`` (decided by signature, not by trial call — a TypeError
    from inside the endpoint must surface as a 500, not be mistaken
    for an arity probe).  The name requirement is the contract: an
    endpoint with an unrelated optional positional (``chrome``'s
    ``records=None``) must NOT be handed the query dict."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):  # pragma: no cover - builtins
        return False
    for p in sig.parameters.values():
        if p.name == "query" and p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        ):
            return True
    return False


def build_info() -> dict:
    """The /buildz payload: package version + pinned environment axes
    (best-effort — a half-initialized process still answers)."""
    info = {
        "package": "pumiumtally_tpu",
        "version": None,
        "backend": None,
        "x64": None,
        "n_devices": None,
        "pid": os.getpid(),
    }
    try:
        from importlib.metadata import version

        info["version"] = version("pumiumtally_tpu")
    except Exception:  # pragma: no cover - metadata is environmental
        pass
    try:
        from ..analysis.contracts import environment

        env = environment()
        info["backend"] = env.get("backend")
        info["x64"] = env.get("x64")
        info["n_devices"] = env.get("n_devices")
    except Exception as e:  # pragma: no cover - jax not importable
        info["error"] = f"{type(e).__name__}: {e}"[:200]
    return info


class MetricsExporter:
    """One HTTP server serving one registry's Prometheus text plus the
    optional extra JSON endpoints the owner registers."""

    def __init__(self, registry, port: int, host: str = "127.0.0.1",
                 endpoints: dict | None = None):
        self.registry = registry
        # path -> callable returning either a JSON-able object (served
        # as application/json) or a pre-rendered str (served as
        # Prometheus text — the fleet router's /fleetz rollup).  A
        # callable with a positional parameter receives the parsed
        # query string as {key: last value} (e.g. /jobs?limit=50);
        # zero-arg callables keep working unchanged.
        self.endpoints = dict(endpoints or {})
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path, _, rawq = self.path.partition("?")
                try:
                    if path in ("/", "/metrics"):
                        body = (
                            exporter.registry.render_prometheus().encode()
                        )
                        ctype = PROM_CONTENT_TYPE
                    elif path == "/healthz":
                        body, ctype = b"ok\n", "text/plain"
                    elif path == "/buildz":
                        # The build payload also names every mounted
                        # route (extra endpoints like /jobs or /fleet
                        # included), so one probe discovers the whole
                        # scrape surface.
                        info = dict(
                            build_info(),
                            endpoints=(
                                ["/metrics", "/healthz", "/buildz"]
                                + sorted(exporter.endpoints)
                            ),
                        )
                        body = (
                            json.dumps(info, sort_keys=True) + "\n"
                        ).encode()
                        ctype = "application/json"
                    elif path in exporter.endpoints:
                        query = {
                            k: v[-1]
                            for k, v in parse_qs(rawq).items()
                        }
                        result = exporter._call(path, query)
                        if isinstance(result, str):
                            body = result.encode()
                            ctype = PROM_CONTENT_TYPE
                        else:
                            body = (
                                json.dumps(result, default=str) + "\n"
                            ).encode()
                            ctype = "application/json"
                    else:
                        known = ", ".join(
                            ["/metrics", "/healthz", "/buildz"]
                            + sorted(exporter.endpoints)
                        )
                        body = (
                            f"unknown path {path!r}; valid endpoints: "
                            f"{known}\n"
                        ).encode()
                        self.send_response(404)
                        self.send_header("Content-Type", "text/plain")
                        self.send_header(
                            "Content-Length", str(len(body))
                        )
                        self.end_headers()
                        self.wfile.write(body)
                        return
                except Exception as e:
                    # An endpoint callable must never kill the scrape
                    # thread — report the failure as the response.
                    body = (
                        f"endpoint {path!r} failed: "
                        f"{type(e).__name__}: {e}\n"
                    ).encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log events
                pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        # stop() races between facade close() and the GC finalizer
        # thread; the flag flip must be atomic so exactly one caller
        # runs the shutdown sequence (machine-checked by
        # analysis/astlint.py PUMI007).
        self._stop_lock = threading.Lock()
        self._stopped = False  # guarded by: self._stop_lock
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="pumi-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def _call(self, path: str, query: dict):
        """Invoke one mounted endpoint, passing the parsed query dict
        to callables declaring a positional parameter (``/jobs`` takes
        ``?limit=``) and nothing to the zero-arg ones."""
        fn = self.endpoints[path]
        if _accepts_query(fn):
            return fn(query)
        return fn()

    @property
    def port(self) -> int:
        """The bound port (resolves port 0 to the ephemeral choice)."""
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}/metrics"

    def stop(self) -> None:
        """Shut the server down and release the socket (idempotent —
        called from facade close() AND the facade's GC finalizer)."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def maybe_start_exporter(registry, port=None, endpoints=None):
    """Start an exporter when configured, else None.

    ``port`` defaults to the ``PUMI_TPU_PROM_PORT`` env var (unset →
    no exporter, zero cost).  Bind failures warn and return None."""
    if port is None:
        spec = os.environ.get(ENV_PORT, "").strip()
        if not spec:
            return None
        try:
            port = int(spec)
        except ValueError:
            log_warn(
                f"{ENV_PORT}={spec!r} is not a port number; "
                "metrics endpoint disabled"
            )
            return None
    try:
        exp = MetricsExporter(registry, port, endpoints=endpoints)
    except OSError as e:
        log_warn(
            f"metrics endpoint could not bind port {port} ({e}); "
            "continuing without it"
        )
        return None
    log_info(f"metrics endpoint serving at {exp.url}")
    return exp
