"""Facade-level telemetry: one registry + one flight recorder per tally.

Shared by PumiTally and PartitionedTally so the two facades cannot drift
on metric names or record schemas. The facade calls:

  * ``record_walk(kind, move, stats, seconds=..., **extra)`` once per
    trace (initial search or move) with the host view of the on-device
    stats vector (obs.walk_stats.stats_to_dict / reduce_chip_stats);
  * ``record_memory(phase)`` at phase boundaries (construction, VTK
    write) to capture per-device HBM peaks;
  * ``snapshot(times=...)`` from ``tally.telemetry()``.

Metric families (private registry per tally by default, so concurrent
tallies don't interleave):
  pumi_moves_total, pumi_segments_total, pumi_crossings_total,
  pumi_truncated_walks_total, pumi_chase_hops_total,
  pumi_migration_rounds_total, pumi_compaction_occupancy,
  pumi_move_seconds, pumi_device_peak_bytes{device=...}

Resilience families (fed by the quarantine / truncation-escalation
paths, resilience/):
  pumi_quarantined_lanes_total (deduplicated lanes),
  pumi_quarantine_reasons_total{reason=...},
  pumi_rewalked_lanes_total, pumi_lost_walks_total
"""
from __future__ import annotations

import dataclasses

from ..utils.profiling import device_memory_stats
from .recorder import FlightRecorder
from .registry import MetricsRegistry


class TallyTelemetry:
    def __init__(
        self,
        facade: str,
        registry: MetricsRegistry | None = None,
        recorder: FlightRecorder | None = None,
    ):
        self.facade = facade
        self.registry = registry if registry is not None else MetricsRegistry()
        self.recorder = recorder if recorder is not None else FlightRecorder()
        r = self.registry
        self._moves = r.counter(
            "pumi_moves_total", "facade move_to_next_location calls"
        )
        self._segments = r.counter(
            "pumi_segments_total", "scored particle-segments"
        )
        self._crossings = r.counter(
            "pumi_crossings_total", "real element-boundary crossings"
        )
        self._truncated = r.counter(
            "pumi_truncated_walks_total",
            "walks not finished within max_crossings / the round bound",
        )
        self._chase = r.counter(
            "pumi_chase_hops_total",
            "stuck-escape (relocation chase) activations",
        )
        self._rounds = r.counter(
            "pumi_migration_rounds_total",
            "partitioned walk/exchange rounds executed",
        )
        self._occ = r.gauge(
            "pumi_compaction_occupancy",
            "mean post-compaction active occupancy of the last trace",
        )
        self._move_s = r.histogram(
            "pumi_move_seconds", "wall-clock seconds per facade move"
        )
        self._hbm = r.gauge(
            "pumi_device_peak_bytes", "peak device memory in use"
        )
        self._quarantined = r.counter(
            "pumi_quarantined_lanes_total",
            "lanes masked out of the walk by the bad-particle "
            "quarantine (each lane once per move, however many "
            "reasons it trips)",
        )
        self._quarantine_reasons = r.counter(
            "pumi_quarantine_reasons_total",
            "quarantine verdicts by reason (a lane tripping several "
            "reasons counts once per reason)",
        )
        self._rewalked = r.counter(
            "pumi_rewalked_lanes_total",
            "truncated lanes re-walked by the escalation policy",
        )
        self._lost = r.counter(
            "pumi_lost_walks_total",
            "walks declared lost after bounded re-walk retries (or "
            "immediately, with the escalation policy off)",
        )
        # Move-loop I/O accounting (ops/staging.py): bytes and transfer
        # counts the facade actually staged per trace.  Under
        # io_pipeline="packed" the steady-state invariant is ONE H2D
        # and ONE D2H per move — tests/test_io_pipeline.py asserts it
        # through these counters under a jax.transfer_guard.
        self._h2d_bytes = r.counter(
            "pumi_h2d_bytes_total",
            "host-to-device bytes staged by the move loop",
        )
        self._d2h_bytes = r.counter(
            "pumi_d2h_bytes_total",
            "device-to-host bytes read back by the move loop",
        )
        self._h2d_transfers = r.counter(
            "pumi_h2d_transfers_total",
            "host-to-device transfers issued by the move loop",
        )
        self._d2h_transfers = r.counter(
            "pumi_d2h_transfers_total",
            "device-to-host transfers issued by the move loop",
        )
        # Self-verification families (integrity/): invariant + audit +
        # watchdog violations by check, shadow-audit volume, and the
        # worst conservation residual seen this run.
        self._integ_violations = r.counter(
            "pumi_integrity_violations_total",
            "integrity-check violations (labeled by check: "
            "conservation, flux, lanes, sdc_audit, watchdog)",
        )
        self._audited = r.counter(
            "pumi_audited_lanes_total",
            "lanes re-walked by the float64 shadow audit",
        )
        self._audit_mismatch = r.counter(
            "pumi_audit_mismatches_total",
            "shadow-audit lanes disagreeing with the host reference "
            "beyond tolerance",
        )
        self._max_residual = 0.0

    # ------------------------------------------------------------------ #
    def record_walk(
        self,
        kind: str,
        move: int,
        stats: dict | None,
        seconds: float | None = None,
        **extra,
    ) -> dict:
        """Fold one trace's stats into the counters and the recorder.
        ``stats`` is the named dict from the on-device stats vector (or
        None when walk stats are disabled); ``seconds`` is the facade
        phase time for this call where measured."""
        fields = dict(extra)
        fields["move"] = int(move)
        if seconds is not None:
            fields["seconds"] = round(float(seconds), 6)
            if kind == "move":
                self._move_s.observe(float(seconds))
        if kind == "move":
            self._moves.inc()
        elif kind == "megastep":
            # One megastep record covers K fused device moves; the
            # moves counter advances by the fused count so the totals
            # stay per-MOVE comparable across loop modes.
            self._moves.inc(int(extra.get("moves", 1)))
        if stats is not None:
            fields.update(stats)
            self._segments.inc(stats["segments"])
            self._crossings.inc(stats["crossings"])
            self._truncated.inc(stats["truncated"])
            self._chase.inc(stats["chase_hops"])
            if stats.get("occupancy") is not None:
                self._occ.set(stats["occupancy"])
        if "rounds" in extra:
            self._rounds.inc(int(extra["rounds"]))
        # I/O accounting riding the same per-trace record (the facade
        # passes what it actually staged — packed: one record each way;
        # legacy: one entry per staged array).
        for key, counter in (
            ("h2d_bytes", self._h2d_bytes),
            ("d2h_bytes", self._d2h_bytes),
            ("h2d_transfers", self._h2d_transfers),
            ("d2h_transfers", self._d2h_transfers),
        ):
            if key in extra:
                counter.inc(int(extra[key]))
        return self.recorder.record(kind, **fields)

    def record_quarantine(
        self, move: int, lanes: int, reasons: dict
    ) -> dict:
        """Fold one move's quarantine verdicts: ``lanes`` is the
        DEDUPLICATED parked-lane count (the headline number, agrees
        with ``quarantined_lanes()``); ``reasons`` maps reason name →
        verdict count (resilience/quarantine.py REASONS)."""
        self._quarantined.inc(lanes)
        for reason, count in reasons.items():
            if count:
                self._quarantine_reasons.inc(count, reason=reason)
        return self.recorder.record(
            "quarantine", move=int(move), lanes=int(lanes), **reasons
        )

    def record_rewalk(self, move: int, retried: int, lost: int) -> dict:
        """Fold one move's truncation-escalation outcome: lanes
        re-walked (summed over attempts) and lanes finally lost."""
        if retried:
            self._rewalked.inc(retried)
        if lost:
            self._lost.inc(lost)
        return self.recorder.record(
            "rewalk", move=int(move), retried=int(retried),
            lost=int(lost),
        )

    def record_integrity(
        self, move: int, fields: dict, violations: list
    ) -> dict:
        """Fold one move's integrity evaluation: the invariant scalars
        (integrity/invariants.py field names, possibly empty for
        watchdog-only events) plus the violated check names. Counting
        happens here BEFORE policy escalation so the counters are
        consistent whichever rung fires."""
        for check in violations:
            self._integ_violations.inc(check=check)
        if fields.get("max_residual") is not None:
            self._max_residual = max(
                self._max_residual, float(fields["max_residual"])
            )
        return self.recorder.record(
            "integrity",
            move=int(move),
            violations=list(violations),
            **fields,
        )

    def record_audit(
        self, move: int, audited: int, mismatches: int, skipped: int,
        max_dev: float,
    ) -> dict:
        """Fold one move's shadow-audit outcome (integrity/audit.py) —
        per-move results in the flight recorder (and any
        PUMI_TPU_METRICS=jsonl: stream)."""
        if audited:
            self._audited.inc(audited)
        if mismatches:
            self._audit_mismatch.inc(mismatches)
        return self.recorder.record(
            "audit",
            move=int(move),
            audited=int(audited),
            mismatches=int(mismatches),
            skipped=int(skipped),
            max_dev=float(max_dev),
        )

    def record_memory(self, phase: str) -> dict:
        """Sample per-device memory at a phase boundary (peak bytes where
        the backend reports them — TPU does, CPU usually returns {})."""
        mem = device_memory_stats()
        for dev, rec in mem.items():
            if "peak_bytes_in_use" in rec:
                self._hbm.set(rec["peak_bytes_in_use"], device=dev)
        return self.recorder.record("memory", phase=phase, devices=mem)

    # ------------------------------------------------------------------ #
    def snapshot(self, times=None, tail: int = 64) -> dict:
        """The ``tally.telemetry()`` payload: counter totals, the last
        ``tail`` flight records, a fresh memory sample, phase times, and
        the full registry snapshot."""
        quarantined = self._quarantined.value()
        out = {
            "facade": self.facade,
            "totals": {
                "moves": self._moves.value(),
                "segments": self._segments.value(),
                "crossings": self._crossings.value(),
                "truncated": self._truncated.value(),
                "chase_hops": self._chase.value(),
                "migration_rounds": self._rounds.value(),
                "quarantined": quarantined,
                "rewalked": self._rewalked.value(),
                "lost": self._lost.value(),
                "h2d_bytes": self._h2d_bytes.value(),
                "d2h_bytes": self._d2h_bytes.value(),
                "h2d_transfers": self._h2d_transfers.value(),
                "d2h_transfers": self._d2h_transfers.value(),
            },
            # Headline resilience count, also at the top level: the
            # acceptance surface is telemetry()["quarantined"].
            "quarantined": quarantined,
            # Self-verification block (integrity/): violations by
            # check, shadow-audit volume, worst conservation residual.
            "integrity": {
                "violations": {
                    s["labels"].get("check", ""): s["value"]
                    for s in self._integ_violations.snapshot()["series"]
                },
                "audited_lanes": self._audited.value(),
                "audit_mismatches": self._audit_mismatch.value(),
                "max_residual": self._max_residual,
            },
            "per_move": self.recorder.tail(tail),
            "memory": device_memory_stats(),
            "metrics": self.registry.snapshot(),
        }
        if times is not None:
            out["times"] = dataclasses.asdict(times)
        return out
