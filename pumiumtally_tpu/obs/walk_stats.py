"""Schema of the on-device walk stats vector.

The reference library's only per-move observability is the host-side
"Not all particles are found" printf (pumipic_particle_data_structure
.cpp:765-768) plus four coarse [TIME] phase timers — it cannot say WHY a
run is slow. Here every fused trace folds a small vector of counters
into the jitted program itself (ops/walk.py, ops/walk_partitioned.py):
one scalar-vector readback per move carries everything the flight
recorder needs, replacing the per-move host scan of the ``done`` array
the facade used to do, with zero extra device dispatches.

The vector layout is the single source of truth for both walk kernels;
``tests/test_obs.py`` pins the field order against the kernels so a
drift breaks loudly.
"""
from __future__ import annotations

import numpy as np

WALK_STATS_FIELDS = (
    # Real element-boundary crossings summed over all lanes (relocation-
    # chase hops are bookkeeping and excluded, matching the segment
    # count's convention in ops/walk.py).
    "crossings",
    # Max real crossings by any single lane. For the partitioned walk a
    # lane is a resident SLOT, so this is a per-chip per-slot maximum
    # (counters do not migrate with particles across cuts).
    "max_crossings",
    # Stuck-escape activations: relocation-chase hops executed
    # (ops/walk.py "Degeneracy robustness"). Nonzero means grazing-ray
    # recovery is active — on a clean mesh this should be 0.
    "chase_hops",
    # In-flight walks not finished when the trace returned (truncated at
    # max_crossings / the migration round bound) — the per-particle
    # analog of the reference's cpp:765-768 error, as one scalar.
    "truncated",
    # Straggler-compaction occupancy: active lanes placed into subset
    # slots, and subset slots swept, summed over every compaction round.
    # occ_active/occ_slots is the mean post-compaction occupancy; both 0
    # when compaction never ran.
    "occ_active",
    "occ_slots",
    # Scored particle-segments (duplicates TraceResult.n_segments so ONE
    # vector fetch serves the whole flight-recorder record).
    "segments",
    # While-loop body iterations executed (TraceResult.n_crossings; for
    # the partitioned walk: phase-1 iters + all follow-up round iters).
    "loop_iters",
)

WALK_STATS_LEN = len(WALK_STATS_FIELDS)

IDX = {name: i for i, name in enumerate(WALK_STATS_FIELDS)}


def _derived(d: dict) -> dict:
    d["occupancy"] = (
        round(d["occ_active"] / d["occ_slots"], 4) if d["occ_slots"] else None
    )
    return d


def stats_to_dict(vec) -> dict:
    """Host-side view of one stats vector: named integer fields plus the
    derived mean compaction ``occupancy`` (None when compaction never
    ran)."""
    v = np.asarray(vec)
    if v.shape != (WALK_STATS_LEN,):
        raise ValueError(
            f"expected a [{WALK_STATS_LEN}] stats vector, got {v.shape}"
        )
    return _derived({f: int(v[i]) for i, f in enumerate(WALK_STATS_FIELDS)})


def reduce_chip_stats(mat) -> dict:
    """Aggregate a per-chip [n_parts, LEN] stats matrix into one run-level
    dict: sums everywhere except ``max_crossings`` (max over chips)."""
    m = np.asarray(mat)
    if m.ndim != 2 or m.shape[1] != WALK_STATS_LEN:
        raise ValueError(
            f"expected [n_parts, {WALK_STATS_LEN}] chip stats, got {m.shape}"
        )
    d = {f: int(m[:, i].sum()) for i, f in enumerate(WALK_STATS_FIELDS)}
    d["max_crossings"] = int(m[:, IDX["max_crossings"]].max(initial=0))
    return _derived(d)
