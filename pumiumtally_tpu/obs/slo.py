"""Declarative SLOs with multi-window burn-rate evaluation.

An SLO here is a statement over metrics the serving stack already
emits — no new instrumentation in the hot path:

  * ``kind="latency"``: fraction of observations of a HISTOGRAM family
    at or under ``threshold_s`` (good = cumulative count of the
    largest bucket bound <= threshold, so "good" never overcounts);
  * ``kind="ratio"``: fraction of a COUNTER family's observations
    whose ``label`` value is in ``good_values`` (job success ratio
    over ``pumi_jobs_total{outcome=}``);
  * ``kind="availability"``: fraction of fleet members alive, sampled
    once per evaluation (each tick contributes one observation per
    member, so the error budget burns in supervisor time).

Evaluation follows the multi-window burn-rate pattern (SRE workbook):
for each ``(fast, slow)`` window pair the burn rate is

    burn(W) = (bad_W / total_W) / (1 - objective)

— 1.0 means "burning budget exactly at the rate that exhausts it at
the objective horizon"; an ALERT fires only when BOTH windows burn
above ``alert_burn`` (fast window catches the spike, slow window
confirms it is not a blip).  Burn rates are exported as
``pumi_slo_burn_rate{slo=,window=}`` gauges; a rising alert edge emits
an ``slo_breach`` flight record naming the offending member (the
member whose own bad-count delta over the fast window is largest) —
``FleetSupervisor`` consumes that attribution as an advisory signal
and quarantines the offender through its existing hysteresis
machinery (breach-record-before-quarantine, protolint-verified).

The evaluator is deliberately pull-based and allocation-light: one
cumulative (good, total) sample per member per tick into a bounded
ring, deltas against the ring on evaluation — no per-observation
callbacks anywhere near the dispatch path.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque


@dataclasses.dataclass(frozen=True)
class SLO:
    """One objective over an existing metric family."""

    name: str
    kind: str                      # "latency" | "ratio" | "availability"
    objective: float               # target good fraction, e.g. 0.99
    metric: str = ""               # histogram/counter family name
    threshold_s: float | None = None   # latency: good iff <= threshold
    label: str = ""                # ratio: label key holding the outcome
    good_values: tuple = ()        # ratio: label values that count good
    windows: tuple = ((30.0, 120.0),)  # (fast_s, slow_s) pairs
    alert_burn: float = 1.0        # burn threshold (both windows)

    def __post_init__(self):
        if self.kind not in ("latency", "ratio", "availability"):
            raise ValueError(f"SLO {self.name}: unknown kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name}: objective must be in (0, 1): "
                f"{self.objective}"
            )
        if self.kind == "latency" and (
            not self.metric or self.threshold_s is None
        ):
            raise ValueError(
                f"SLO {self.name}: latency kind needs metric + threshold_s"
            )
        if self.kind == "ratio" and (
            not self.metric or not self.label or not self.good_values
        ):
            raise ValueError(
                f"SLO {self.name}: ratio kind needs metric + label + "
                "good_values"
            )
        for pair in self.windows:
            fast, slow = pair
            if not 0 < fast <= slow:
                raise ValueError(
                    f"SLO {self.name}: window pair {pair} must satisfy "
                    "0 < fast <= slow"
                )


def default_slos() -> tuple:
    """The fleet's stock objectives — all over families the scheduler
    already emits (serving/scheduler.py)."""
    return (
        SLO(
            name="job-e2e-latency",
            kind="latency",
            metric="pumi_job_e2e_seconds",
            threshold_s=30.0,
            objective=0.95,
            windows=((60.0, 300.0),),
        ),
        SLO(
            name="time-to-first-quantum",
            kind="latency",
            metric="pumi_job_time_to_first_quantum_seconds",
            threshold_s=10.0,
            objective=0.95,
            windows=((60.0, 300.0),),
        ),
        SLO(
            name="job-success",
            kind="ratio",
            metric="pumi_jobs_total",
            label="outcome",
            good_values=("completed", "cancelled"),
            objective=0.99,
            windows=((60.0, 300.0),),
        ),
        SLO(
            name="member-availability",
            kind="availability",
            objective=0.90,
            windows=((30.0, 120.0),),
        ),
    )


def _latency_counts(registry, metric: str, threshold: float):
    """(good, total) over every series of a histogram family: good is
    the cumulative count of the largest bucket bound <= threshold —
    an under-count when the threshold falls inside a bucket, never an
    over-count."""
    snap = registry.snapshot().get(metric)
    if snap is None or snap["type"] != "histogram":
        return 0, 0
    good = total = 0
    for entry in snap["series"]:
        v = entry["value"]
        total += v["count"]
        best = -1.0
        best_c = 0
        for ub, c in v["buckets"].items():
            b = float(ub)
            if b <= threshold and b > best:
                best, best_c = b, c
        good += best_c
    return good, total


def _ratio_counts(registry, metric: str, label: str, good_values):
    snap = registry.snapshot().get(metric)
    if snap is None:
        return 0, 0
    good = total = 0
    for entry in snap["series"]:
        v = entry["value"]
        total += v
        if entry["labels"].get(label) in good_values:
            good += v
    return good, total


class SLOEvaluator:
    """Tick-driven burn-rate evaluation over per-member registries.

    ``evaluate(members)`` takes ``[(index, label, registry, alive),
    ...]`` — the router's live view — appends one cumulative sample to
    the ring, recomputes burn rates per window, updates the
    ``pumi_slo_burn_rate`` gauges, and maintains ``self.alerts``
    ({slo name -> alert dict}).  A RISING edge records ``slo_breach``
    through the recorder; the alert stays active (and keeps its
    original attribution) until every window's burn drops back under
    the threshold.
    """

    def __init__(self, slos, registry, recorder=None, *,
                 clock=time.monotonic, max_samples: int = 1024):
        self.slos = tuple(slos)
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.recorder = recorder
        self._clock = clock
        self._burn_gauge = registry.gauge(
            "pumi_slo_burn_rate",
            "error-budget burn rate per SLO and evaluation window "
            "(1.0 = burning exactly at the objective rate; alerts "
            "need every window of a pair above the threshold)",
        )
        self._alerts_gauge = registry.gauge(
            "pumi_slo_alert",
            "1 while the SLO's multi-window burn-rate alert is "
            "active, else 0",
        )
        # Ring of (t, {slo: {"fleet": (good, total),
        #                    "member": {index: (good, total)}}}).
        self._samples: deque = deque(maxlen=int(max_samples))
        # Availability ticks accumulated here so the samples stay
        # cumulative like every counter-backed kind — a raw per-tick
        # (alive, 1) snapshot would difference to zero in every
        # window and the SLO could never burn.
        self._avail: dict[str, dict[int, tuple]] = {}
        #: Active alerts: {slo name: {"slo", "member", "burn", "since"}}.
        self.alerts: dict[str, dict] = {}

    # ------------------------------------------------------------------ #
    def _counts(self, slo: SLO, members):
        """Cumulative (good, total) fleet-wide and per member index."""
        per: dict[int, tuple] = {}
        if slo.kind == "availability":
            cum = self._avail.setdefault(slo.name, {})
            for index, _label, _registry, alive in members:
                good, total = cum.get(index, (0, 0))
                cum[index] = per[index] = (
                    good + (1 if alive else 0), total + 1,
                )
        else:
            # Dead members' registries stay in the fold: their counts
            # are cumulative history — dropping them would shrink the
            # fleet totals and fake a good/bad delta.
            for index, _label, registry, _alive in members:
                if registry is None:
                    continue
                if slo.kind == "latency":
                    per[index] = _latency_counts(
                        registry, slo.metric, slo.threshold_s
                    )
                else:
                    per[index] = _ratio_counts(
                        registry, slo.metric, slo.label, slo.good_values
                    )
        fleet = (
            sum(g for g, _ in per.values()),
            sum(t for _, t in per.values()),
        )
        return fleet, per

    def _window_delta(self, now: float, window: float, slo: str,
                      member: int | None = None):
        """(good_delta, total_delta) between the newest sample and the
        newest sample at least ``window`` old (the oldest one when
        history is still shorter than the window)."""
        if not self._samples:
            return 0, 0
        newest = self._samples[-1]
        base = self._samples[0]
        for s in reversed(self._samples):
            if now - s[0] >= window:
                base = s
                break

        def pick(sample):
            entry = sample[1].get(slo)
            if entry is None:
                return (0, 0)
            if member is None:
                return entry["fleet"]
            return entry["member"].get(member, (0, 0))

        g1, t1 = pick(newest)
        g0, t0 = pick(base)
        # Availability samples are per-tick observations, cumulative by
        # construction; counters can only grow — clamp defensively so a
        # member swap never yields negative deltas.
        return max(0, g1 - g0), max(0, t1 - t0)

    @staticmethod
    def _burn(good: float, total: float, objective: float) -> float:
        if total <= 0:
            return 0.0
        bad_ratio = (total - good) / total
        return bad_ratio / (1.0 - objective)

    # ------------------------------------------------------------------ #
    def evaluate(self, members) -> dict:
        """One tick: sample, recompute burns, maintain alerts.
        Returns ``self.alerts`` (live dict, keyed by SLO name)."""
        now = self._clock()
        sample = {}
        for slo in self.slos:
            fleet, per = self._counts(slo, members)
            sample[slo.name] = {"fleet": fleet, "member": per}
        self._samples.append((now, sample))

        for slo in self.slos:
            breaching = False
            burns = {}
            for fast, slow in slo.windows:
                pair_hot = True
                for w in (fast, slow):
                    g, t = self._window_delta(now, w, slo.name)
                    burn = self._burn(g, t, slo.objective)
                    burns[f"{w:g}s"] = burn
                    self._burn_gauge.set(
                        burn, slo=slo.name, window=f"{w:g}s"
                    )
                    if burn <= slo.alert_burn:
                        pair_hot = False
                breaching = breaching or pair_hot
            active = self.alerts.get(slo.name)
            if breaching and active is None:
                fast = min(f for f, _ in slo.windows)
                offender = None
                worst = 0
                for index, _label, _registry, _alive in members:
                    g, t = self._window_delta(
                        now, fast, slo.name, member=index
                    )
                    bad = t - g
                    if bad > worst:
                        worst, offender = bad, index
                alert = {
                    "slo": slo.name,
                    "member": offender,
                    "burn": dict(burns),
                    "since": now,
                }
                self.alerts[slo.name] = alert
                if self.recorder is not None:
                    self.recorder.record(
                        "slo_breach", slo=slo.name, member=offender,
                        burn=dict(burns),
                        objective=slo.objective,
                    )
            elif breaching:
                active["burn"] = dict(burns)
            elif active is not None:
                del self.alerts[slo.name]
            self._alerts_gauge.set(
                1.0 if slo.name in self.alerts else 0.0, slo=slo.name
            )
        return self.alerts

    # ------------------------------------------------------------------ #
    def alerts_by_member(self) -> dict[int, list[dict]]:
        """Active alerts grouped by attributed member index (alerts
        with no attribution — e.g. a fleet-wide availability burn —
        are not anyone's fault and do not appear here)."""
        out: dict[int, list[dict]] = {}
        for alert in self.alerts.values():
            if alert.get("member") is not None:
                out.setdefault(int(alert["member"]), []).append(alert)
        return out

    def status(self) -> dict:
        """The FLEETSTATS.json ``slo`` section: declared objectives,
        current burns, active alerts, and the recent sample ring (the
        burn timeline fleetview renders)."""
        now = self._clock()
        slos = []
        for slo in self.slos:
            windows = []
            for fast, slow in slo.windows:
                for w in (fast, slow):
                    g, t = self._window_delta(now, w, slo.name)
                    windows.append({
                        "window_s": w,
                        "good": g,
                        "total": t,
                        "burn": self._burn(g, t, slo.objective),
                    })
            slos.append({
                "name": slo.name,
                "kind": slo.kind,
                "objective": slo.objective,
                "metric": slo.metric,
                "threshold_s": slo.threshold_s,
                "windows": windows,
                "alert": self.alerts.get(slo.name),
            })
        timeline = [
            {
                "t": t,
                "age_s": now - t,
                "slos": {
                    name: {"fleet": list(entry["fleet"])}
                    for name, entry in sample.items()
                },
            }
            for t, sample in list(self._samples)[-64:]
        ]
        return {"slos": slos, "alerts": dict(self.alerts),
                "timeline": timeline}
