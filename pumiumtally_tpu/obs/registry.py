"""Labeled metrics registry: counters, gauges, histograms.

The reference has no metrics at all (four printf timers, SURVEY.md §5);
this is the run-wide aggregation layer the flight recorder and facades
feed. Deliberately dependency-free: a tiny in-process registry with
``snapshot()`` for structured consumers (bench JSON, ``telemetry()``),
Prometheus text exposition for scrapers, and JSONL emission riding the
``PUMI_TPU_METRICS`` sink (utils/log.emit_metric).

Label handling follows the Prometheus model: a metric name owns a family
of series keyed by the label set supplied at observation time
(``counter.inc(3, device="tpu:0")``); the empty label set is one series.
"""
from __future__ import annotations

import math
import threading


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict = {}
        self._lock = threading.Lock()

    def labels_seen(self) -> list[dict]:
        return [dict(k) for k in self._series]

    def _snapshot_value(self, v):
        return v

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "type": self.kind,
                "help": self.help,
                "series": [
                    {"labels": dict(k), "value": self._snapshot_value(v)}
                    for k, v in self._series.items()
                ],
            }


class Counter(_Metric):
    """Monotonically increasing count (negative increments rejected)."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {amount})"
            )
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels) -> float:
        return self._series.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Point-in-time value (set wins; inc/dec for running levels)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = value

    def inc(self, amount: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return self._series.get(_label_key(labels), 0)


# Wall-clock-per-move oriented default: 1 ms .. 60 s.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; +Inf is implicit)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        b = tuple(sorted(float(x) for x in buckets))
        if not b or any(not math.isfinite(x) for x in b):
            raise ValueError(f"histogram {name}: buckets must be finite")
        self.buckets = b

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = {"count": 0, "sum": 0.0,
                     "buckets": [0] * len(self.buckets)}
                self._series[key] = s
            s["count"] += 1
            s["sum"] += float(value)
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    s["buckets"][i] += 1

    def value(self, **labels) -> dict | None:
        s = self._series.get(_label_key(labels))
        return None if s is None else dict(s, buckets=list(s["buckets"]))

    def _snapshot_value(self, v):
        return {
            "count": v["count"],
            "sum": v["sum"],
            "buckets": dict(zip((str(b) for b in self.buckets),
                                v["buckets"])),
        }


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(items.items()))
    return "{" + inner + "}"


class MetricsRegistry:
    """Create-or-get metric families by name; duplicate names must agree
    on type (a counter named like an existing gauge raises)."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            elif help and m.help and help != m.help:
                # Same name re-registered with a DIFFERENT meaning is the
                # cross-family drift the metrics lint exists to catch —
                # refuse instead of silently serving one family's help
                # text for the other's observations. (Re-registering
                # with the identical help, or looking a metric up with
                # no help, stays a create-or-get.)
                raise ValueError(
                    f"metric {name!r} already registered with help "
                    f"{m.help!r}; conflicting help {help!r}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def _families(self) -> dict:
        """Stable copy of the family table: readers (snapshot, the
        exporter's scrape thread) iterate the copy, never the live dict
        — lazy mid-run registration (e.g. the fault counters on first
        injection) would otherwise mutate it under a concurrent scrape."""
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> dict:
        """{name: {type, help, series: [{labels, value}, ...]}} — the
        structured view ``telemetry()`` and the bench JSON embed."""
        return {name: m.snapshot() for name, m in self._families().items()}

    def render_prometheus(self) -> str:
        """Prometheus text exposition (content-type
        ``text/plain; version=0.0.4``) of every registered series."""
        lines: list[str] = []
        for name, m in sorted(self._families().items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for entry in m.snapshot()["series"]:
                labels = entry["labels"]
                if m.kind == "histogram":
                    v = entry["value"]
                    # observe() incremented every bucket with value <= ub,
                    # so the stored counts are already cumulative.
                    for ub, c in v["buckets"].items():
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels(labels, {'le': ub})} {c}"
                        )
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, {'le': '+Inf'})} "
                        f"{v['count']}"
                    )
                    lines.append(
                        f"{name}_sum{_fmt_labels(labels)} {v['sum']}"
                    )
                    lines.append(
                        f"{name}_count{_fmt_labels(labels)} {v['count']}"
                    )
                else:
                    lines.append(
                        f"{name}{_fmt_labels(labels)} {entry['value']}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


# Process-default registry for callers that want one shared aggregation
# point; the facades default to a private registry per tally instance so
# concurrent tallies (and tests) do not interleave counts.
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
