"""Statistical convergence observability: on-device uncertainty
reduction, batch statistics, and the ConvergenceMonitor.

The reference accumulates per-segment squared contributions
(cpp:640-641) but never turns the second moment into the quantities
Monte Carlo practitioners actually steer runs by: per-element relative
error, converged fraction, and figure of merit (PUMI-Tally,
arXiv:2504.19048; exascale frameworks treat in-flight statistical
diagnostics as a first-class subsystem, arXiv:2603.24508).  This module
is that subsystem for both facades:

  * **batch statistics** — the run is divided into *batches* (every
    ``TallyConfig.batch_moves`` moves, or explicit ``tally.end_batch()``)
    and the flux accumulator's per-bin batch totals ``T_b`` are folded
    into device-resident accumulators ``S1 = Σ T_b`` and ``S2 = Σ T_b²``
    so the relative error is a proper N-batch estimator:

        R = sqrt((N·S2 − S1²)/(N − 1)) / S1        per scored bin

    ``S1`` is exactly the even (Σc) flux entries at the last batch
    boundary, so the state is (snapshot, Σ T², n_batches, move counter)
    — two bin-sized arrays and two scalars.
  * **on-device reduction** — ``fold_and_reduce`` runs INSIDE the walk
    programs (ops/walk.py trace with ``conv_state``, ops/
    walk_partitioned.py make_partitioned_step(convergence=True)): the
    batch fold plus a [CONV_LEN] summary vector (scored-bin count,
    Σ rel-err, max rel-err, converged-bin count) that rides the packed
    readback tail — ZERO extra dispatches or transfers; the
    steady-state 1 H2D + 1 D2H invariant of the I/O pipeline holds
    with convergence on (pinned in tests/test_convergence.py).
  * **ConvergenceMonitor** — folds the per-move summary into the gauge
    families ``pumi_rel_err_max`` / ``pumi_rel_err_mean`` /
    ``pumi_converged_fraction`` / ``pumi_fom``, emits one flight-recorder
    record per completed batch, and answers ``tally.converged()`` for
    caller-driven early stop.

The reductions READ the accumulator and never write it: with
``TallyConfig.convergence=False`` (the default) nothing here exists and
outputs are bit-identical to a build without this module.

Counts travel as walk-dtype floats through the readback tail (the
integrity-vector encoding); above 2^24 scored bins an f32 count loses
ulps — statistically irrelevant for a monitor, and the f64 path is
exact.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Field order of the on-device convergence summary vector.  The single
# source of truth for both walk kernels and the staging pack/split
# (ops/staging.py appends CONV_LEN carrier words to the readback tail).
CONV_FIELDS = (
    # Completed batches N (replicated per chip on the partitioned walk).
    "n_batches",
    # Bins with a nonzero accumulated score (the rel-err denominator
    # population; per-chip partials sum — every element is owned by
    # exactly one chip and halo rows return zeroed).
    "scored",
    # Σ over scored bins of the per-bin relative error (host divides by
    # ``scored`` for the mean; bins with N < 2 batches report rel-err 1,
    # i.e. unconverged, so early gauges cannot read as converged).
    "sum_rel_err",
    # max over scored bins of the per-bin relative error.
    "max_rel_err",
    # Scored bins with rel-err <= TallyConfig.rel_err_target.
    "converged",
)

CONV_LEN = len(CONV_FIELDS)

CONV_IDX = {name: i for i, name in enumerate(CONV_FIELDS)}


# --------------------------------------------------------------------- #
# Traced reductions (run inside the walk programs and end_batch folds)
# --------------------------------------------------------------------- #
def conv_reduce(snap, sumsq, nb, rel_err_target):
    """Per-bin relative error reduced to the [CONV_LEN] summary vector.

    ``snap``/``sumsq`` are the batch accumulators with bins on the LAST
    axis; ``nb`` is the completed-batch count with one fewer dimension
    (scalar for a single chip / one shard, [n_parts] for assembled
    slabs).  Returns [..., CONV_LEN] in ``snap.dtype``.

    Bins with fewer than 2 batches have no variance estimate: scored
    bins there report rel-err 1.0 (unconverged), unscored bins 0 and
    are excluded everywhere.
    """
    dtype = snap.dtype
    nbf = jnp.maximum(nb, 1).astype(dtype)[..., None]
    scored = snap > 0
    denom = jnp.maximum(nbf - 1.0, 1.0)
    var_num = jnp.maximum(nbf * sumsq - snap * snap, 0.0)
    rel = jnp.sqrt(var_num / denom) / jnp.where(scored, snap, 1.0)
    defined = (nb >= 2)[..., None]
    rel = jnp.where(scored, jnp.where(defined, rel, 1.0), 0.0)
    n_scored = jnp.sum(scored, axis=-1)
    n_conv = jnp.sum(
        scored & defined & (rel <= rel_err_target), axis=-1
    )
    return jnp.stack(
        [
            nb.astype(dtype),
            n_scored.astype(dtype),
            jnp.sum(rel, axis=-1).astype(dtype),
            jnp.max(rel, axis=-1).astype(dtype),
            n_conv.astype(dtype),
        ],
        axis=-1,
    )


def fold_and_reduce(
    flux,
    snap,
    sumsq,
    nb,
    mv,
    *,
    batch_moves: int,
    rel_err_target: float,
    enable=None,
    force: bool = False,
):
    """One move's (or one explicit end_batch's) convergence step.

    ``flux`` is the stride-2 accumulator with the interleaved (Σc, Σc²)
    pairs on the LAST axis (flat single-chip vector, flat per-chip slab
    inside shard_map, or [n_parts, 2L] assembled slabs); only the even
    (Σc) entries are read — convergence therefore composes with
    ``score_squares=False`` and ``sd_mode="batch"`` alike.

    ``mv`` counts enabled moves since the last explicit batch end; a
    batch completes when ``mv % batch_moves == 0`` (or always, with
    ``force=True`` — the explicit ``end_batch()`` path, which also
    resets the cadence counter).  ``enable`` gates the whole fold
    (device-resident 0/1 scalar): the partitioned facade passes 0 for
    initial-search and escalation re-walk dispatches so they never
    advance the batch cadence.

    Returns ``((snap', sumsq', nb', mv'), summary_vec)``.  The checks
    read ``flux`` and never write it.
    """
    even = flux[..., 0::2]
    if force:
        mv_new = mv * 0
        b_end = nb >= 0  # device-varying all-True in nb's shape
    else:
        en = (
            jnp.int32(1)
            if enable is None
            else enable.astype(jnp.int32)
        )
        mv_new = mv + en
        b_end = (en != 0) & (mv_new % batch_moves == 0)
    gate = b_end[..., None] if even.ndim > b_end.ndim else b_end
    delta = even - snap
    sumsq = jnp.where(gate, sumsq + delta * delta, sumsq)
    snap = jnp.where(gate, even, snap)
    nb = nb + b_end.astype(nb.dtype)
    return (snap, sumsq, nb, mv_new), conv_reduce(
        snap, sumsq, nb, rel_err_target
    )


@functools.partial(
    jax.jit,
    static_argnames=("rel_err_target",),
    donate_argnames=("snap", "sumsq"),
)
def end_batch_fold(flux, snap, sumsq, nb, mv, *, rel_err_target):
    """The explicit ``tally.end_batch()`` program: unconditionally close
    the current batch (whatever the ``batch_moves`` cadence says), reset
    the cadence counter, and return the fresh summary vector.  One tiny
    dispatch + one [CONV_LEN] fetch — an API call, not the move loop."""
    return fold_and_reduce(
        flux, snap, sumsq, nb, mv,
        batch_moves=1, rel_err_target=rel_err_target, force=True,
    )


# --------------------------------------------------------------------- #
# Host-side views
# --------------------------------------------------------------------- #
def conv_to_dict(vec) -> dict:
    """Named host view of one summary vector (single-chip facades)."""
    v = np.asarray(vec, np.float64)
    if v.shape != (CONV_LEN,):
        raise ValueError(
            f"expected a [{CONV_LEN}] convergence vector, got {v.shape}"
        )
    return {
        "n_batches": int(v[CONV_IDX["n_batches"]]),
        "scored": int(v[CONV_IDX["scored"]]),
        "sum_rel_err": float(v[CONV_IDX["sum_rel_err"]]),
        "max_rel_err": float(v[CONV_IDX["max_rel_err"]]),
        "converged": int(v[CONV_IDX["converged"]]),
    }


def reduce_chip_conv(mat) -> dict:
    """Aggregate per-chip [n_parts, CONV_LEN] partials into the run-level
    dict: counts and sums add (each bin is owned by exactly one chip),
    ``max_rel_err`` maxes, ``n_batches`` is replicated (max guards a
    ragged read)."""
    m = np.asarray(mat, np.float64)
    if m.ndim != 2 or m.shape[1] != CONV_LEN:
        raise ValueError(
            f"expected [n_parts, {CONV_LEN}] chip partials, got {m.shape}"
        )
    return {
        "n_batches": int(m[:, CONV_IDX["n_batches"]].max(initial=0)),
        "scored": int(m[:, CONV_IDX["scored"]].sum()),
        "sum_rel_err": float(m[:, CONV_IDX["sum_rel_err"]].sum()),
        "max_rel_err": float(m[:, CONV_IDX["max_rel_err"]].max(initial=0)),
        "converged": int(m[:, CONV_IDX["converged"]].sum()),
    }


def host_relative_error(snap, sumsq, nb: int) -> np.ndarray:
    """Per-bin relative error on HOST float64 — the same estimator the
    fused reduction computes, exposed for ``tally.relative_error()`` and
    the VTK uncertainty export (and pinned against an independent NumPy
    oracle in tests/test_convergence.py).  Unscored bins report 0;
    scored bins with fewer than 2 batches report 1."""
    s1 = np.asarray(snap, np.float64)
    s2 = np.asarray(sumsq, np.float64)
    n = int(nb)
    scored = s1 > 0
    if n < 2:
        return np.where(scored, 1.0, 0.0)
    var_num = np.maximum(n * s2 - s1 * s1, 0.0)
    rel = np.sqrt(var_num / (n - 1)) / np.where(scored, s1, 1.0)
    return np.where(scored, rel, 0.0)


# --------------------------------------------------------------------- #
# Monitor
# --------------------------------------------------------------------- #
class ConvergenceMonitor:
    """Folds per-move convergence summaries into gauges, per-batch
    flight records, and the ``converged()`` early-stop answer.

    One instance per tally (like TallyTelemetry, which it feeds): the
    gauge families land in the tally's private registry so the live
    scrape endpoint (obs/exporter.py) and ``telemetry()`` see them
    without any cross-tally interleaving.
    """

    def __init__(
        self,
        telemetry,
        *,
        rel_err_target: float,
        converged_fraction: float,
        batch_moves: int,
    ):
        self.telemetry = telemetry
        self.rel_err_target = float(rel_err_target)
        self.converged_fraction = float(converged_fraction)
        self.batch_moves = int(batch_moves)
        r = telemetry.registry
        self._g_max = r.gauge(
            "pumi_rel_err_max",
            "max per-bin relative error over scored tally bins",
        )
        self._g_mean = r.gauge(
            "pumi_rel_err_mean",
            "mean per-bin relative error over scored tally bins",
        )
        self._g_frac = r.gauge(
            "pumi_converged_fraction",
            "fraction of scored tally bins with relative error at or "
            "below TallyConfig.rel_err_target",
        )
        self._g_fom = r.gauge(
            "pumi_fom",
            "figure of merit 1/(rel_err_mean^2 * tally_seconds) — "
            "constant once a run is variance-dominated",
        )
        self._c_batches = r.counter(
            "pumi_batches_total",
            "statistical batches completed (batch_moves cadence plus "
            "explicit end_batch calls)",
        )
        self._last: dict = {}
        self._batches_seen = 0

    # ------------------------------------------------------------------ #
    def update(self, fields: dict, seconds: float) -> dict:
        """Fold one summary (conv_to_dict / reduce_chip_conv output).
        ``seconds`` is the cumulative tally wall-clock driving the FOM.
        Emits a flight-recorder record per COMPLETED batch (the per-move
        cadence stays in the walk records)."""
        nb = int(fields["n_batches"])
        scored = int(fields["scored"])
        mean = fields["sum_rel_err"] / scored if scored else 0.0
        frac = fields["converged"] / scored if scored else 0.0
        fom = (
            1.0 / (mean * mean * seconds)
            if mean > 0 and seconds > 0
            else 0.0
        )
        self._g_max.set(float(fields["max_rel_err"]))
        self._g_mean.set(mean)
        self._g_frac.set(frac)
        self._g_fom.set(fom)
        self._last = {
            "n_batches": nb,
            "scored": scored,
            "rel_err_mean": mean,
            "rel_err_max": float(fields["max_rel_err"]),
            "converged_fraction": frac,
            "fom": fom,
            "seconds": float(seconds),
        }
        if nb > self._batches_seen:
            self._c_batches.inc(nb - self._batches_seen)
            self._batches_seen = nb
            self.telemetry.recorder.record(
                "convergence",
                batch=nb,
                scored=scored,
                rel_err_mean=round(mean, 9),
                rel_err_max=round(float(fields["max_rel_err"]), 9),
                converged_fraction=round(frac, 6),
                fom=round(fom, 3),
            )
        return self._last

    @property
    def converged(self) -> bool:
        """True once at least 2 batches exist, something scored, and the
        converged fraction has reached ``converged_fraction``."""
        d = self._last
        return bool(
            d
            and d["n_batches"] >= 2
            and d["scored"] > 0
            and d["converged_fraction"] >= self.converged_fraction
        )

    def reset(self) -> None:
        """Forget the statistical history (checkpoint restore / rollback
        re-bases the batch accumulators — see the facades'
        ``_reset_convergence``)."""
        self._last = {}
        self._batches_seen = 0
        for g in (self._g_max, self._g_mean, self._g_frac, self._g_fom):
            g.set(0.0)

    def snapshot(self) -> dict:
        """The ``telemetry()["convergence"]`` payload."""
        out = {
            "enabled": True,
            "rel_err_target": self.rel_err_target,
            "converged_fraction_target": self.converged_fraction,
            "batch_moves": self.batch_moves,
            "converged": self.converged,
        }
        out.update(
            self._last
            or {
                "n_batches": 0,
                "scored": 0,
                "rel_err_mean": 0.0,
                "rel_err_max": 0.0,
                "converged_fraction": 0.0,
                "fom": 0.0,
                "seconds": 0.0,
            }
        )
        return out
