"""pumiumtally_tpu — TPU-native Monte Carlo track-length tallies on
unstructured tetrahedral meshes (JAX/XLA/Pallas).

From-scratch framework with the capabilities of OpenMCNP/PumiUMTally
(see SURVEY.md): takes particle origin→destination batches from a Monte
Carlo transport driver, ray-walks each particle through a tet mesh, scores
segment_length × weight per element and energy group, handles domain- and
material-boundary stops, normalizes by element volume, and writes VTK.
"""

from .api import PumiTally
from .parallel.mesh_partition import (
    MeshPartition,
    assemble_global_flux,
    partition_mesh,
)
from .parallel.partitioned_api import PartitionedTally
from .core.state import ParticleState, make_particle_state
from .core.tally import make_flux, normalize_flux, reaction_rate
from .mesh.box import build_box, build_box_arrays
from .mesh.core import TetMesh
from .integrity import (
    DispatchTimeoutError,
    FatalIntegrityViolation,
    IntegrityViolation,
    TransientIntegrityViolation,
)
from .mesh.io import load_mesh, save_npz
from .models.pipeline import StreamingTallyPipeline
from .models.transport import Material, SyntheticTransport
from .obs import FlightRecorder, MetricsExporter, MetricsRegistry
from .ops.source import SourceParams
from .ops.walk import trace, TraceResult
from .resilience import CheckpointStore, FaultInjector, ResilientRunner
from .utils.config import TallyConfig
from .utils.timing import TallyTimes

__version__ = "0.1.0"

__all__ = [
    "PumiTally",
    "PartitionedTally",
    "MeshPartition",
    "partition_mesh",
    "assemble_global_flux",
    "ParticleState",
    "make_particle_state",
    "make_flux",
    "normalize_flux",
    "reaction_rate",
    "build_box",
    "build_box_arrays",
    "TetMesh",
    "load_mesh",
    "save_npz",
    "StreamingTallyPipeline",
    "Material",
    "SyntheticTransport",
    "MetricsRegistry",
    "MetricsExporter",
    "FlightRecorder",
    "ResilientRunner",
    "CheckpointStore",
    "FaultInjector",
    "IntegrityViolation",
    "TransientIntegrityViolation",
    "FatalIntegrityViolation",
    "DispatchTimeoutError",
    "SourceParams",
    "trace",
    "TraceResult",
    "TallyConfig",
    "TallyTimes",
    "__version__",
]
