#pragma once
#include "Omega_h_file.hpp"
