// Minimal Omega_h API stub so native/osh2npz.cpp's npz-emitting pipeline
// can be compiled and exercised WITHOUT the real Omega_h (absent in this
// environment). The stub "reads" a fixed 2-tet mesh regardless of path;
// tests/test_osh.py::test_osh2npz_emitter_roundtrip then checks numpy can
// load the produced .npz bit-exactly. Only the symbols osh2npz.cpp
// touches exist here — this is NOT an Omega_h reimplementation.
#pragma once
#include <cstdint>
#include <string>
#include <vector>

namespace Omega_h {

using Real = double;
using LO = int32_t;
using ClassId = int32_t;
enum { VERT = 0, REGION = 3 };

template <typename T>
struct HostRead {
  std::vector<T> v;
  HostRead() = default;
  explicit HostRead(std::vector<T> x) : v(std::move(x)) {}
  const T* data() const { return v.data(); }
  T operator[](int64_t i) const { return v[static_cast<size_t>(i)]; }
};

struct Adj {
  std::vector<LO> ab2b;
};

struct CommPtr {};

struct Mesh {
  int dim() const { return 3; }
  int64_t nverts() const { return 5; }
  int64_t nelems() const { return 2; }
  bool has_tag(int, const std::string& name) const {
    return name == "class_id";
  }
  std::vector<Real> coords() const {
    return {0, 0, 0, 1, 0, 0, 0, 1, 0, 0, 0, 1, 1, 1, 1};
  }
  Adj ask_down(int, int) const { return Adj{{0, 1, 2, 3, 1, 2, 3, 4}}; }
  template <typename T>
  std::vector<T> get_array(int, const std::string&) const {
    return {7, 9};
  }
};

struct Library {
  Library(int*, char***) {}
  CommPtr world() { return {}; }
};

namespace binary {
inline Mesh read(const std::string&, CommPtr) { return Mesh{}; }
}  // namespace binary

// HostRead over the plain vectors the stub hands out.
template <typename T>
HostRead<T> make_host_read(std::vector<T> v) {
  return HostRead<T>(std::move(v));
}

}  // namespace Omega_h
