"""Profiler integration: a trace capture around real facade work must
produce trace artifacts, and annotations/memory stats must not throw."""
from __future__ import annotations

import glob
import os

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.utils.profiling import (
    annotate,
    device_memory_stats,
    profile_trace,
)


@pytest.mark.slow
def test_profile_trace_writes_artifacts(tmp_path):
    logdir = str(tmp_path / "trace")
    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
    t = PumiTally(mesh, 8, TallyConfig(tolerance=1e-6))
    rng = np.random.default_rng(0)
    with profile_trace(logdir):
        with annotate("init"):
            t.initialize_particle_location(
                rng.uniform(0.1, 0.9, (8, 3)).ravel()
            )
        with annotate("move"):
            t.move_to_next_location(
                rng.uniform(0.1, 0.9, (8, 3)),
                np.ones(8, np.int8),
                np.ones(8),
                np.zeros(8, np.int32),
                np.full(8, -1, np.int32),
            )
    found = glob.glob(
        os.path.join(logdir, "**", "*.xplane.pb"), recursive=True
    ) + glob.glob(os.path.join(logdir, "**", "*.trace*"), recursive=True)
    assert found, f"no trace artifacts under {logdir}"


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    for rec in stats.values():
        for v in rec.values():
            assert isinstance(v, int)
