"""Streaming pipeline: flux must equal the sum of sequentially traced
batches, and per-batch outputs must come back in submission order."""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import TallyConfig, build_box, make_flux
from pumiumtally_tpu.models.pipeline import StreamingTallyPipeline
from pumiumtally_tpu.ops.walk import trace_impl


def _batches(mesh, n, k, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(k):
        elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
        origin = np.asarray(mesh.centroids())[elem]
        dest = rng.uniform(-0.05, 1.05, (n, 3))
        weight = rng.uniform(0.5, 2.0, n)
        group = rng.integers(0, 2, n).astype(np.int32)
        out.append((origin, dest, elem, weight, group))
    return out


def test_pipeline_matches_sequential():
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    cfg = TallyConfig(n_groups=2, tolerance=1e-6)
    batches = _batches(mesh, 40, 5)

    pipe = StreamingTallyPipeline(mesh, cfg, depth=2)
    for origin, dest, elem, weight, group in batches:
        pipe.submit(origin, dest, elem, weight, group)
    flux = pipe.finish()

    ref = make_flux(mesh.ntet, 2, cfg.dtype)
    ref_positions = []
    for origin, dest, elem, weight, group in batches:
        n = len(elem)
        r = trace_impl(
            mesh,
            jnp.asarray(origin, cfg.dtype),
            jnp.asarray(dest, cfg.dtype),
            jnp.asarray(elem),
            jnp.ones(n, bool),
            jnp.asarray(weight, cfg.dtype),
            jnp.asarray(group),
            jnp.full(n, -1, jnp.int32),
            ref,
            initial=False,
            max_crossings=mesh.ntet + 64,
            tolerance=cfg.tolerance,
        )
        ref = r.flux
        ref_positions.append(np.asarray(r.position))

    np.testing.assert_allclose(flux, np.asarray(ref), atol=1e-5)
    got = list(pipe.results())
    assert [b.index for b in got] == [0, 1, 2, 3, 4]
    for b, expect in zip(got, ref_positions):
        np.testing.assert_allclose(b.position, expect, atol=1e-6)
        assert b.all_done


@pytest.mark.slow
def test_pipeline_no_outputs_mode():
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    pipe = StreamingTallyPipeline(
        mesh, TallyConfig(n_groups=2, tolerance=1e-6),
        depth=3, want_outputs=False,
    )
    for origin, dest, elem, weight, group in _batches(mesh, 24, 4, seed=2):
        pipe.submit(origin, dest, elem, weight, group)
    flux = pipe.finish()
    assert flux[..., 0].sum() > 0
    assert list(pipe.results()) == []


@pytest.mark.slow
def test_pipeline_records_xpoints_when_configured():
    """TallyConfig.record_xpoints must apply on the pipeline path too —
    BatchResult carries the crossing points (None when the flag is off)."""
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    pipe = StreamingTallyPipeline(
        mesh, TallyConfig(n_groups=2, tolerance=1e-6, record_xpoints=8),
        depth=2,
    )
    for origin, dest, elem, weight, group in _batches(mesh, 24, 2, seed=5):
        pipe.submit(origin, dest, elem, weight, group)
    pipe.finish()
    got = list(pipe.results())
    assert got and all(b.xpoints is not None for b in got)
    for b in got:
        assert b.xpoints.shape == (24, 8, 3)
        assert b.n_xpoints.shape == (24,)
        # Crossing counts are genuine: some particles cross, and each
        # recorded point differs from the one before it.
        assert (b.n_xpoints > 0).any()
    off = StreamingTallyPipeline(
        mesh, TallyConfig(n_groups=2, tolerance=1e-6), depth=2
    )
    for origin, dest, elem, weight, group in _batches(mesh, 24, 1, seed=6):
        off.submit(origin, dest, elem, weight, group)
    off.finish()
    assert all(b.xpoints is None for b in off.results())
