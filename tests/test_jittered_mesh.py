"""Walk robustness on irregular tets: a box mesh with jittered interior
vertices (non-uniform, near-degenerate elements) must still conserve
track length exactly and terminate every walk — the closest thing to a
production mesh this environment can synthesize."""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import make_flux
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.ops.walk import trace_impl


def _jittered_mesh(nx, jitter, seed, dtype):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, nx, nx, nx)
    rng = np.random.default_rng(seed)
    h = 1.0 / nx
    interior = (
        (coords > 1e-9).all(axis=1) & (coords < 1 - 1e-9).all(axis=1)
    )
    coords = coords.copy()
    coords[interior] += rng.uniform(
        -jitter * h, jitter * h, (interior.sum(), 3)
    )
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    return TetMesh.from_numpy(coords, tets, cid, dtype=dtype)


@pytest.mark.parametrize("dtype,tol,atol", [
    (jnp.float64, 1e-8, 1e-9),
    (jnp.float32, 1e-6, 5e-4),
])
def test_jittered_mesh_conserves_tracklength(dtype, tol, atol):
    mesh = _jittered_mesh(6, 0.25, seed=11, dtype=dtype)
    assert float(np.asarray(mesh.volumes).min()) > 0  # still valid tets
    n = 512
    rng = np.random.default_rng(4)
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], dtype
    )
    dest = jnp.asarray(rng.uniform(0.02, 0.98, (n, 3)), dtype)
    weight = jnp.ones(n, dtype)
    r = trace_impl(
        mesh, origin, dest, elem, jnp.ones(n, bool), weight,
        jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 1, dtype),
        initial=False, max_crossings=mesh.ntet + 8, tolerance=tol,
    )
    assert bool(np.asarray(r.done).all()), "walk must terminate everywhere"
    # Material stops clip mid-flight, so conservation compares scored
    # flux against ACTUAL path walked (origin -> final position).
    path = np.linalg.norm(
        np.asarray(r.position) - np.asarray(origin), axis=1
    ).sum()
    tallied = float(np.asarray(r.flux)[..., 0].sum())
    assert tallied == pytest.approx(path, abs=max(atol, 1e-7 * path))
    # Every stop is accounted for: reached (-1 kept from material update),
    # domain exit (-1), or a material stop carrying a real region id.
    mats = np.asarray(r.material_id)
    assert np.isin(mats, (-1, 0, 1)).all()
    assert (mats >= 0).any()  # some rays crossed the material plane


def test_jittered_mesh_packed_equals_unpacked():
    """The packed/unpacked bodies must agree bit-for-bit on irregular
    geometry too, not just on the uniform box of test_walk_variants."""
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 5, 5, 5)
    rng = np.random.default_rng(3)
    interior = (
        (coords > 1e-9).all(axis=1) & (coords < 1 - 1e-9).all(axis=1)
    )
    coords = coords.copy()
    coords[interior] += rng.uniform(-0.06, 0.06, (interior.sum(), 3))
    cid = (coords[tets].mean(axis=1)[:, 2] > 0.5).astype(np.int32)
    mesh_p = TetMesh.from_numpy(coords, tets, cid, dtype=jnp.float32)
    mesh_u = TetMesh.from_numpy(
        coords, tets, cid, dtype=jnp.float32, packed=False
    )
    n = 256
    elem = jnp.asarray(rng.integers(0, mesh_p.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh_p.centroids())[np.asarray(elem)], jnp.float32
    )
    dest = jnp.asarray(rng.uniform(-0.05, 1.05, (n, 3)), jnp.float32)
    args = (
        origin, dest, elem, jnp.ones(n, bool),
        jnp.asarray(rng.uniform(0.5, 2.0, n), jnp.float32),
        jnp.asarray(rng.integers(0, 2, n), jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    kw = dict(initial=False, max_crossings=mesh_p.ntet + 8, tolerance=1e-6)
    a = trace_impl(mesh_p, *args, make_flux(mesh_p.ntet, 2, jnp.float32), **kw)
    b = trace_impl(mesh_u, *args, make_flux(mesh_u.ntet, 2, jnp.float32), **kw)
    np.testing.assert_array_equal(np.asarray(a.flux), np.asarray(b.flux))
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(b.elem))
    np.testing.assert_array_equal(
        np.asarray(a.material_id), np.asarray(b.material_id)
    )
    assert int(a.n_segments) == int(b.n_segments)


def test_tangled_mesh_rejected_at_build():
    """Overlapping (tangled) geometry — positive volumes but a vertex
    pushed through a neighbor face — must be rejected at mesh build, not
    walked forever: no face-adjacency walk can terminate on it."""
    with pytest.raises(ValueError, match="tangled"):
        _jittered_mesh(6, 0.35, seed=11, dtype=jnp.float64)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_walk_termination_and_conservation(seed):
    """Fuzz: random jittered meshes × random + adversarial rays
    (axis-aligned, face-grazing, corner-aimed) must always terminate and
    conserve track length in f32 — the dtype where degeneracies bite."""
    rng = np.random.default_rng(100 + seed)
    nx = int(rng.integers(3, 7))
    jitter = float(rng.uniform(0.05, 0.25))
    mesh = _jittered_mesh(nx, jitter, seed=200 + seed, dtype=jnp.float32)
    n = 384
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = np.asarray(mesh.centroids())[np.asarray(elem)]
    dest = rng.uniform(0.02, 0.98, (n, 3))
    # Adversarial destinations: axis-aligned rays (graze structured
    # faces), rays aimed at mesh vertices (corner crossings), and
    # destinations just outside the domain (boundary clips).
    dest[:96, 1:] = origin[:96, 1:]          # pure-x rays
    verts = np.asarray(mesh.coords)
    vidx = rng.integers(0, verts.shape[0], 96)
    dest[96:192] = verts[vidx] + rng.normal(0, 1e-7, (96, 3))
    dest[192:288] = rng.uniform(1.0, 1.1, (96, 3))  # outside
    r = trace_impl(
        mesh,
        jnp.asarray(origin, jnp.float32),
        jnp.asarray(dest, jnp.float32),
        elem,
        jnp.ones(n, bool),
        jnp.ones(n, jnp.float32),
        jnp.zeros(n, jnp.int32),
        jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 1, jnp.float32),
        initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-6,
    )
    assert bool(np.asarray(r.done).all()), (
        f"walk truncated (nx={nx}, jitter={jitter:.3f})"
    )
    path = np.linalg.norm(
        np.asarray(r.position) - origin, axis=1
    ).sum()
    tallied = float(np.asarray(r.flux)[..., 0].sum())
    assert tallied == pytest.approx(path, abs=max(5e-4, 1e-5 * path))


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_truncate_mode_fails_safe(seed):
    """robust=False (reference-parity truncate mode) on adversarial rays:
    a degeneracy may legitimately truncate the walk (done=False — the
    reference prints "Not all particles are found"), but it must FAIL
    SAFE: finite positions inside the domain envelope, in-range parent
    elements, finite flux, and the conservation ledger still equal to
    the net displacement (movement never leaves the ray)."""
    rng = np.random.default_rng(300 + seed)
    mesh = _jittered_mesh(5, 0.2, seed=400 + seed, dtype=jnp.float32)
    n = 256
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = np.asarray(mesh.centroids())[np.asarray(elem)]
    dest = rng.uniform(0.02, 0.98, (n, 3))
    dest[:64, 1:] = origin[:64, 1:]  # grazing pure-x rays
    verts = np.asarray(mesh.coords)
    dest[64:128] = verts[rng.integers(0, verts.shape[0], 64)] + rng.normal(
        0, 1e-7, (64, 3)
    )
    r = trace_impl(
        mesh,
        jnp.asarray(origin, jnp.float32),
        jnp.asarray(dest, jnp.float32),
        elem,
        jnp.ones(n, bool),
        jnp.ones(n, jnp.float32),
        jnp.zeros(n, jnp.int32),
        jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 1, jnp.float32),
        initial=False, max_crossings=192, tolerance=1e-6, robust=False,
    )
    pos = np.asarray(r.position)
    assert np.isfinite(pos).all()
    assert (pos > -0.01).all() and (pos < 1.01).all()
    el = np.asarray(r.elem)
    assert ((el >= 0) & (el < mesh.ntet)).all()
    flux = np.asarray(r.flux)
    assert np.isfinite(flux).all() and (flux >= 0).all()
    # Ledger: scored length == net displacement per particle, truncated
    # or not (generous f32 envelope for ~200-crossing accumulation).
    tl = np.asarray(r.track_length)
    disp = np.linalg.norm(pos - origin, axis=1)
    np.testing.assert_allclose(tl, disp, atol=2e-4)
