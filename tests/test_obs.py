"""Telemetry subsystem (obs/): registry semantics, flight-recorder JSONL
golden schema, and the on-device walk stats vector against a
hand-checked small-mesh oracle plus the independent intersection-point
recorder."""
from __future__ import annotations

import json

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, build_box, make_flux
from pumiumtally_tpu.obs import (
    IDX,
    WALK_STATS_FIELDS,
    WALK_STATS_LEN,
    FlightRecorder,
    MetricsRegistry,
    reduce_chip_stats,
    stats_to_dict,
)
from pumiumtally_tpu.ops.walk import trace


# --------------------------------------------------------------------- #
# Registry
# --------------------------------------------------------------------- #
def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("hits", "help text")
    c.inc()
    c.inc(4)
    assert c.value() == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    # Same name returns the same family; values persist.
    assert reg.counter("hits") is c


def test_labeled_series_are_independent():
    reg = MetricsRegistry()
    c = reg.counter("reqs")
    c.inc(2, device="tpu:0")
    c.inc(3, device="tpu:1")
    c.inc(7)
    assert c.value(device="tpu:0") == 2
    assert c.value(device="tpu:1") == 3
    assert c.value() == 7
    snap = reg.snapshot()["reqs"]
    assert snap["type"] == "counter"
    assert len(snap["series"]) == 3


def test_gauge_set_and_inc():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert g.value() == 13


def test_histogram_cumulative_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    s = h.value()
    assert s["count"] == 5
    assert s["sum"] == pytest.approx(56.05)
    # Cumulative: <=0.1 -> 1, <=1.0 -> 3, <=10.0 -> 4 (the 50.0 only in +Inf).
    assert s["buckets"] == [1, 3, 4]


def test_type_mismatch_raises():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_prometheus_exposition():
    reg = MetricsRegistry()
    reg.counter("seg_total", "segments").inc(9, kind="move")
    reg.gauge("occ").set(0.75)
    reg.histogram("lat", buckets=(1.0,)).observe(0.5)
    text = reg.render_prometheus()
    assert "# TYPE seg_total counter" in text
    assert 'seg_total{kind="move"} 9' in text
    assert "occ 0.75" in text
    assert 'lat_bucket{le="1.0"} 1' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_sum 0.5" in text
    assert "lat_count 1" in text


# --------------------------------------------------------------------- #
# Flight recorder + JSONL golden schema
# --------------------------------------------------------------------- #
def test_recorder_ring_and_seq():
    rec = FlightRecorder(capacity=3)
    for i in range(5):
        rec.record("move", move=i)
    assert len(rec) == 3
    assert rec.total_recorded == 5
    assert [r["move"] for r in rec.records()] == [2, 3, 4]
    assert [r["seq"] for r in rec.tail(2)] == [3, 4]


def test_recorder_jsonl_sink_schema(tmp_path, monkeypatch):
    """Golden schema of the JSONL record: the log-formatter envelope
    (ts/level/msg) plus the recorder fields, one JSON object per line."""
    path = tmp_path / "metrics.jsonl"
    monkeypatch.setenv("PUMI_TPU_METRICS", f"jsonl:{path}")
    rec = FlightRecorder()
    rec.record("move", move=1, segments=42, crossings=7)
    rec.record("memory", phase="vtk_write", devices={})
    lines = path.read_text().strip().split("\n")
    assert len(lines) == 2
    first = json.loads(lines[0])
    assert set(first) == {
        "ts", "level", "msg", "seq", "kind", "move", "segments",
        "crossings",
    }
    assert first["level"] == "metric"
    assert first["msg"] == "move" and first["kind"] == "move"
    assert first["segments"] == 42
    second = json.loads(lines[1])
    assert second["kind"] == "memory" and second["phase"] == "vtk_write"


def test_no_sink_is_silent(tmp_path, monkeypatch):
    monkeypatch.delenv("PUMI_TPU_METRICS", raising=False)
    FlightRecorder().record("move", move=0)  # must not raise or write


def test_unwritable_sink_never_crashes(monkeypatch, capsys):
    """Metric emission is best-effort: a typo'd PUMI_TPU_METRICS path
    must warn (once) and keep the run alive, not raise on every move."""
    monkeypatch.setenv(
        "PUMI_TPU_METRICS", "jsonl:/nonexistent_dir_pumi/m.jsonl"
    )
    rec = FlightRecorder()
    rec.record("move", move=0)
    rec.record("move", move=1)
    err = capsys.readouterr().err
    assert err.count("unwritable") == 1  # warned exactly once
    assert rec.total_recorded == 2  # ring still records


# --------------------------------------------------------------------- #
# On-device walk stats: hand-checked small-mesh oracle
# --------------------------------------------------------------------- #
N_GROUPS = 2


@pytest.fixture(scope="module")
def small_mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2, dtype=jnp.float64)


def _trace(mesh, origin, dest, elem, in_flight=None, **kw):
    n = origin.shape[0]
    if in_flight is None:
        in_flight = jnp.ones(n, bool)
    kw.setdefault("initial", False)
    kw.setdefault("max_crossings", mesh.ntet + 64)
    kw.setdefault("tolerance", 1e-8)
    kw.setdefault("n_groups", N_GROUPS)
    return trace(
        mesh,
        jnp.asarray(origin, jnp.float64),
        jnp.asarray(dest, jnp.float64),
        jnp.asarray(elem, jnp.int32),
        in_flight,
        jnp.ones(n, jnp.float64),
        jnp.zeros(n, jnp.int32),
        jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, N_GROUPS, jnp.float64),
        **kw,
    )


def test_stats_vector_schema_length(small_mesh):
    cen = np.asarray(small_mesh.centroids())
    r = _trace(small_mesh, cen[:1], cen[:1] + 1e-3, np.array([0]))
    assert r.stats.shape == (WALK_STATS_LEN,)
    assert tuple(IDX[f] for f in WALK_STATS_FIELDS) == tuple(
        range(WALK_STATS_LEN)
    )


def test_stats_zero_crossing_walk(small_mesh):
    """Hand-checked: a destination inside the origin element crosses no
    boundary and scores exactly one segment; a parked lane contributes
    nothing at all."""
    cen = np.asarray(small_mesh.centroids())
    origin = cen[[0, 0]]
    dest = origin + np.array([[1e-4, 0, 0], [0.3, 0.3, 0.3]])
    r = _trace(
        small_mesh, origin, dest, np.zeros(2, np.int32),
        in_flight=jnp.asarray([True, False]),
    )
    d = stats_to_dict(r.stats)
    assert d["crossings"] == 0  # lane 0 stays in its element
    assert d["max_crossings"] == 0
    assert d["segments"] == 1  # destination-reach segment of lane 0 only
    assert d["truncated"] == 0  # the parked lane is done, not truncated
    assert d["chase_hops"] == 0
    assert d["occupancy"] is None  # no compaction configured


def test_stats_match_recorded_crossings(small_mesh):
    """The stats counters must agree with the independently recorded
    intersection points (record_xpoints) and result scalars, lane by
    lane aggregated: total/max crossings, segments, loop iterations."""
    rng = np.random.default_rng(11)
    n = 32
    elem = rng.integers(0, small_mesh.ntet, n).astype(np.int32)
    origin = np.asarray(small_mesh.centroids())[elem]
    dest = np.clip(origin + rng.normal(0, 0.4, (n, 3)), 0.02, 0.98)
    r = _trace(small_mesh, origin, dest, elem, record_xpoints=64)
    d = stats_to_dict(r.stats)
    counts = np.asarray(r.n_xpoints)
    assert d["crossings"] == counts.sum()
    assert d["max_crossings"] == counts.max()
    assert d["segments"] == int(r.n_segments)
    assert d["loop_iters"] == int(r.n_crossings)
    assert d["truncated"] == int(np.sum(~np.asarray(r.done))) == 0
    assert d["chase_hops"] == 0  # clean box mesh: no recovery expected


def test_stats_truncation_counter(small_mesh):
    """max_crossings=1 truncates every walk that needed more than one
    crossing; the on-device counter must equal the host scan of done."""
    rng = np.random.default_rng(5)
    n = 16
    elem = rng.integers(0, small_mesh.ntet, n).astype(np.int32)
    origin = np.asarray(small_mesh.centroids())[elem]
    dest = np.clip(origin + rng.normal(0, 0.5, (n, 3)), 0.02, 0.98)
    r = _trace(small_mesh, origin, dest, elem, max_crossings=1)
    d = stats_to_dict(r.stats)
    n_undone = int(np.sum(~np.asarray(r.done)))
    assert n_undone > 0  # the workload must actually truncate
    assert d["truncated"] == n_undone


def test_stats_compaction_occupancy_and_flux_parity(small_mesh):
    """Compaction rounds fill the occupancy accumulator; the scored flux
    (up to fp summation order — schedules group the scatter adds
    differently, ~1e-15 in f64) and every crossing counter match the
    flat loop."""
    rng = np.random.default_rng(7)
    n = 64
    elem = rng.integers(0, small_mesh.ntet, n).astype(np.int32)
    origin = np.asarray(small_mesh.centroids())[elem]
    dest = np.clip(origin + rng.normal(0, 0.4, (n, 3)), 0.02, 0.98)
    r_flat = _trace(small_mesh, origin, dest, elem)
    r_cmp = _trace(
        small_mesh, origin, dest, elem, compact_stages=((1, 16),)
    )
    d_flat = stats_to_dict(r_flat.stats)
    d_cmp = stats_to_dict(r_cmp.stats)
    np.testing.assert_allclose(
        np.asarray(r_cmp.flux), np.asarray(r_flat.flux),
        rtol=1e-13, atol=1e-15,
    )
    for f in ("crossings", "max_crossings", "segments", "truncated"):
        assert d_cmp[f] == d_flat[f]
    assert d_flat["occ_slots"] == 0
    assert d_cmp["occ_slots"] > 0
    assert 0 < d_cmp["occupancy"] <= 1


def test_stats_knob_off(small_mesh):
    cen = np.asarray(small_mesh.centroids())
    r = _trace(small_mesh, cen[:4], cen[:4] + 0.1, np.zeros(4, np.int32),
               stats=False)
    assert r.stats is None


def test_reduce_chip_stats():
    m = np.zeros((2, WALK_STATS_LEN), np.int64)
    m[0, IDX["crossings"]] = 5
    m[1, IDX["crossings"]] = 7
    m[0, IDX["max_crossings"]] = 4
    m[1, IDX["max_crossings"]] = 9
    m[:, IDX["occ_active"]] = 1
    m[:, IDX["occ_slots"]] = 2
    d = reduce_chip_stats(m)
    assert d["crossings"] == 12
    assert d["max_crossings"] == 9
    assert d["occupancy"] == 0.5


# --------------------------------------------------------------------- #
# Facade telemetry
# --------------------------------------------------------------------- #
def _drive_tally(n=16, moves=2, **cfg_kw):
    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2, dtype=jnp.float64)
    cfg = TallyConfig(
        dtype=jnp.float64, n_groups=N_GROUPS, tolerance=1e-8, **cfg_kw
    )
    t = PumiTally(mesh, n, cfg)
    rng = np.random.default_rng(0)
    pos = rng.uniform(0.1, 0.9, (n, 3))
    t.initialize_particle_location(pos.ravel().copy())
    for _ in range(moves):
        dest = np.clip(pos + rng.normal(0, 0.2, (n, 3)), 0.02, 0.98)
        buf = dest.ravel().copy()
        t.move_to_next_location(
            buf, np.ones(n, np.int8), np.ones(n),
            np.zeros(n, np.int32), np.full(n, -1, np.int32),
        )
        pos = buf.reshape(n, 3)
    return t


def test_pumitally_telemetry_snapshot():
    t = _drive_tally(moves=3)
    snap = t.telemetry()
    assert snap["facade"] == "PumiTally"
    assert snap["totals"]["moves"] == 3
    assert snap["totals"]["segments"] == t.total_segments > 0
    assert snap["totals"]["truncated"] == 0
    kinds = [r["kind"] for r in snap["per_move"]]
    assert kinds.count("move") == 3
    assert "initial_search" in kinds
    assert "memory" in kinds  # construction phase boundary sample
    assert snap["times"]["n_moves"] == 3
    move_recs = [r for r in snap["per_move"] if r["kind"] == "move"]
    for r in move_recs:
        assert {"move", "seconds", "crossings", "segments", "truncated",
                "occupancy"} <= set(r)
    # Registry view agrees with the counters.
    m = snap["metrics"]
    assert m["pumi_moves_total"]["series"][0]["value"] == 3
    # Prometheus exposition renders without error and carries the totals.
    text = t.metrics.render_prometheus()
    assert "pumi_segments_total" in text


def test_pumitally_telemetry_jsonl_stream(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PUMI_TPU_METRICS", f"jsonl:{path}")
    t = _drive_tally(moves=2)
    recs = [json.loads(ln) for ln in path.read_text().strip().split("\n")]
    moves = [r for r in recs if r["kind"] == "move"]
    assert len(moves) == 2
    assert sum(r["segments"] for r in moves) == t.total_segments


def test_pumitally_walk_stats_off_falls_back():
    t = _drive_tally(moves=2, walk_stats=False)
    snap = t.telemetry()
    # No stats vector: segment totals still flow (result scalar), the
    # stats-derived counters stay zero.
    assert t.total_segments > 0
    assert snap["totals"]["moves"] == 2
    assert snap["totals"]["crossings"] == 0


def test_tally_times_per_move_report(capsys):
    from pumiumtally_tpu.utils.timing import TallyTimes

    tt = TallyTimes(total_time_to_tally=3.0, n_moves=4)
    tt.print_times()
    err = capsys.readouterr().err
    assert "tally_per_move" in err
    assert "0.75" in err
    assert "n_moves=4" in err
