"""Fleet observability plane contracts (obs/aggregate.py, obs/slo.py,
obs/profile.py + the serving wiring).

Contracts pinned here:

  * AGGREGATION — ``FleetAggregator.merge()`` sums counters across
    member registries per label-set, bucket-merges histograms, and
    keeps gauges per member under a ``member=`` label; the merge is
    DETERMINISTIC across member orderings (byte-identical Prometheus
    text), and cross-member type drift is a loud error.
  * SLO BURN — ``SLOEvaluator`` fires an alert only when EVERY window
    of a pair burns above the threshold, attributes the member with
    the largest bad-count delta, records one ``slo_breach`` flight
    record per rising edge, and clears the alert once the windows
    slide past the bad observations.
  * FLEETSTATS — the router snapshots {schema, fleet, slo, profile,
    metrics, router_metrics} atomically at construction (round zero)
    and again at close; ``fleetview --check`` accepts the directory;
    ``PUMI_TPU_FLEET_OBS=off`` runs the fleet bare (no /fleetz, no
    snapshot, no advisory).
  * TRACEPARENT — a W3C (or bare-hex) ``traceparent`` on POST /submit
    makes the job JOIN the caller's trace; the submit response carries
    ``trace_id`` (the dedup path returns the ORIGINAL trace);
    /progress rows carry ``trace_id``; malformed headers are 400s.
  * EXPORTER — ``/jobs`` caps at ``?limit=`` (default 500, newest
    first); concurrent ``/metrics`` + ``/fleetz`` scrapes during an
    active fleet run stay parseable with monotonic counters (the
    thread-safety contract).

Compile budget: the fast core (-m 'not slow') only submits (enqueue,
no quanta) or works on bare registries.  Everything draining real
quanta is marked slow and runs in CI's fleet-obs step.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from pumiumtally_tpu import TallyConfig, build_box
from pumiumtally_tpu.obs import (
    FLEETSTATS_FILE,
    FLEETSTATS_SCHEMA,
    FleetAggregator,
    FleetProfiler,
    MetricsRegistry,
    SLO,
    SLOEvaluator,
    default_slos,
    profile_mode,
    render_snapshot_prometheus,
)
from pumiumtally_tpu.serving import FleetRouter, TallyGateway
from pumiumtally_tpu.serving.gateway import parse_traceparent
from pumiumtally_tpu.serving.journal import request_to_json
from pumiumtally_tpu.serving.saturate import synthetic_requests

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(1, os.path.join(ROOT, "scripts"))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in (
        "PUMI_TPU_MEGASTEP", "PUMI_TPU_KERNEL", "PUMI_TPU_IO_PIPELINE",
        "PUMI_TPU_TUNING", "PUMI_TPU_AOT_FAULT", "PUMI_TPU_PROM_PORT",
        "PUMI_TPU_FAULTS", "PUMI_TPU_FLEET_OBS", "PUMI_TPU_PROFILE",
    ):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2)


def _cfg():
    return TallyConfig(tolerance=1e-6)


def _router(tmp_path, mesh, n_members=2, **kw):
    kw.setdefault("quantum_moves", 2)
    kw.setdefault("max_resident", 2)
    return FleetRouter(
        mesh, _cfg(), fleet_dir=str(tmp_path / "fleet"),
        n_members=n_members, bank=None, **kw,
    )


# --------------------------------------------------------------------- #
# Aggregation
# --------------------------------------------------------------------- #
def _seed_registries():
    a, b = MetricsRegistry(), MetricsRegistry()
    for r, n in ((a, 3), (b, 4)):
        r.counter("pumi_jobs_total", "jobs").inc(n, outcome="completed")
        r.gauge("pumi_queue_depth", "depth").set(n)
        h = r.histogram("pumi_job_e2e_seconds", "e2e")
        h.observe(0.002)
        h.observe(5.0)
    a.counter("pumi_jobs_total", "jobs").inc(1, outcome="poisoned")
    return a, b


def test_aggregator_merge_semantics():
    a, b = _seed_registries()
    agg = FleetAggregator(lambda: [("m0", a), ("m1", b)])
    snap = agg.merge()
    jobs = {
        tuple(sorted(e["labels"].items())): e["value"]
        for e in snap["pumi_jobs_total"]["series"]
    }
    # Counters: summed per label-set across members.
    assert jobs[(("outcome", "completed"),)] == 7
    assert jobs[(("outcome", "poisoned"),)] == 1
    # Gauges: one series per member, labeled.
    depth = {
        e["labels"]["member"]: e["value"]
        for e in snap["pumi_queue_depth"]["series"]
    }
    assert depth == {"m0": 3, "m1": 4}
    # Histograms: counts and sums fold, buckets stay cumulative.
    e2e = snap["pumi_job_e2e_seconds"]["series"][0]["value"]
    assert e2e["count"] == 4
    assert e2e["sum"] == pytest.approx(2 * (0.002 + 5.0))
    assert e2e["buckets"]["0.0025"] == 2
    assert e2e["buckets"]["5.0"] == 4


def test_aggregator_deterministic_across_member_orderings():
    a, b = _seed_registries()
    sources = [("m0", a), ("m1", b)]
    merges, texts = [], []
    for perm in itertools.permutations(sources):
        agg = FleetAggregator(lambda p=perm: list(p))
        merges.append(agg.merge())
        texts.append(agg.render_prometheus())
    assert merges[0] == merges[1]
    assert texts[0] == texts[1]
    # And renderable through the shared snapshot renderer (the
    # fleetview offline path).
    assert render_snapshot_prometheus(merges[0]) == texts[0]


def test_aggregator_type_drift_is_loud():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("pumi_thing", "x").inc()
    b.gauge("pumi_thing", "x").set(1)
    agg = FleetAggregator(lambda: [("m0", a), ("m1", b)])
    with pytest.raises(ValueError, match="pumi_thing"):
        agg.merge()


# --------------------------------------------------------------------- #
# SLO burn-rate evaluation
# --------------------------------------------------------------------- #
class _Recorder:
    def __init__(self):
        self.records = []

    def record(self, kind, **fields):
        self.records.append(dict(fields, kind=kind))


def test_slo_alert_fires_attributes_and_clears():
    slo = SLO(
        name="e2e", kind="latency", metric="pumi_job_e2e_seconds",
        threshold_s=1.0, objective=0.9, windows=((2.0, 4.0),),
    )
    regs = [MetricsRegistry(), MetricsRegistry()]
    hists = [
        r.histogram("pumi_job_e2e_seconds", "e2e") for r in regs
    ]
    rec = _Recorder()
    clock = itertools.count(start=0.0, step=1.0)
    ev = SLOEvaluator(
        (slo,), MetricsRegistry(), rec, clock=lambda: next(clock)
    )

    def members(alive=(True, True)):
        return [
            (i, f"m{i}", regs[i], alive[i]) for i in range(2)
        ]

    # Baseline: good observations only — no alert.
    hists[0].observe(0.01)
    hists[1].observe(0.01)
    for _ in range(5):
        assert ev.evaluate(members()) == {}
    # Member 1 turns bad: every window pair heats past burn 1.
    hists[1].observe(30.0)
    hists[1].observe(30.0)
    ev.evaluate(members())
    alert = ev.alerts["e2e"]
    assert alert["member"] == 1
    assert [r["kind"] for r in rec.records] == ["slo_breach"]
    assert rec.records[0]["slo"] == "e2e"
    assert rec.records[0]["member"] == 1
    assert ev.alerts_by_member() == {1: [alert]}
    # A still-breaching tick updates burns but records NO new edge.
    ev.evaluate(members())
    assert len(rec.records) == 1
    # The windows slide past the bad observations: alert clears.
    for _ in range(6):
        ev.evaluate(members())
    assert ev.alerts == {}
    assert ev.alerts_by_member() == {}


def test_slo_availability_burns_on_dead_member():
    slo = SLO(
        name="avail", kind="availability", objective=0.5,
        windows=((2.0, 3.0),),
    )
    clock = itertools.count(start=0.0, step=1.0)
    ev = SLOEvaluator(
        (slo,), MetricsRegistry(), clock=lambda: next(clock)
    )
    members = [(0, "m0", None, True), (1, "m1", None, False)]
    for _ in range(4):
        ev.evaluate(members)
    # Half the fleet down at objective 0.5 → burn exactly 1.0, which
    # does NOT exceed the default alert threshold (alert_burn=1.0).
    assert ev.alerts == {}
    members = [(0, "m0", None, False), (1, "m1", None, False)]
    for _ in range(3):
        ev.evaluate(members)
    assert "avail" in ev.alerts


def test_default_slos_are_wellformed():
    slos = default_slos()
    assert len({s.name for s in slos}) == len(slos) == 4
    with pytest.raises(ValueError, match="kind"):
        SLO(name="x", kind="nope", objective=0.5)
    with pytest.raises(ValueError, match="objective"):
        SLO(name="x", kind="availability", objective=1.5)
    with pytest.raises(ValueError, match="window"):
        SLO(name="x", kind="availability", objective=0.5,
            windows=((5.0, 2.0),))


# --------------------------------------------------------------------- #
# Profiling
# --------------------------------------------------------------------- #
def test_profile_mode_resolution(monkeypatch):
    assert profile_mode() == "off"
    monkeypatch.setenv("PUMI_TPU_PROFILE", "anomaly")
    assert profile_mode() == "anomaly"
    with pytest.raises(ValueError, match="bogus"):
        profile_mode("bogus")


def test_profiler_capture_gated_off_by_default(tmp_path):
    prof = FleetProfiler(
        MetricsRegistry(), journal_dir=str(tmp_path),
    )
    assert prof.status()["mode"] == "off"
    assert prof.on_alert({"slo": "e2e", "member": 0}) is False
    assert prof.status()["captures"] == []
    assert not os.path.exists(os.path.join(tmp_path, "profiles"))


# --------------------------------------------------------------------- #
# FLEETSTATS + the off switch
# --------------------------------------------------------------------- #
def test_fleetstats_written_from_round_zero(tmp_path, mesh):
    router = _router(tmp_path, mesh)
    try:
        path = router.fleetstats_path()
        assert os.path.basename(path) == FLEETSTATS_FILE
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["schema"] == FLEETSTATS_SCHEMA
        assert {m["member"] for m in doc["fleet"]["members"]} == {0, 1}
        assert [s["name"] for s in doc["slo"]["slos"]] == [
            s.name for s in default_slos()
        ]
        from fleetview import check_fleetstats, load_dir

        assert check_fleetstats(load_dir(router.journal.dir)) == []
    finally:
        router.close()
    # close() snapshots one last time; the picture outlives the router.
    from fleetview import check_fleetstats, load_dir

    assert check_fleetstats(load_dir(router.journal.dir)) == []


def test_fleet_obs_off_runs_bare(tmp_path, mesh, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_FLEET_OBS", "off")
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    router = _router(tmp_path, mesh)
    try:
        assert router.aggregator is None
        assert router.slo is None
        assert router.slo_alerts_by_member() == {}
        assert not os.path.exists(router.fleetstats_path())
        base = router._exporter.url.replace("/metrics", "")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/fleetz", timeout=5)
        assert err.value.code == 404
    finally:
        router.close()
    assert not os.path.exists(router.fleetstats_path())


def test_fleetz_mounted_and_taught(tmp_path, mesh, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    router = _router(tmp_path, mesh)
    try:
        base = router._exporter.url.replace("/metrics", "")
        with urllib.request.urlopen(f"{base}/fleetz", timeout=5) as r:
            text = r.read().decode()
            ctype = r.headers.get("Content-Type", "")
        assert "text/plain" in ctype
        assert "# TYPE pumi_jobs_total counter" in text
        # /buildz and the 404 body both teach the mounted endpoint.
        with urllib.request.urlopen(f"{base}/buildz", timeout=5) as r:
            assert "/fleetz" in json.loads(r.read())["endpoints"]
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/nope", timeout=5)
        assert "/fleetz" in err.value.read().decode()
    finally:
        router.close()


# --------------------------------------------------------------------- #
# Traceparent ingress
# --------------------------------------------------------------------- #
W3C = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"


def test_parse_traceparent_forms():
    assert parse_traceparent(None) is None
    assert parse_traceparent("  ") is None
    assert parse_traceparent(W3C) == "4bf92f3577b34da6a3ce929d0e0e4736"
    assert parse_traceparent("DEADBEEFDEADBEEF") == "deadbeefdeadbeef"
    for bad in ("xyz", "00-short-span-01", "ff" * 20):
        with pytest.raises(ValueError):
            parse_traceparent(bad)


def _post(url, body, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_traceparent_joins_submit_and_dedup(tmp_path, mesh):
    router = _router(tmp_path, mesh)
    gateway = TallyGateway(router)
    try:
        req = synthetic_requests(mesh, 1, class_sizes=(24,))[0]
        body = dict(request_to_json(req), idempotency_key="k1")
        status, payload = _post(
            f"{gateway.url}/submit", body, {"traceparent": W3C}
        )
        assert status == 200
        trace = "4bf92f3577b34da6a3ce929d0e0e4736"
        assert payload["trace_id"] == trace
        assert router.job(payload["job"]).trace_id == trace
        # The dedup path answers with the ORIGINAL trace even when the
        # retry carries a different (or no) traceparent.
        status2, payload2 = _post(f"{gateway.url}/submit", body)
        assert status2 == 200
        assert payload2 == payload
        # Malformed header: refused before anything is journaled.
        status3, payload3 = _post(
            f"{gateway.url}/submit", body, {"traceparent": "zz"}
        )
        assert status3 == 400
        assert "traceparent" in payload3["error"]
        # No header: the job mints its own root.
        other = synthetic_requests(
            mesh, 2, class_sizes=(24,), seed=9,
        )[1]
        status4, payload4 = _post(
            f"{gateway.url}/submit",
            dict(request_to_json(other), idempotency_key="k2"),
        )
        assert status4 == 200
        assert payload4["trace_id"]
        assert payload4["trace_id"] != trace
    finally:
        gateway.stop()
        router.close()


@pytest.mark.slow
def test_progress_rows_carry_trace_id(tmp_path, mesh):
    router = _router(tmp_path, mesh)
    gateway = TallyGateway(router)
    try:
        req = synthetic_requests(
            mesh, 1, class_sizes=(24,), n_moves=2,
        )[0]
        status, payload = _post(
            f"{gateway.url}/submit",
            dict(request_to_json(req), idempotency_key="k1"),
            {"traceparent": W3C},
        )
        assert status == 200
        router.run()
        with urllib.request.urlopen(
            f"{gateway.url}/progress/{payload['job']}?timeout=5",
            timeout=30,
        ) as resp:
            rows = [
                json.loads(line) for line in resp.read().splitlines()
            ]
        assert rows
        assert all(
            r["trace_id"] == payload["trace_id"] for r in rows
        )
    finally:
        gateway.stop()
        router.close()


# --------------------------------------------------------------------- #
# /jobs limit
# --------------------------------------------------------------------- #
def test_jobs_endpoint_limit(tmp_path, mesh, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    router = _router(tmp_path, mesh)
    try:
        for r in synthetic_requests(mesh, 5, class_sizes=(24,)):
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        base = router._exporter.url.replace("/metrics", "")

        def jobs(q=""):
            with urllib.request.urlopen(
                f"{base}/jobs{q}", timeout=5
            ) as resp:
                return json.loads(resp.read())
        full = jobs()
        assert full["total_jobs"] == 5
        assert full["limit"] == 500
        assert len(full["jobs"]) == 5
        capped = jobs("?limit=2")
        assert capped["limit"] == 2
        assert capped["total_jobs"] == 5
        assert len(capped["jobs"]) == 2
        # Newest first: the per-member submission ordinal leads.
        assert (
            capped["jobs"][0]["index"] >= capped["jobs"][1]["index"]
        )
        assert jobs("?limit=bogus")["limit"] == 500
    finally:
        router.close()


def test_exporter_query_optin_is_by_param_name():
    """The exporter hands the parsed query dict only to endpoints
    declaring a positional parameter literally named ``query`` — an
    unrelated optional positional (``TallyTracer.chrome(records=None)``)
    must NOT be mistaken for a query sink, or /trace renders an empty
    document from the query dict."""
    from pumiumtally_tpu.obs.exporter import _accepts_query

    assert _accepts_query(lambda query: query)
    assert _accepts_query(lambda query=None: query)
    assert not _accepts_query(lambda records=None: records)
    assert not _accepts_query(lambda: None)
    assert not _accepts_query(lambda **kw: kw)


# --------------------------------------------------------------------- #
# Exporter thread-safety under an active fleet
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_concurrent_scrapes_parse_and_stay_monotonic(
    tmp_path, mesh, monkeypatch
):
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    router = _router(tmp_path, mesh)
    try:
        for r in synthetic_requests(
            mesh, 4, class_sizes=(24,), n_moves=4,
        ):
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        base = router._exporter.url.replace("/metrics", "")
        stop = threading.Event()
        quanta: list[float] = []
        errors: list[str] = []

        def scrape(path, sink):
            from fleetview import check_prom_text

            while not stop.is_set():
                try:
                    with urllib.request.urlopen(
                        f"{base}{path}", timeout=10
                    ) as resp:
                        text = resp.read().decode()
                except OSError as e:  # noqa: PERF203
                    errors.append(f"{path}: {e}")
                    return
                problems = check_prom_text(text, path)
                if problems:
                    errors.extend(problems)
                    return
                total = 0.0
                for line in text.splitlines():
                    if line.startswith("pumi_quanta_total"):
                        total += float(line.rsplit(" ", 1)[1])
                sink.append(total)

        threads = [
            threading.Thread(
                target=scrape, args=("/fleetz", quanta), daemon=True
            ),
            threading.Thread(
                target=scrape, args=("/metrics", []), daemon=True
            ),
        ]
        for t in threads:
            t.start()
        router.run()
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(quanta) >= 2
        # The fleet-level counter never moves backwards mid-scrape.
        assert all(
            b >= a for a, b in zip(quanta, quanta[1:])
        ), quanta
        assert quanta[-1] > 0
        # And the post-run picture is reconstructible.
        from fleetview import check_fleetstats, load_dir

        assert check_fleetstats(load_dir(router.journal.dir)) == []
        doc = json.load(open(router.fleetstats_path()))
        util = doc["router_metrics"].get(
            "pumi_member_device_utilization"
        )
        assert util is not None and util["series"]
    finally:
        router.close()
