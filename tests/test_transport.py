"""End-to-end synthetic transport driver over the 4-call facade.

Drives PumiTally exactly the way OpenMC drives the reference (init →
move-per-event → write), on a two-region box so every outcome class —
destination reached, material-boundary stop, domain escape, roulette —
occurs. Checks physical invariants rather than golden numbers.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.models.transport import Material, SyntheticTransport


def _two_region_mesh(cells=4):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, cells, cells, cells)
    centroids = coords[tets].mean(axis=1)
    class_id = (centroids[:, 0] > 0.5).astype(np.int32)
    return TetMesh.from_numpy(coords, tets, class_id)


@pytest.mark.slow
def test_transport_smoke(tmp_path):
    mesh = _two_region_mesh()
    tally = PumiTally(
        mesh, 64, TallyConfig(n_groups=2, tolerance=1e-6)
    )
    driver = SyntheticTransport(
        tally,
        materials={0: Material(2.0, 0.4), 1: Material(8.0, 0.6)},
        seed=3,
    )
    out = str(tmp_path / "flux.vtu")
    stats = driver.run(batches=2, output=out)

    assert stats.batches == 2
    assert stats.events > 0
    assert stats.collisions > 0
    assert stats.absorbed_weight > 0
    # On a 1 cm box with mfp 0.125-0.5 cm, some particles must escape and
    # some must die by roulette across two 64-particle batches.
    assert stats.boundary_escapes + stats.roulette_kills > 0
    assert os.path.exists(out)

    flux = tally.raw_flux
    assert (flux[..., 0] >= 0).all()
    assert flux[..., 0].sum() > 0
    # Both regions were flown through.
    cid = np.asarray(mesh.class_id)
    assert flux[cid == 0, :, 0].sum() > 0
    assert flux[cid == 1, :, 0].sum() > 0
    # Downscatter populated group 1.
    assert flux[:, 1, 0].sum() > 0


def test_flux_tracks_track_length_conservation():
    """Total scored track length equals the summed per-event segment count
    times nothing magic — verify Σ flux·? by energy-group marginals: the
    sum over the raw group-0+1 contributions equals weight·length summed,
    which is bounded by events × max flight; sanity envelope only."""
    mesh = _two_region_mesh(3)
    tally = PumiTally(mesh, 32, TallyConfig(n_groups=2, tolerance=1e-6))
    driver = SyntheticTransport(tally, seed=11)
    driver.run(batches=1)
    total = float(tally.raw_flux[..., 0].sum())
    # Weight ≤ 1 per particle and every segment lies inside the unit box, so
    # a single particle cannot score more than the box diagonal per event.
    assert 0 < total <= tally.num_particles * driver.stats.events * np.sqrt(3)
