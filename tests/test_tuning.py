"""Shape-class autotuner contracts (pumiumtally_tpu/tuning/, the
round-7 tentpole).

Contracts pinned here:

  * DATABASE — round-trip, schema-version refusal, environment-keyed
    sections with cross-environment refusal (exactly CONTRACTS.json's
    rule), miss semantics.
  * CONSUMPTION — facade construction consumes a synthetic database
    (kernel="auto" picks the winner, lane_block and megastep K follow),
    explicit config knobs and env overrides always beat it, and a miss
    (or an empty database) leaves every resolve at today's defaults.
  * BYTE-IDENTITY — with no database / an empty database the facade's
    outputs are bitwise identical to a tuned run (every winner is
    parity-gated, and the knobs are pure scheduling), pinned on real
    multi-move facade runs.
  * PARITY GATE — a deliberately corrupted candidate (one-ULP flux
    perturbation through the PUMI_TPU_TUNE_FAULT hook) is recorded
    with parity="failed" and can never win.
  * DETERMINISM — scripts/tune.py --rehearsal reproduces identical
    winners across two fresh processes (the model-ranked rehearsal
    mode), proven through the CLI's --check gate; a tampered winner is
    drift (exit 1).
  * LANE_BLOCK LADDER — every block width is bitwise identical to
    DEFAULT_LANE_BLOCK (the knob is scheduling, never results).
  * CALIBRATION — costmodel.calibrate_points recovers known
    coefficients and predict_seconds composes with them.

Compile budget: the fast core (-m 'not slow') keeps only the
no-compile database/resolve tests; everything that compiles or
subprocesses is marked slow and runs in the dedicated CI tuning step.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.analysis.costmodel import (
    NOMINAL_COEFFS,
    calibrate_points,
    predict_seconds,
)
from pumiumtally_tpu.tuning import (
    TUNING_SCHEMA,
    ShapeClass,
    TunedDecision,
    bucket,
    classify,
    empty_db,
    env_key,
    environment,
    load_tuning,
    lookup_tuned,
    write_tuning,
)
from pumiumtally_tpu.tuning import search

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _synthetic_db(path, entries, env=None, mode="rehearsal"):
    env = env or environment()
    data = empty_db()
    data["environments"][env_key(env)] = {
        "environment": env,
        "mode": mode,
        "entries": entries,
    }
    write_tuning(str(path), data)
    return str(path)


def _mesh(cells=2, dtype=jnp.float32):
    return build_box(1.0, 1.0, 1.0, cells, cells, cells, dtype=dtype)


def _seeded(mesh, n, seed=3):
    rng = np.random.default_rng(seed)
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    pos0 = np.asarray(mesh.centroids())[elem].astype(np.float64)
    return pos0


def _run_moves(mesh, n, cfg, moves=3, seed=11):
    t = PumiTally(mesh, n, cfg)
    t.initialize_particle_location(_seeded(mesh, n).reshape(-1).copy())
    prev = _seeded(mesh, n)
    for i in range(moves):
        rng = np.random.default_rng(seed + i)
        d = rng.normal(0, 1, (n, 3))
        d /= np.linalg.norm(d, axis=1, keepdims=True)
        dest = np.clip(prev + d * 0.1, 0.01, 0.99)
        buf = dest.reshape(-1).copy()
        t.move_to_next_location(
            buf, np.ones(n, np.int8), np.ones(n),
            np.zeros(n, np.int32), np.full(n, -1, np.int32),
        )
        prev = buf.reshape(n, 3)
    return np.asarray(t.flux)


# --------------------------------------------------------------------- #
# Shape classes
# --------------------------------------------------------------------- #
def test_shape_class_bucketing():
    assert bucket(1) == 64 and bucket(64) == 64 and bucket(65) == 128
    sc = classify(48, 1000, 2, jnp.float32, True)
    assert sc == ShapeClass(64, 1024, 2, "float32", True)
    assert sc.key() == "ntet64.n1024.g2.float32.packed"
    # dtype/packedness never share a bucket
    assert classify(48, 1000, 2, jnp.float64, True) != sc
    assert classify(48, 1000, 2, jnp.float32, False) != sc


# --------------------------------------------------------------------- #
# Database round-trip + refusals
# --------------------------------------------------------------------- #
def test_db_roundtrip(tmp_path):
    sc = classify(48, 256, 2, jnp.float32, True)
    path = _synthetic_db(
        tmp_path / "t.json",
        {sc.key(): {"kernel": "pallas", "lane_block": 64, "megastep": 4}},
    )
    db = load_tuning(path)
    entry = db.lookup(sc)
    assert entry["kernel"] == "pallas" and entry["lane_block"] == 64
    assert db.lookup(classify(9999, 256, 2, jnp.float32, True)) is None


def test_db_schema_refusal(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"schema": TUNING_SCHEMA + 1,
                             "environments": {}}))
    with pytest.raises(ValueError, match="schema"):
        load_tuning(str(p))
    p2 = tmp_path / "worse.json"
    p2.write_text(json.dumps({"entries": {}}))
    with pytest.raises(ValueError, match="schema"):
        load_tuning(str(p2))


def test_db_cross_environment_refusal(tmp_path):
    other = {"backend": "tpu", "x64": False, "n_devices": 4}
    path = _synthetic_db(tmp_path / "tpu.json", {}, env=other)
    db = load_tuning(path)
    with pytest.raises(ValueError, match="no section for the current"):
        db.section(strict=True)
    # ... and through the facade's construction-time consult.
    cfg = TallyConfig(tuning=path)
    with pytest.raises(ValueError, match="no section for the current"):
        PumiTally(_mesh(), 64, cfg)


def test_db_section_env_drift_refused(tmp_path):
    # A section whose key matches but whose pinned environment doesn't
    # (hand-edited file) is refused, not silently consumed.
    env = environment()
    data = empty_db()
    data["environments"][env_key(env)] = {
        "environment": dict(env, x64=not env["x64"]),
        "entries": {},
    }
    p = tmp_path / "drift.json"
    write_tuning(str(p), data)
    with pytest.raises(ValueError, match="drifted"):
        load_tuning(str(p)).section()


def test_empty_db_is_all_miss(tmp_path):
    p = tmp_path / "empty.json"
    write_tuning(str(p), empty_db())
    dec = lookup_tuned(
        str(p), ntet=48, n_particles=64, n_groups=2,
        dtype=jnp.float32, packed=True,
    )
    assert not dec.hit and dec.kernel is None


# --------------------------------------------------------------------- #
# Knob resolution (no compiles)
# --------------------------------------------------------------------- #
def test_resolve_tuning_env_beats_field(monkeypatch):
    cfg = TallyConfig(tuning="/cfg/path.json")
    assert cfg.resolve_tuning() == "/cfg/path.json"
    monkeypatch.setenv("PUMI_TPU_TUNING", "off")
    assert cfg.resolve_tuning() is None
    monkeypatch.setenv("PUMI_TPU_TUNING", "/env/path.json")
    assert cfg.resolve_tuning() == "/env/path.json"
    monkeypatch.delenv("PUMI_TPU_TUNING")
    assert TallyConfig().resolve_tuning() is None


def test_resolve_lane_block_validation(monkeypatch):
    assert TallyConfig().resolve_lane_block(256) is None
    assert TallyConfig(pallas_lane_block=64).resolve_lane_block(256) == 64
    # clamped to the batch
    assert TallyConfig(pallas_lane_block=512).resolve_lane_block(80) == 80
    with pytest.raises(ValueError, match="power of two"):
        TallyConfig(pallas_lane_block=100).resolve_lane_block(256)
    with pytest.raises(ValueError, match="power of two"):
        TallyConfig(pallas_lane_block=-8).resolve_lane_block(256)
    # env beats field
    monkeypatch.setenv("PUMI_TPU_PALLAS_LANE_BLOCK", "32")
    assert TallyConfig(pallas_lane_block=64).resolve_lane_block(256) == 32


def test_resolve_knobs_precedence_over_db(monkeypatch):
    tuned = TunedDecision(
        path="x", key="k", hit=True, kernel="pallas", lane_block=32,
        megastep=4,
    )
    # db fills the defer values...
    assert TallyConfig().resolve_lane_block(256, tuned=tuned) == 32
    assert TallyConfig().resolve_megastep(tuned=tuned) == 4
    # ...config fields beat it...
    assert TallyConfig(pallas_lane_block=16).resolve_lane_block(
        256, tuned=tuned
    ) == 16
    assert TallyConfig(megastep=2).resolve_megastep(tuned=tuned) == 2
    # ...and env overrides beat both.
    monkeypatch.setenv("PUMI_TPU_PALLAS_LANE_BLOCK", "8")
    monkeypatch.setenv("PUMI_TPU_MEGASTEP", "16")
    assert TallyConfig(pallas_lane_block=16).resolve_lane_block(
        256, tuned=tuned
    ) == 8
    assert TallyConfig(megastep=2).resolve_megastep(tuned=tuned) == 16


# --------------------------------------------------------------------- #
# Facade consumption at construction
# --------------------------------------------------------------------- #
def test_construction_consumes_db(tmp_path, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    mesh = _mesh()
    n = 64
    sc = classify(mesh.ntet, n, 2, jnp.float32, True)
    path = _synthetic_db(
        tmp_path / "t.json",
        {sc.key(): {"kernel": "pallas", "lane_block": 32, "megastep": 4}},
    )
    t = PumiTally(mesh, n, TallyConfig(kernel="auto", tuning=path))
    assert t._kernel == "pallas"
    assert t._lane_block == 32
    assert t._tuned.hit and t._tuned.key == sc.key()
    assert t.config.resolve_megastep(tuned=t._tuned) == 4


def test_db_kernel_xla_pins_auto(tmp_path, monkeypatch):
    # A database that measured XLA faster overrides the in-regime
    # "auto" heuristic that would have picked Pallas.
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    mesh = _mesh()
    sc = classify(mesh.ntet, 64, 2, jnp.float32, True)
    path = _synthetic_db(
        tmp_path / "t.json", {sc.key(): {"kernel": "xla", "megastep": 1}}
    )
    t = PumiTally(mesh, 64, TallyConfig(kernel="auto", tuning=path))
    assert t._kernel == "xla"
    # without the database the same construction picks Pallas
    t2 = PumiTally(mesh, 64, TallyConfig(kernel="auto"))
    assert t2._kernel == "pallas"


def test_explicit_config_beats_db(tmp_path, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    mesh = _mesh()
    sc = classify(mesh.ntet, 64, 2, jnp.float32, True)
    path = _synthetic_db(
        tmp_path / "t.json",
        {sc.key(): {"kernel": "pallas", "lane_block": 32, "megastep": 4}},
    )
    # explicit kernel="xla" (the default) never flips to the db winner
    t = PumiTally(mesh, 64, TallyConfig(tuning=path))
    assert t._kernel == "xla"
    # explicit lane_block beats the db's 32
    t2 = PumiTally(
        mesh, 64,
        TallyConfig(kernel="auto", tuning=path, pallas_lane_block=16),
    )
    assert t2._kernel == "pallas" and t2._lane_block == 16
    # explicit megastep beats the db's 4
    assert t2.config.resolve_megastep(tuned=t2._tuned) == 4
    t3 = PumiTally(
        mesh, 64, TallyConfig(tuning=path, megastep=2)
    )
    assert t3.config.resolve_megastep(tuned=t3._tuned) == 2


def test_db_miss_falls_back_to_defaults(tmp_path, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    mesh = _mesh()
    other = classify(99999, 64, 2, jnp.float32, True)  # not this mesh
    path = _synthetic_db(
        tmp_path / "t.json",
        {other.key(): {"kernel": "pallas", "lane_block": 32,
                       "megastep": 64}},
    )
    t = PumiTally(mesh, 64, TallyConfig(kernel="auto", tuning=path))
    assert t._tuned is not None and not t._tuned.hit
    assert t._kernel == "pallas"  # today's auto policy, unchanged
    assert t._lane_block is None  # kernel default
    assert t.config.resolve_megastep(tuned=t._tuned) == 1


def test_partitioned_consumes_megastep_only(tmp_path):
    from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

    mesh = _mesh(3)
    n = 64
    sc = classify(mesh.ntet, n, 2, jnp.float32, packed=False)
    path = _synthetic_db(
        tmp_path / "t.json",
        {sc.key(): {"kernel": "pallas", "lane_block": 64, "megastep": 4}},
    )
    t = PartitionedTally(
        mesh, n, n_parts=4, config=TallyConfig(tuning=path)
    )
    assert t._tuned.hit
    assert t._kernel == "xla"  # the partitioned walk never rides Mosaic
    assert t.config.resolve_megastep(tuned=t._tuned) == 4


# --------------------------------------------------------------------- #
# Byte-identity (real facade runs — compiles)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_db_miss_and_empty_db_byte_identity(tmp_path):
    mesh = _mesh()
    n = 64
    f_plain = _run_moves(mesh, n, TallyConfig())
    p_empty = tmp_path / "empty.json"
    write_tuning(str(p_empty), empty_db())
    f_empty = _run_moves(mesh, n, TallyConfig(tuning=str(p_empty)))
    other = classify(99999, n, 2, jnp.float32, True)
    p_miss = _synthetic_db(
        tmp_path / "miss.json",
        {other.key(): {"kernel": "pallas", "lane_block": 32}},
    )
    f_miss = _run_moves(mesh, n, TallyConfig(tuning=p_miss))
    assert f_plain.tobytes() == f_empty.tobytes() == f_miss.tobytes()


@pytest.mark.slow
def test_tuned_run_bitwise_identical_to_default(tmp_path, monkeypatch):
    # The whole point of the parity gate: a database steering the
    # kernel to Pallas at a non-default lane_block changes NOTHING in
    # the outputs, bit for bit.
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    mesh = _mesh()
    n = 64
    sc = classify(mesh.ntet, n, 2, jnp.float32, True)
    path = _synthetic_db(
        tmp_path / "t.json",
        {sc.key(): {"kernel": "pallas", "lane_block": 32, "megastep": 2}},
    )
    f_default = _run_moves(mesh, n, TallyConfig())
    f_tuned = _run_moves(
        mesh, n, TallyConfig(kernel="auto", tuning=path)
    )
    assert f_default.tobytes() == f_tuned.tobytes()


@pytest.mark.slow
@pytest.mark.parametrize("lane_block", [8, 16, 32])
def test_lane_block_ladder_bitwise_parity(lane_block):
    # Every rung of the block-width ladder is bitwise identical to the
    # kernel default: the one-hot contraction is exact and collisions
    # peel in ascending-lane order within any block split.
    from pumiumtally_tpu.ops.walk import trace_impl

    mesh = _mesh(2)
    n = 48
    rng = np.random.default_rng(5)
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], jnp.float32
    )
    dest = jnp.asarray(rng.uniform(0.05, 0.95, (n, 3)), jnp.float32)
    fly = jnp.ones(n, bool)
    w = jnp.asarray(rng.uniform(0.5, 2.0, n), jnp.float32)
    g = jnp.asarray(rng.integers(0, 2, n), jnp.int32)
    mat = jnp.full(n, -1, jnp.int32)

    def run(lb):
        flux = jnp.zeros((mesh.ntet, 2, 2), jnp.float32)
        r = trace_impl(
            mesh, origin, dest, elem, fly, w, g, mat, flux,
            initial=False, max_crossings=mesh.ntet + 64,
            tolerance=1e-6, kernel="pallas", lane_block=lb,
        )
        return (
            np.asarray(r.flux), np.asarray(r.position),
            np.asarray(r.elem), np.asarray(r.done),
        )

    ref = run(None)  # DEFAULT_LANE_BLOCK (clamped to the batch)
    out = run(lane_block)
    for a, b in zip(ref, out):
        assert a.tobytes() == b.tobytes()


# --------------------------------------------------------------------- #
# The search driver: parity gate + winners
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_parity_gate_rejects_corrupted_candidate(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    spec = dict(cells=2, n_particles=32, n_groups=2)
    # Corrupt the (single, clamped-to-batch) Pallas candidate by one
    # ULP: the bitwise gate must reject it and the winner must fall
    # back to a clean candidate.
    monkeypatch.setenv("PUMI_TPU_TUNE_FAULT", "kernel:pallas:32")
    _, entry = search.tune_shape_class(
        spec, mode="rehearsal", reps=1, moves=1, mega_moves=1,
    )
    pallas = [
        c for c in entry["candidates"]
        if c["kind"] == "kernel" and c["kernel"] == "pallas"
    ]
    assert pallas and all(c["parity"] == "failed" for c in pallas)
    assert entry["kernel"] == "xla"  # the corrupted candidate never wins
    # ...and without the fault the same candidate passes.
    monkeypatch.delenv("PUMI_TPU_TUNE_FAULT")
    _, clean = search.tune_shape_class(
        spec, mode="rehearsal", reps=1, moves=1, mega_moves=1,
    )
    assert all(
        c["parity"] == "bitwise" for c in clean["candidates"]
    )


@pytest.mark.slow
def test_megastep_parity_gate_rejects_corruption(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("PUMI_TPU_TUNE_FAULT", "megastep:4")
    spec = dict(cells=2, n_particles=32, n_groups=2)
    _, entry = search.tune_shape_class(
        spec, mode="rehearsal", reps=1, moves=1, mega_moves=4,
    )
    k4 = [
        c for c in entry["candidates"]
        if c["kind"] == "megastep" and c["megastep"] == 4
    ]
    assert k4 and k4[0]["parity"] == "failed"
    assert entry["megastep"] == 1


# --------------------------------------------------------------------- #
# The CLI: determinism across fresh processes + the drift gate
# --------------------------------------------------------------------- #
def _tune_cli(args, timeout=420):
    env = dict(os.environ)
    env.pop("JAX_ENABLE_X64", None)
    env.pop("PUMI_TPU_TUNING", None)
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "tune.py"),
         "--rehearsal", "--shapes", "t=2:64:2", "--moves", "1",
         "--reps", "1", "--mega-moves", "4", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=ROOT,
    )


@pytest.mark.slow
def test_tuner_deterministic_across_processes_and_check_gate(tmp_path):
    out = str(tmp_path / "t.json")
    r1 = _tune_cli(["--out", out])
    assert r1.returncode == 0, r1.stderr
    # A SECOND fresh process re-tunes and compares winners against the
    # first through --check: exit 0 == identical winners, which is the
    # determinism contract (rehearsal mode ranks on the deterministic
    # cost model, not interpret-mode wall clock).
    r2 = _tune_cli(["--check", out])
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "tuning check clean" in r2.stdout
    # Tampering with a committed winner is drift: exit 1, named key.
    data = json.load(open(out))
    sec = next(iter(data["environments"].values()))
    key, entry = next(iter(sec["entries"].items()))
    entry["megastep"] = 999
    json.dump(data, open(out, "w"))
    r3 = _tune_cli(["--check", out])
    assert r3.returncode == 1
    assert "tuning drift" in r3.stdout and key in r3.stdout


# --------------------------------------------------------------------- #
# Calibration (analysis/costmodel.py)
# --------------------------------------------------------------------- #
def test_calibrate_points_recovers_coefficients():
    F, B = 1e12, 2e11  # planted effective throughput / bandwidth
    pts = [
        dict(flops=f, bytes_accessed=b, seconds=f / F + b / B)
        for f, b in [(1e9, 2e8), (5e9, 4e8), (2e10, 8e9), (1e8, 6e9)]
    ]
    cal = calibrate_points(pts)
    assert cal["points"] == 4
    assert abs(cal["flops_per_s"] - F) / F < 1e-6
    assert abs(cal["bytes_per_s"] - B) / B < 1e-6
    assert cal["rmse_s"] < 1e-9
    # predict_seconds closes the loop
    m = dict(flops=3e9, bytes_accessed=5e8)
    assert abs(
        predict_seconds(m, cal) - (3e9 / F + 5e8 / B)
    ) < 1e-9


def test_calibrate_points_degenerate_falls_back():
    # Identical signatures (singular system) → single-term fit, not a
    # crash or a negative coefficient.
    pts = [
        dict(flops=1e9, bytes_accessed=2e8, seconds=s)
        for s in (0.01, 0.011, 0.009)
    ]
    cal = calibrate_points(pts)
    assert cal is not None
    assert (cal["flops_per_s"] is None) != (cal["bytes_per_s"] is None)
    # predict_seconds tolerates the explicit None fallback (the
    # persisted degenerate calibration must not crash its consumers)
    t = predict_seconds(dict(flops=1e9, bytes_accessed=2e8), cal)
    assert t > 0
    assert calibrate_points([]) is None


def test_nominal_predict_orders_dispatch_amortization():
    m = dict(flops=1e9, bytes_accessed=1e8)
    t1 = predict_seconds(m, NOMINAL_COEFFS, dispatches=1.0)
    t16 = predict_seconds(m, NOMINAL_COEFFS, dispatches=1.0 / 16)
    assert t16 < t1  # fused dispatches amortize the launch overhead


# --------------------------------------------------------------------- #
# Satellites: committed smoke db, perfdiff table, astlint coverage
# --------------------------------------------------------------------- #
def test_committed_tuning_db_schema():
    # The committed smoke database parses under the current schema and
    # carries the CPU rehearsal section with parity-clean winners.
    db = load_tuning(os.path.join(ROOT, "TUNING.json"))
    sec = db.environments.get("cpu-x64off-d1")
    assert sec is not None and sec["mode"] == "rehearsal"
    assert sec["entries"], "smoke database must carry entries"
    for entry in sec["entries"].values():
        winners = [
            c for c in entry["candidates"]
            if c["parity"] == "bitwise"
        ]
        assert winners, "every entry needs parity-clean candidates"
        assert entry["calibration"] is not None


def test_perfdiff_tuning_table():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "perfdiff.py"),
         "--tuning", os.path.join(ROOT, "TUNING.json")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "speedup" in proc.stdout
    assert "calibration" in proc.stdout


def test_astlint_covers_tuner_scripts():
    # The scripts/*.py value-safety subset picks the tuner up
    # automatically — pin that it stays clean under it (PUMI001/003/
    # 004/005: host syncs, use-after-donate, nondeterminism, f64).
    from pumiumtally_tpu.analysis.astlint import lint_sources

    src = {}
    for rel in ("scripts/tune.py", "pumiumtally_tpu/tuning/search.py",
                "pumiumtally_tpu/tuning/db.py",
                "pumiumtally_tpu/tuning/shapes.py"):
        src[rel] = open(os.path.join(ROOT, rel)).read()
    findings = lint_sources(src)
    assert findings == [], [f.render() for f in findings]
