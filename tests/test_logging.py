"""Structured logging: tag format, level filtering, JSON mode."""
from __future__ import annotations

import json
import logging

from pumiumtally_tpu.utils import log as plog


def _capture(capsys):
    return capsys.readouterr().err.strip().split("\n")


def test_tagged_format(capsys):
    plog.log_info("mesh loaded", ntet=6)
    plog.log_warn("truncated")
    lines = _capture(capsys)
    assert lines[0] == "[INFO] mesh loaded ntet=6"
    assert lines[1] == "[WARN] truncated"


def test_level_filtering(capsys):
    logger = plog.get_logger()
    old = logger.level
    try:
        logger.setLevel(logging.WARNING)
        plog.log_info("hidden")
        plog.log_error("shown")
        lines = _capture(capsys)
        assert lines == ["[ERROR] shown"]
    finally:
        logger.setLevel(old)


def test_json_mode(monkeypatch, capsys):
    monkeypatch.setenv("PUMI_TPU_LOG_JSON", "1")
    plog.log_time("tally", 1.25, steps=10)
    (line,) = _capture(capsys)
    rec = json.loads(line)
    assert rec["level"] == "info"
    assert rec["phase"] == "tally"
    assert rec["seconds"] == 1.25
    assert rec["steps"] == 10


def test_tally_times_print_goes_through_logger(capsys):
    from pumiumtally_tpu.utils.timing import TallyTimes

    t = TallyTimes(initialization_time=1.0, total_time_to_tally=2.0)
    t.print_times()
    lines = _capture(capsys)
    assert any("initialization" in ln and "1.0" in ln for ln in lines)
    assert any("total" in ln and "3.0" in ln for ln in lines)
