"""C ABI integration: compile the embedded-interpreter bridge and a pure-C
host program, run it end to end, and check its flux against the same
deterministic scenario driven from Python.

This is the OpenMC-shaped consumer test: a C main() links against
libpumi_tally_c.so (no Python in sight), creates a tally on a mesh file,
flies 16 particles out of the box, and reads back clipped positions,
reset flying flags, and the raw flux.
"""
from __future__ import annotations

import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

pytestmark = pytest.mark.skipif(
    any(
        shutil.which(tool) is None
        for tool in ("g++", "gcc", "python3-config")
    ),
    reason="native toolchain unavailable",
)


def _pyconfig(*flags):
    return subprocess.run(
        ["python3-config", *flags], capture_output=True, text=True,
        check=True,
    ).stdout.split()


@pytest.fixture(scope="module")
def c_artifacts(tmp_path_factory):
    build = tmp_path_factory.mktemp("cbuild")
    lib = build / "libpumi_tally_c.so"
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
         os.path.join(NATIVE, "pumi_tally_c.cpp"),
         *_pyconfig("--includes"), "-I", NATIVE,
         "-o", str(lib), *_pyconfig("--ldflags", "--embed")],
        check=True, capture_output=True, text=True,
    )
    demo = build / "demo_host"
    subprocess.run(
        ["gcc", "-O2", os.path.join(NATIVE, "demo_host.c"),
         "-I", NATIVE, "-L", str(build), "-lpumi_tally_c",
         "-o", str(demo)],
        check=True, capture_output=True, text=True,
    )
    return build, demo


def _write_mesh(path):
    from pumiumtally_tpu.mesh.box import build_box_arrays
    from pumiumtally_tpu.mesh.io import save_npz

    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 2, 2, 2)
    save_npz(path, coords, tets, np.zeros(tets.shape[0], np.int32))
    return coords, tets


def test_c_host_end_to_end(c_artifacts, tmp_path):
    build, demo = c_artifacts
    mesh_file = str(tmp_path / "box.npz")
    coords, tets = _write_mesh(mesh_file)
    out_vtu = str(tmp_path / "flux.vtu")

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PUMI_TPU_PLATFORM"] = "cpu"
    env["LD_LIBRARY_PATH"] = (
        str(build) + os.pathsep + env.get("LD_LIBRARY_PATH", "")
    )
    r = subprocess.run(
        [str(demo), mesh_file, out_vtu],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "OK" in r.stdout
    flux_sum = float(
        next(ln for ln in r.stdout.splitlines() if ln.startswith("FLUX_SUM"))
        .split()[1]
    )
    assert os.path.exists(out_vtu)

    # The same deterministic scenario from Python must agree.
    from pumiumtally_tpu import PumiTally, TallyConfig
    from pumiumtally_tpu.mesh.core import TetMesh

    n = 16
    mesh = TetMesh.from_numpy(coords, tets, np.zeros(tets.shape[0], np.int32))
    t = PumiTally(mesh, n, TallyConfig(n_groups=2))
    pos = np.zeros((n, 3))
    pos[:, 0] = 0.2 + 0.6 * np.arange(n) / n
    pos[:, 1] = 0.5
    pos[:, 2] = 0.5
    t.initialize_particle_location(pos.ravel())
    dests = pos.copy()
    dests[:, 0] += 2.0
    t.move_to_next_location(
        dests, np.ones(n, np.int8), np.ones(n),
        (np.arange(n) % 2).astype(np.int32), np.full(n, -1, np.int32),
    )
    expect = float(t.raw_flux[..., 0].sum())
    assert flux_sum == pytest.approx(expect, rel=1e-6)
