"""Truncated-walk coverage: the warn-and-report path
(`_n_truncated`/`_warn_if_truncated`), the bounded re-walk escalation
(`ops/walk.py rewalk_truncated`, `TallyConfig.truncation_retries`) on
both facades, and the `stuck>=4` frozen-lane contract the partitioned
exchange reads (a lane frozen for migration mid-chase keeps its
zero-progress counter across the cut)."""
from __future__ import annotations

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.ops.walk import chase_face_choice, escalated_bump
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

N = 32


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 5, 5, 5)


@pytest.fixture(scope="module")
def mesh64():
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, 5, 5, 5)
    return TetMesh.from_numpy(coords, t2v, dtype=jnp.float64)


def _init(t):
    rng = np.random.default_rng(42)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (t.num_particles, 3)).ravel()
    )
    return t


def _inputs(i, n=N):
    rng = np.random.default_rng(300 + i)
    return (
        # Long moves: many boundary crossings per walk, so a tiny
        # max_crossings bound reliably truncates.
        rng.uniform(0.02, 0.98, (n, 3)).ravel().copy(),
        np.ones(n, np.int8),
        rng.uniform(0.5, 2.0, n),
        rng.integers(0, 2, n).astype(np.int32),
        np.full(n, -1, np.int32),
    )


# ===================================================================== #
# Warn-and-report path (the pre-escalation contract)
# ===================================================================== #
def test_truncated_walks_warn_and_count(mesh):
    t = _init(
        PumiTally(
            mesh, N, TallyConfig(tolerance=1e-6, max_crossings=2)
        )
    )
    with pytest.warns(RuntimeWarning, match="truncated"):
        t.move_to_next_location(*_inputs(1))
    tm = t.telemetry()
    assert tm["totals"]["truncated"] > 0
    assert tm["totals"]["lost"] == tm["totals"]["truncated"]
    assert tm["totals"]["rewalked"] == 0


def test_truncated_fallback_host_scan(mesh):
    """walk_stats=False removes the on-device truncation counter; the
    facade's host scan of ``done`` must still warn."""
    t = _init(
        PumiTally(
            mesh, N,
            TallyConfig(
                tolerance=1e-6, max_crossings=2, walk_stats=False
            ),
        )
    )
    with pytest.warns(RuntimeWarning, match="truncated"):
        t.move_to_next_location(*_inputs(1))


# ===================================================================== #
# Escalation: re-walk only the truncated lanes, bounded retries
# ===================================================================== #
def test_escalation_recovers_truncated_walks(mesh):
    """With retries, a tiny-bound run must recover every lane (no
    RuntimeWarning) and reproduce the ample-bound flux."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        t = _init(
            PumiTally(
                mesh, N,
                TallyConfig(
                    tolerance=1e-6, max_crossings=2,
                    truncation_retries=5,
                ),
            )
        )
        for i in range(1, 4):
            t.move_to_next_location(*_inputs(i))
    ref = _init(
        PumiTally(mesh, N, TallyConfig(tolerance=1e-6))
    )
    for i in range(1, 4):
        ref.move_to_next_location(*_inputs(i))
    np.testing.assert_allclose(
        np.asarray(t.raw_flux), np.asarray(ref.raw_flux), atol=1e-5
    )
    np.testing.assert_array_equal(t.element_ids, ref.element_ids)
    tm = t.telemetry()["totals"]
    assert tm["rewalked"] > 0 and tm["lost"] == 0


def test_escalation_bounded_then_lost(mesh):
    """One retry on a hopeless bound: some lanes recover, the rest are
    declared lost — with the warning and the lost counter agreeing."""
    t = _init(
        PumiTally(
            mesh, N,
            TallyConfig(
                tolerance=1e-6, max_crossings=1, truncation_retries=1
            ),
        )
    )
    with pytest.warns(RuntimeWarning, match="truncated"):
        t.move_to_next_location(*_inputs(1))
    tm = t.telemetry()["totals"]
    assert tm["rewalked"] > 0
    assert tm["lost"] > 0


def test_escalation_composes_with_xpoints(mesh):
    """The re-walk appends its crossing points after the prior
    attempt's, so the recorded path matches an uninterrupted walk."""
    cfg = dict(tolerance=1e-6, record_xpoints=8)
    t = _init(
        PumiTally(
            mesh, N,
            TallyConfig(
                max_crossings=2, truncation_retries=6, **cfg
            ),
        )
    )
    ref = _init(PumiTally(mesh, N, TallyConfig(**cfg)))
    for tally in (t, ref):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            tally.move_to_next_location(*_inputs(1))
    xp_t, c_t = t.intersection_points()
    xp_r, c_r = ref.intersection_points()
    np.testing.assert_array_equal(c_t, c_r)
    np.testing.assert_allclose(xp_t, xp_r, atol=1e-5)


def test_partitioned_escalation_recovers(mesh64):
    """The partitioned escalation (re-arming the same compiled step on
    the truncated lanes) must reproduce the unbounded run's flux."""
    cfg = TallyConfig(
        dtype=jnp.float64, tolerance=1e-8, truncation_retries=8
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        t = PartitionedTally(
            mesh64, N, cfg, n_parts=8, max_rounds=1
        )
        _init(t)
        for i in range(1, 3):
            t.move_to_next_location(*_inputs(i))
    ref = PartitionedTally(
        mesh64, N,
        TallyConfig(dtype=jnp.float64, tolerance=1e-8),
        n_parts=8,
    )
    _init(ref)
    for i in range(1, 3):
        ref.move_to_next_location(*_inputs(i))
    np.testing.assert_allclose(
        t.raw_flux, ref.raw_flux, rtol=0, atol=1e-11
    )
    tm = t.telemetry()["totals"]
    assert tm["rewalked"] > 0 and tm["lost"] == 0


def test_partitioned_escalation_batch_sd_folds_once_per_move(mesh64):
    """sd_mode='batch' + escalation: slot 1 must accumulate ONE squared
    delta per MOVE (the merged total), not one per re-walk attempt —
    i.e. the escalated run's squares equal the unbounded run's."""
    def drive(**kw):
        t = PartitionedTally(
            mesh64, N,
            TallyConfig(
                dtype=jnp.float64, tolerance=1e-8, sd_mode="batch",
                **kw.pop("cfg", {}),
            ),
            n_parts=8, **kw,
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _init(t)
            for i in range(1, 3):
                t.move_to_next_location(*_inputs(i))
        return t

    esc = drive(max_rounds=1, cfg=dict(truncation_retries=8))
    ref = drive()
    assert esc.telemetry()["totals"]["rewalked"] > 0
    np.testing.assert_allclose(
        esc.raw_flux[..., 1], ref.raw_flux[..., 1], rtol=0, atol=1e-11
    )
    np.testing.assert_allclose(
        esc.raw_flux[..., 0], ref.raw_flux[..., 0], rtol=0, atol=1e-11
    )


def test_partitioned_truncation_warns_without_retries(mesh64):
    t = PartitionedTally(
        mesh64, N,
        TallyConfig(dtype=jnp.float64, tolerance=1e-8),
        n_parts=8, max_rounds=1,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # initial search truncates too
        _init(t)
    with pytest.warns(RuntimeWarning, match="truncated"):
        t.move_to_next_location(*_inputs(1))
    assert t.telemetry()["totals"]["lost"] > 0


# ===================================================================== #
# The stuck>=4 frozen-lane contract
# ===================================================================== #
def test_escalated_bump_frozen_lane_contract():
    """The partitioned exchange freezes mid-walk lanes for migration and
    reads ``stuck>=4`` on the far side to know a lane froze mid-chase
    (walk bodies: ``chase = active & (stuck >= 4) & ~contained``). The
    contract that makes this sound: a NON-continuing (frozen) lane
    KEEPS its zero-progress counter; only real progress resets it."""
    dtype = jnp.float64
    tol_floor = 8 * float(jnp.finfo(dtype).eps)
    n = 5
    stuck = jnp.array([0, 2, 5, 48, 3], jnp.int32)
    contained = jnp.zeros(n, bool)
    #            zero-step  zero-step  FROZEN  zero-step  real-step
    continuing = jnp.array([True, True, False, True, True])
    t_step = jnp.array([0.0, 0.0, 0.0, 0.0, 0.5], dtype)
    cur = jnp.ones((n, 3), dtype)
    dnorm = jnp.ones(n, dtype)
    tol_eff = jnp.full(n, 1e-8, dtype)
    extra, nxt = escalated_bump(
        stuck, contained, continuing, t_step, tol_floor, tol_eff,
        cur, dnorm, dtype,
    )
    nxt = np.asarray(nxt)
    assert nxt[0] == 1   # zero-progress increments
    assert nxt[1] == 3
    assert nxt[2] == 5   # FROZEN lane keeps its count across the cut
    assert nxt[3] == 48  # capped (the _exp2i overflow guard)
    assert nxt[4] == 0   # real progress resets
    extra = np.asarray(extra)
    assert (extra >= 0).all()
    # The bump doubles per consecutive zero-progress crossing.
    assert extra[1] > extra[0]


def test_escalated_bump_resets_on_containment():
    """A genuinely contained lane resets even at zero step — chase
    recovery ends the moment containment is restored."""
    dtype = jnp.float64
    n = 2
    stuck = jnp.array([6, 6], jnp.int32)
    contained = jnp.array([True, False])
    continuing = jnp.array([True, True])
    t_step = jnp.zeros(n, dtype)
    _, nxt = escalated_bump(
        stuck, contained, continuing, t_step,
        8 * float(jnp.finfo(dtype).eps),
        jnp.full(n, 1e-8, dtype), jnp.ones((n, 3), dtype),
        jnp.ones(n, dtype), dtype,
    )
    nxt = np.asarray(nxt)
    assert nxt[0] == 0 and nxt[1] == 7


def test_chase_face_choice_excludes_boundary_faces():
    """A mislocated but in-domain particle must never be chased out of
    the domain: boundary faces are excluded while any interior
    candidate exists."""
    dtype = jnp.float64
    sd = jnp.array([[1.0, 2.0, 0.5, 0.1]], dtype)  # face 1 most violated
    interior = jnp.array([[True, False, True, True]])  # face 1 = boundary
    for it in range(8):  # any iteration's pseudo-random weights
        face = chase_face_choice(
            sd, jnp.array([7], jnp.int32), jnp.int32(it), dtype,
            interior,
        )
        assert bool(interior[0, int(face[0])])
    # With NO interior candidate the exclusion lifts (any face valid).
    none_interior = jnp.zeros((1, 4), bool)
    face = chase_face_choice(
        sd, jnp.array([7], jnp.int32), jnp.int32(0), dtype,
        none_interior,
    )
    assert 0 <= int(face[0]) < 4
