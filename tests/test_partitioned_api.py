"""PartitionedTally facade: the 4-call PumiTally contract over the
halo-partitioned walk must match the single-chip facade exactly (f64,
same arithmetic) — flux, copied-back positions, material ids, flying
reset — including flux accumulation across multiple moves and parked
(flying=0) particles staying put."""
import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

N = 256


@pytest.fixture(scope="module")
def mesh():
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, 5, 5, 5)
    cen = coords[t2v].mean(axis=1)
    cls = np.where(cen[:, 0] < 0.5, 1, 2).astype(np.int32)
    return TetMesh.from_numpy(coords, t2v, class_id=cls, dtype=jnp.float64)


def _drive(t, moves=2):
    rng = np.random.default_rng(17)
    pos = rng.uniform(0.05, 0.95, (N, 3))
    t.initialize_particle_location(pos.ravel().copy(), N * 3)
    outs = []
    prev = pos
    for i in range(moves):
        dest = np.clip(prev + rng.normal(0, 0.25, (N, 3)), -0.1, 1.1)
        buf = dest.ravel().copy()
        flying = np.ones(N, np.int8)
        flying[:: 7] = 0  # parked lanes must not move or score
        w = rng.uniform(0.5, 2.0, N)
        g = rng.integers(0, 2, N).astype(np.int32)
        mats = np.full(N, 9, np.int32)
        t.move_to_next_location(buf, flying, w, g, mats, buf.size)
        assert (flying == 0).all()
        outs.append((buf.reshape(N, 3).copy(), mats.copy()))
        prev = buf.reshape(N, 3).copy()
        # Parked particles keep their previous position in the out-param
        # (they were not advanced).
    return outs


def test_partitioned_tally_matches_pumitally(mesh):
    cfg = TallyConfig(n_groups=2, dtype=jnp.float64, tolerance=1e-8)
    single = PumiTally(mesh, N, cfg)
    parted = PartitionedTally(
        mesh, N, cfg, n_parts=8, halo_layers=1
    )
    outs_s = _drive(single)
    outs_p = _drive(parted)
    for (pos_s, mats_s), (pos_p, mats_p) in zip(outs_s, outs_p):
        np.testing.assert_allclose(pos_p, pos_s, atol=1e-12)
        np.testing.assert_array_equal(mats_p, mats_s)
    np.testing.assert_allclose(
        parted.raw_flux, np.asarray(single.raw_flux), rtol=0, atol=1e-11
    )
    np.testing.assert_allclose(
        parted.normalized_flux(), single.normalized_flux(), atol=1e-11
    )
    sigma = np.array([[0.0, 0.0], [1.0, 2.0], [0.5, 0.25]])
    np.testing.assert_allclose(
        parted.reaction_rate(sigma), single.reaction_rate(sigma),
        atol=1e-11,
    )
    assert parted.total_segments == single.total_segments


def test_partitioned_tally_writes_vtk(mesh, tmp_path):
    cfg = TallyConfig(n_groups=1, dtype=jnp.float64)
    t = PartitionedTally(mesh, 64, cfg, n_parts=8)
    rng = np.random.default_rng(1)
    pos = rng.uniform(0.1, 0.9, (64, 3))
    t.initialize_particle_location(pos.ravel().copy())
    buf = np.clip(pos + 0.2, 0.0, 1.0).ravel().copy()
    t.move_to_next_location(
        buf, np.ones(64, np.int8), np.ones(64),
        np.zeros(64, np.int32), np.zeros(64, np.int32),
    )
    t.write_pumi_tally_mesh(str(tmp_path / "part_flux.vtu"))
    body = (tmp_path / "part_flux.vtu").read_text()
    assert "flux_group_0" in body and "volume" in body
    assert t.total_rounds >= 1 and t.iter_count == 1
    # Group range validation mirrors the single-chip facade.
    with pytest.raises(ValueError, match="group"):
        t.move_to_next_location(
            buf, np.ones(64, np.int8), np.ones(64),
            np.full(64, 5, np.int32), np.zeros(64, np.int32),
        )


def test_partitioned_checkpoint_roundtrip_across_layouts(mesh, tmp_path):
    """A checkpoint written by an 8-part halo-1 run must resume under a
    DIFFERENT layout — another halo depth AND another part count — with
    identical assembled flux and identical continued accumulation: the
    stored flux is global, the slab layout is derived state (the
    save_partitioned_checkpoint docstring's promise, pinned here)."""
    cfg = TallyConfig(n_groups=2, dtype=jnp.float64, tolerance=1e-8)
    rng = np.random.default_rng(23)
    pos = rng.uniform(0.05, 0.95, (N, 3))
    dest1 = np.clip(pos + rng.normal(0, 0.2, (N, 3)), 0.0, 1.0)
    dest2 = np.clip(dest1 + rng.normal(0, 0.2, (N, 3)), 0.0, 1.0)
    w = np.ones(N)
    g = np.zeros(N, np.int32)

    def move(t, d):
        buf = d.ravel().copy()
        t.move_to_next_location(
            buf, np.ones(N, np.int8), w, g, np.zeros(N, np.int32)
        )
        return buf

    a = PartitionedTally(mesh, N, cfg, n_parts=8, halo_layers=1)
    a.initialize_particle_location(pos.ravel().copy())
    move(a, dest1)
    a.save_checkpoint(str(tmp_path / "ck"))

    b = PartitionedTally(mesh, N, cfg, n_parts=8, halo_layers=2)
    b.restore_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_allclose(b.raw_flux, a.raw_flux, rtol=0, atol=0)
    assert (b.iter_count, b.total_segments) == (
        a.iter_count, a.total_segments,
    )
    np.testing.assert_array_equal(b.elem_global, a.elem_global)

    # A different PART COUNT (4 chips, halo-2) resumes identically too.
    d = PartitionedTally(mesh, N, cfg, n_parts=4, halo_layers=2)
    d.restore_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_allclose(d.raw_flux, a.raw_flux, rtol=0, atol=0)
    np.testing.assert_array_equal(d.elem_global, a.elem_global)

    # Continued accumulation agrees exactly across the layouts.
    out_a = move(a, dest2)
    out_b = move(b, dest2)
    out_d = move(d, dest2)
    np.testing.assert_allclose(out_b, out_a, atol=1e-12)
    np.testing.assert_allclose(out_d, out_a, atol=1e-12)
    np.testing.assert_allclose(b.raw_flux, a.raw_flux, rtol=0, atol=1e-12)
    np.testing.assert_allclose(d.raw_flux, a.raw_flux, rtol=0, atol=1e-12)

    # Mismatched mesh is rejected.
    other = TetMesh.from_numpy(
        *build_box_arrays(1, 1, 1, 3, 3, 3), dtype=jnp.float64
    )
    c = PartitionedTally(other, N, cfg, n_parts=8)
    with pytest.raises(ValueError, match="different mesh"):
        c.restore_checkpoint(str(tmp_path / "ck"))


def test_partitioned_tally_intersection_points_matches_single(mesh):
    """The facade's intersection_points() must equal PumiTally's for the
    same moves (getIntersectionPoints parity over the partitioned walk),
    with parked lanes recording nothing."""
    cfg = TallyConfig(
        n_groups=2, dtype=jnp.float64, tolerance=1e-8, record_xpoints=6
    )
    single = PumiTally(mesh, N, cfg)
    parted = PartitionedTally(mesh, N, cfg, n_parts=8, halo_layers=1)
    rng = np.random.default_rng(31)
    pos = rng.uniform(0.05, 0.95, (N, 3))
    dest = np.clip(pos + rng.normal(0, 0.3, (N, 3)), -0.1, 1.1)
    flying = np.ones(N, np.int8)
    flying[::5] = 0
    for t in (single, parted):
        t.initialize_particle_location(pos.ravel().copy())
        buf = dest.ravel().copy()
        t.move_to_next_location(
            buf, flying.copy(), np.ones(N),
            np.zeros(N, np.int32), np.zeros(N, np.int32),
        )
    xp_s, c_s = single.intersection_points()
    xp_p, c_p = parted.intersection_points()
    np.testing.assert_array_equal(c_p, c_s)
    np.testing.assert_allclose(xp_p, xp_s, atol=1e-12)
    assert c_s[flying == 0].max() == 0 if (flying == 0).any() else True
    assert c_s.max() >= 2


def test_partitioned_batch_sd_matches_pumitally(mesh):
    """sd_mode='batch' over the partitioned walk: the per-chip
    elementwise fold of owned-slab deltas must reproduce PumiTally's
    batch statistics exactly (halo scores are on owner rows at step
    end, so the owned-row delta IS the move's bin total)."""
    cfg = TallyConfig(
        n_groups=2, dtype=jnp.float64, tolerance=1e-8, sd_mode="batch"
    )
    single = PumiTally(mesh, N, cfg)
    parted = PartitionedTally(mesh, N, cfg, n_parts=8, halo_layers=1)
    _drive(single, moves=3)
    _drive(parted, moves=3)
    np.testing.assert_allclose(
        parted.raw_flux, np.asarray(single.raw_flux), rtol=0, atol=1e-11
    )
    np.testing.assert_allclose(
        parted.normalized_flux(), single.normalized_flux(), atol=1e-11
    )
    # Segment-mode mean must equal batch-mode mean (same walk).
    seg = PartitionedTally(
        mesh, N,
        TallyConfig(n_groups=2, dtype=jnp.float64, tolerance=1e-8),
        n_parts=8, halo_layers=1,
    )
    _drive(seg, moves=3)
    np.testing.assert_array_equal(
        seg.raw_flux[..., 0], parted.raw_flux[..., 0]
    )
    assert not np.array_equal(
        seg.raw_flux[..., 1], parted.raw_flux[..., 1]
    )
    with pytest.raises(NotImplementedError):
        parted.reaction_rate(np.ones((3, 2)))
