"""Self-verifying tallies (pumiumtally_tpu/integrity/): on-device
conservation invariants, shadow audits, escalation policy, dispatch
watchdog — and the fault-injection modes that prove each detector by
corrupting and catching (ISSUE 4 acceptance):

  * ``bitflip_flux``  → on-device flux invariant (next move);
  * ``sdc_walk``      → float64 shadow-audit re-walk;
  * ``hang_at_move``  → watchdog deadline + ResilientRunner re-arm;
  * ``nan_src``       → PR 2 quarantine, with the invariants staying
                        clean around it.

Plus: integrity="off" reproduces default outputs bit-identically (and
so does "warn" — the checks read, never write), the invariant scalars
agree with host-computed oracle sums on jittered meshes across dtypes
and all three io_pipeline modes, and the checkpoint-directory fsync
durability fix.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import (
    CheckpointStore,
    DispatchTimeoutError,
    FatalIntegrityViolation,
    PumiTally,
    ResilientRunner,
    TallyConfig,
    TransientIntegrityViolation,
    build_box,
)
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally
from pumiumtally_tpu.resilience.faultinject import parse_faults

N = 64


@pytest.fixture
def no_io_pipeline_env(monkeypatch):
    """The CI integrity step runs this file under
    PUMI_TPU_IO_PIPELINE=overlap so the fault-detection tests genuinely
    exercise the deepest pipeline (detection rides the packed readback
    tail + deferred folds there). ONLY the tests that parametrize
    io_pipeline themselves opt into dropping the override, so their
    field wins; everything else inherits the CI mode."""
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=jnp.float64)


def _jittered(nx, jitter, seed, dtype):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, nx, nx, nx)
    rng = np.random.default_rng(seed)
    interior = (
        (coords > 1e-9).all(axis=1) & (coords < 1 - 1e-9).all(axis=1)
    )
    coords = coords.copy()
    coords[interior] += rng.uniform(
        -jitter / nx, jitter / nx, (interior.sum(), 3)
    )
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    return TetMesh.from_numpy(coords, tets, cid, dtype=dtype)


def _inputs(rng, n=N):
    return (
        rng.uniform(0.05, 0.95, (n, 3)).ravel().copy(),
        np.ones(n, np.int8),
        rng.uniform(0.5, 2.0, n),
        rng.integers(0, 2, n).astype(np.int32),
        np.full(n, -1, np.int32),
    )


def _drive(t, moves=3, seed=42, n=N):
    rng = np.random.default_rng(seed)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (n, 3)).ravel())
    outs = []
    for _ in range(moves):
        dest, fly, w, g, mats = _inputs(rng, n)
        t.move_to_next_location(dest, fly, w, g, mats)
        outs.append((dest.reshape(n, 3).copy(), mats.copy()))
    return outs


# ===================================================================== #
# Bit-identity: off == today's default, and the checks never write
# ===================================================================== #
def test_integrity_off_and_warn_bit_identical(mesh):
    base = PumiTally(mesh, N, TallyConfig(dtype=jnp.float64))
    off = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, integrity="off")
    )
    warn = PumiTally(
        mesh, N,
        TallyConfig(dtype=jnp.float64, integrity="warn", audit_lanes=4),
    )
    outs = {id(t): _drive(t) for t in (base, off, warn)}
    for t in (off, warn):
        for (pa, ma), (pb, mb) in zip(outs[id(base)], outs[id(t)]):
            np.testing.assert_array_equal(pb, pa)
            np.testing.assert_array_equal(mb, ma)
        np.testing.assert_array_equal(t.raw_flux, base.raw_flux)
        np.testing.assert_array_equal(t.element_ids, base.element_ids)
    # The audited run actually audited, and cleanly.
    tm = warn.telemetry()["integrity"]
    assert tm["audited_lanes"] > 0 and tm["audit_mismatches"] == 0
    assert tm["violations"] == {}


# ===================================================================== #
# Satellite: invariant scalars vs host oracle sums — jittered meshes,
# both dtypes, all three pipelines
# ===================================================================== #
@pytest.mark.parametrize("io", ["legacy", "packed", "overlap"])
@pytest.mark.parametrize("dtype,tol", [
    (jnp.float64, 1e-9),
    (jnp.float32, 2e-3),
])
def test_conservation_invariants_match_oracle(
    io, dtype, tol, no_io_pipeline_env
):
    mesh = _jittered(5, 0.15, seed=11, dtype=dtype)
    n = 256
    t = PumiTally(
        mesh, n,
        TallyConfig(
            dtype=dtype, tolerance=1e-6, integrity="warn",
            io_pipeline=io, n_groups=2,
        ),
    )
    rng = np.random.default_rng(4)
    cents = np.asarray(mesh.centroids())
    pos = cents[rng.integers(0, mesh.ntet, n)].astype(np.float64)
    t.initialize_particle_location(pos.ravel().copy())
    prev_pos = pos
    prev_flux = t.raw_flux[..., 0].sum()
    for mv in range(1, 3):
        dest, fly, w, g, mats = _inputs(rng, n)
        t.move_to_next_location(dest, fly, w, g, mats)
        out = dest.reshape(n, 3)
        rec = [
            r for r in t.telemetry()["per_move"]
            if r["kind"] == "integrity" and r["move"] == mv
        ][-1]
        assert rec["violations"] == []
        assert rec["lanes_flying"] == n and rec["lanes_done"] == n
        # Oracle: Σ w·|final − origin| from the caller-visible copy-back
        # buffers — test_tally_oracle's reference-sum identity.
        oracle = float((w * np.linalg.norm(out - prev_pos, axis=1)).sum())
        scale = max(1.0, oracle)
        assert rec["path_wlen"] == pytest.approx(oracle, abs=tol * scale)
        assert rec["scored_wlen"] == pytest.approx(
            oracle, abs=tol * scale
        )
        # And against the flux accumulator itself: the move's scored
        # weighted length is exactly the move's Σc delta.
        flux_now = t.raw_flux[..., 0].sum()
        assert rec["scored_wlen"] == pytest.approx(
            float(flux_now - prev_flux), abs=tol * scale
        )
        prev_pos, prev_flux = out.copy(), flux_now


# ===================================================================== #
# bitflip_flux → on-device flux invariant
# ===================================================================== #
def test_bitflip_flux_detected_and_warned(mesh, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_FAULTS", "bitflip_flux:1")
    t = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, integrity="warn")
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    t.move_to_next_location(*_inputs(rng))  # flip lands after move 1
    with pytest.warns(RuntimeWarning, match="integrity violation"):
        t.move_to_next_location(*_inputs(rng))
    tm = t.telemetry()["integrity"]
    assert tm["violations"].get("flux", 0) >= 1
    inj = t.metrics.counter("pumi_injected_faults_total")
    assert inj.value(kind="bitflip_flux") == 1


def test_bitflip_flux_halt_flushes_last_good(mesh, monkeypatch, tmp_path):
    monkeypatch.setenv("PUMI_TPU_FAULTS", "bitflip_flux:1")
    t = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, integrity="halt")
    )
    rng = np.random.default_rng(42)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False, sleep=lambda s: None,
    )
    run.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    run.move_to_next_location(*_inputs(rng))
    with pytest.raises(FatalIntegrityViolation) as exc:
        run.move_to_next_location(*_inputs(rng))
    assert "flux" in exc.value.checks
    # The flushed generation is the last GOOD state (post-move-1, taken
    # before the flip could be detected but from the retry anchor that
    # predates the violation surfacing), never the suspect one.
    latest = run.store.find_latest()
    assert latest is not None and latest[0] == 1


def test_bitflip_retry_policy_exhausts_and_propagates(
    mesh, monkeypatch, tmp_path
):
    """integrity="retry" under at-rest corruption: the corruption is in
    the snapshot too, so every replay re-trips — the bounded retries
    exhaust and the violation propagates (fail-safe, never an infinite
    loop)."""
    monkeypatch.setenv("PUMI_TPU_FAULTS", "bitflip_flux:1")
    t = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, integrity="retry")
    )
    rng = np.random.default_rng(42)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False, max_retries=2, sleep=lambda s: None,
    )
    run.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    run.move_to_next_location(*_inputs(rng))
    with pytest.raises(TransientIntegrityViolation):
        run.move_to_next_location(*_inputs(rng))
    assert t.metrics.counter("pumi_move_retries_total").value() == 2


# ===================================================================== #
# sdc_walk → shadow audit
# ===================================================================== #
def test_sdc_walk_caught_by_shadow_audit(mesh, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_FAULTS", "sdc_walk:2")
    t = PumiTally(
        mesh, N,
        TallyConfig(dtype=jnp.float64, integrity="warn", audit_lanes=4),
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    t.move_to_next_location(*_inputs(rng))  # clean audit
    with pytest.warns(RuntimeWarning, match="sdc_audit"):
        t.move_to_next_location(*_inputs(rng))
    tm = t.telemetry()["integrity"]
    assert tm["violations"].get("sdc_audit", 0) == 1
    assert tm["audit_mismatches"] == 1
    assert tm["audited_lanes"] >= 8  # both moves audited
    # Per-move audit outcomes land in the flight recorder.
    audits = [
        r for r in t.telemetry()["per_move"] if r["kind"] == "audit"
    ]
    assert [a["mismatches"] for a in audits] == [0, 1]
    inj = t.metrics.counter("pumi_injected_faults_total")
    assert inj.value(kind="sdc_walk") == 1


# ===================================================================== #
# hang_at_move → dispatch watchdog
# ===================================================================== #
def test_hang_watchdog_rearm_bitwise_identical(
    mesh, monkeypatch, tmp_path
):
    """The ISSUE 4 watchdog contract: a hung dispatch surfaces as a
    retryable timeout, the supervisor re-arms and replays, and the
    completed run is bitwise-identical to an undisturbed one."""
    ref = PumiTally(mesh, N, TallyConfig(dtype=jnp.float64))
    ref_outs = _drive(ref, moves=3, seed=9)

    monkeypatch.setenv(
        "PUMI_TPU_FAULTS", "hang_at_move:2,hang_seconds:1.0"
    )
    t = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, move_deadline_s=0.25)
    )
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False, sleep=lambda s: None,
    )
    rng = np.random.default_rng(9)
    run.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    outs = []
    for _ in range(3):
        dest, fly, w, g, mats = _inputs(rng)
        run.move_to_next_location(dest, fly, w, g, mats)
        outs.append((dest.reshape(N, 3).copy(), mats.copy()))
    assert t.metrics.counter("pumi_move_retries_total").value() == 1
    assert t.telemetry()["integrity"]["violations"]["watchdog"] == 1
    for (pa, ma), (pb, mb) in zip(ref_outs, outs):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(
        np.asarray(t.raw_flux), np.asarray(ref.raw_flux)
    )


def test_hang_without_runner_propagates_timeout(mesh, monkeypatch):
    monkeypatch.setenv(
        "PUMI_TPU_FAULTS", "hang_at_move:2,hang_seconds:1.0"
    )
    t = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, move_deadline_s=0.25)
    )
    rng = np.random.default_rng(3)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    t.move_to_next_location(*_inputs(rng))  # warm-up (deadline unarmed)
    with pytest.raises(DispatchTimeoutError):
        t.move_to_next_location(*_inputs(rng))


def test_deadline_passes_on_healthy_moves(mesh):
    """A generous deadline around healthy dispatches must never fire
    and must not perturb results."""
    ref = PumiTally(mesh, N, TallyConfig(dtype=jnp.float64))
    t = PumiTally(
        mesh, N, TallyConfig(dtype=jnp.float64, move_deadline_s=30.0)
    )
    ref_outs = _drive(ref, moves=2, seed=5)
    outs = _drive(t, moves=2, seed=5)
    for (pa, ma), (pb, mb) in zip(ref_outs, outs):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(t.raw_flux, ref.raw_flux)
    assert "watchdog" not in t.telemetry()["integrity"]["violations"]


# ===================================================================== #
# nan_src (the PR 2 mode) under the integrity layer
# ===================================================================== #
def test_nan_src_quarantined_with_clean_invariants(
    mesh, monkeypatch, tmp_path
):
    """The existing nan_src detector (quarantine) composes with the
    invariants: bad lanes are parked and counted, the lane-conservation
    check still closes around them, and no violation fires."""
    monkeypatch.setenv("PUMI_TPU_FAULTS", "nan_src:0.3,seed:7")
    t = PumiTally(
        mesh, N,
        TallyConfig(dtype=jnp.float64, integrity="warn", quarantine=True),
    )
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False, sleep=lambda s: None,
    )
    rng = np.random.default_rng(42)
    run.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    for _ in range(2):
        run.move_to_next_location(*_inputs(rng))
    tm = t.telemetry()
    assert tm["quarantined"] > 0
    assert np.isfinite(np.asarray(t.raw_flux)).all()
    assert tm["integrity"]["violations"] == {}


# ===================================================================== #
# Partitioned facade
# ===================================================================== #
@pytest.mark.parametrize("io", ["legacy", "packed"])
def test_partitioned_invariants_clean_and_oracle(io, no_io_pipeline_env):
    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=jnp.float64)
    t = PartitionedTally(
        mesh, N,
        TallyConfig(
            dtype=jnp.float64, integrity="warn", audit_lanes=4,
            io_pipeline=io,
        ),
        n_parts=4, halo_layers=1,
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    pos_before = t.positions.copy()
    dest, fly, w, g, mats = _inputs(rng)
    t.move_to_next_location(dest, fly, w, g, mats)
    tm = t.telemetry()
    assert tm["integrity"]["violations"] == {}
    assert tm["integrity"]["audit_mismatches"] == 0
    assert tm["integrity"]["audited_lanes"] > 0
    rec = [
        r for r in tm["per_move"]
        if r["kind"] == "integrity" and r["move"] == 1
    ][-1]
    oracle = float(
        (w * np.linalg.norm(
            dest.reshape(N, 3) - pos_before, axis=1
        )).sum()
    )
    assert rec["scored_wlen"] == pytest.approx(oracle, abs=1e-9 * max(1, oracle))
    assert rec["path_wlen"] == pytest.approx(oracle, abs=1e-9 * max(1, oracle))
    assert rec["lanes_flying"] == N and rec["lanes_done"] == N


def test_partitioned_bitflip_detected(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_FAULTS", "bitflip_flux:1")
    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=jnp.float64)
    t = PartitionedTally(
        mesh, N, TallyConfig(dtype=jnp.float64, integrity="warn"),
        n_parts=4,
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    t.move_to_next_location(*_inputs(rng))
    with pytest.warns(RuntimeWarning, match="integrity violation"):
        t.move_to_next_location(*_inputs(rng))
    assert t.telemetry()["integrity"]["violations"].get("flux", 0) >= 1


# ===================================================================== #
# Fault grammar + config validation
# ===================================================================== #
def test_new_fault_grammar():
    p = parse_faults(
        "bitflip_flux:2,sdc_walk:3,hang_at_move:4,hang_seconds:0.5"
    )
    assert (p.bitflip_flux, p.sdc_walk, p.hang_at_move) == (2, 3, 4)
    assert p.hang_seconds == 0.5 and p.any()
    with pytest.raises(ValueError, match="hang_seconds"):
        parse_faults("hang_seconds:0")
    with pytest.raises(ValueError, match="unknown fault"):
        parse_faults("bitflip:1")


def test_config_validation():
    assert TallyConfig().resolve_integrity() == "off"
    assert TallyConfig(integrity="warn").resolve_integrity() == "warn"
    with pytest.raises(ValueError, match="integrity"):
        TallyConfig(integrity="maybe").resolve_integrity()
    with pytest.raises(ValueError, match="ledger"):
        TallyConfig(integrity="warn", ledger=False).resolve_integrity()
    with pytest.raises(ValueError, match="ledger"):
        TallyConfig(audit_lanes=4, ledger=False).resolve_integrity()
    with pytest.raises(ValueError, match="audit_every"):
        TallyConfig(audit_every=0).resolve_integrity()
    with pytest.raises(ValueError, match="move_deadline_s"):
        TallyConfig(move_deadline_s=0.0).resolve_integrity()


# ===================================================================== #
# Satellite: checkpoint-directory durability (fsync after rotation)
# ===================================================================== #
def test_rotation_fsyncs_directory(mesh, tmp_path, monkeypatch):
    """CheckpointStore rotation must fsync the directory after keep-N
    deletions — without it a power cut can resurrect a rotated-out
    generation while losing the newest rename."""
    import pumiumtally_tpu.resilience.store as store_mod

    calls = []
    monkeypatch.setattr(
        store_mod, "fsync_dir", lambda d: calls.append(d)
    )
    store = CheckpointStore(str(tmp_path / "cks"), keep=1)
    t = PumiTally(mesh, N, TallyConfig(dtype=jnp.float64))
    rng = np.random.default_rng(0)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    store.save(t)
    assert not calls  # nothing rotated out yet
    t.move_to_next_location(*_inputs(rng))
    store.save(t)  # generation 0 rotated out → directory fsync
    assert calls == [store.directory]
    assert [it for it, _ in store.entries()] == [1]
