"""Mesh core tests: box generator connectivity against the reference's
analytic 6-tet oracle (test_pumi_tally_impl_methods.cpp:31-110), adjacency
invariants, volumes."""
import jax.numpy as jnp
import numpy as np
import pytest

from pumiumtally_tpu.mesh.box import build_box, build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh, build_tet2tet
from pumiumtally_tpu.ops.geometry import locate_points, point_in_tet


@pytest.fixture(scope="module")
def unit_box():
    return build_box(dtype=jnp.float64)


def test_unit_box_counts(unit_box):
    # Omega_h build_box(1,1,1,1,1,1): 8 vertices, 6 tets (test:70-71).
    assert unit_box.nverts == 8
    assert unit_box.ntet == 6


def test_unit_box_volumes(unit_box):
    vols = np.asarray(unit_box.volumes)
    np.testing.assert_allclose(vols, 1.0 / 6.0, atol=1e-12)
    assert vols.sum() == pytest.approx(1.0, abs=1e-12)


def test_elem0_centroid_matches_reference(unit_box):
    # The reference seeds particles at elem 0's centroid (0.5, 0.75, 0.25)
    # (test:84); this pins the build_box element ordering.
    c = np.asarray(unit_box.centroids())
    np.testing.assert_allclose(c[0], [0.5, 0.75, 0.25], atol=1e-12)


def test_oracle_point_locations(unit_box):
    # Parent elements asserted by the reference white-box test:
    # (0.1,0.4,0.5) in elem 2 (test:158); the +x ray spans elems 2,3,4
    # (test:282-284); (0.15,0.05,0.2) in 3, (0.85,0.05,0.1) in 4
    # (test:361-365).
    pts = jnp.asarray(
        [
            [0.1, 0.4, 0.5],
            [0.45, 0.4, 0.5],
            [0.7, 0.4, 0.5],
            [0.15, 0.05, 0.2],
            [0.85, 0.05, 0.1],
        ],
        dtype=jnp.float64,
    )
    elems = np.asarray(locate_points(unit_box, pts, tol=1e-12))
    np.testing.assert_array_equal(elems, [2, 3, 4, 3, 4])


def test_outside_point_not_located(unit_box):
    pts = jnp.asarray([[1.5, 0.5, 0.5], [-0.1, 0.2, 0.2]], dtype=jnp.float64)
    elems = np.asarray(locate_points(unit_box, pts, tol=1e-12))
    np.testing.assert_array_equal(elems, [-1, -1])


def test_point_in_tet(unit_box):
    pts = jnp.asarray([[0.1, 0.4, 0.5]], dtype=jnp.float64)
    assert bool(point_in_tet(unit_box, jnp.asarray([2]), pts, 1e-12)[0])
    assert not bool(point_in_tet(unit_box, jnp.asarray([0]), pts, 1e-12)[0])


def test_unit_box_boundary_faces(unit_box):
    # A cube's surface triangulates into 12 boundary faces; the 6 interior
    # face-pairs must be mutual.
    t2t = np.asarray(unit_box.tet2tet)
    assert (t2t == -1).sum() == 12
    for e in range(6):
        for f in range(4):
            nb = t2t[e, f]
            if nb >= 0:
                assert e in t2t[nb]


@pytest.mark.parametrize("dims", [(2, 2, 2), (3, 1, 2)])
def test_multicell_box(dims):
    nx, ny, nz = dims
    mesh = build_box(2.0, 1.0, 1.5, nx, ny, nz, dtype=jnp.float64)
    assert mesh.ntet == 6 * nx * ny * nz
    np.testing.assert_allclose(
        np.asarray(mesh.volumes).sum(), 2.0 * 1.0 * 1.5, atol=1e-10
    )
    t2t = np.asarray(mesh.tet2tet)
    # Mutual adjacency everywhere.
    for e in range(mesh.ntet):
        for f in range(4):
            nb = t2t[e, f]
            if nb >= 0:
                assert e in t2t[nb]
    # Every point interior to the box is locatable.
    rng = np.random.default_rng(0)
    pts = rng.uniform(0.05, 0.95, size=(50, 3)) * np.array([2.0, 1.0, 1.5])
    elems = np.asarray(locate_points(mesh, jnp.asarray(pts), tol=1e-12))
    assert (elems >= 0).all()


def test_orientation_canonicalization():
    coords, tet2vert = build_box_arrays()
    # Scramble vertex order of each tet; volumes must still come out positive.
    rng = np.random.default_rng(1)
    scrambled = np.stack(
        [tet2vert[i, rng.permutation(4)] for i in range(len(tet2vert))]
    )
    mesh = TetMesh.from_numpy(coords, scrambled, dtype=jnp.float64)
    assert (np.asarray(mesh.volumes) > 0).all()
    # Adjacency is permutation-invariant.
    ref = build_tet2tet(tet2vert)
    got = np.asarray(mesh.tet2tet)
    for e in range(6):
        assert set(got[e]) == set(ref[e])
