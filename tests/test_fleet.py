"""Serving-fleet contracts (the ISSUE 17 robustness tentpole:
network ingress + multi-chip fleet with crash-safe routing and
cross-chip migration).

Contracts pinned here:

  * IDEMPOTENT SUBMISSION — a resubmitted ``idempotency_key`` maps to
    the ORIGINAL job id without touching any scheduler, and the proof
    is journaled: FLEET.json's ``accepted`` map is flushed before the
    job reaches a member (idempotency-record-before-accept), so the
    dedup survives a router crash + recovery.
  * PLACEMENT — jobs spread across members least-loaded-first, with
    shape-class warmth as the tiebreak; per-member placement counts
    balance for a uniform workload.
  * MEMBER DEATH — ``kill_member`` re-places the dead member's
    JOURNALED jobs onto survivors: zero lost, zero duplicated, and
    survivors' fluxes stay bitwise vs the fault-free fleet.
  * CROSS-CHIP MIGRATION — ``migrate`` checkpoint-preempts on the
    source, adopts on the target, and the finished flux is bitwise vs
    the uninterrupted fleet; the hop is observable (``migrated`` trace
    link + ``pumi_jobs_recovered_total{source="migrated"}``).
  * GATEWAY VALIDATION — malformed JSON and path-unsafe job ids are
    400s before any filesystem name could be formed; unknown jobs are
    404s; unknown paths teach the route list; cancel is idempotent
    (false on terminal jobs) and a cancelled job's result is a 409.
  * TORN ROUTING JOURNAL — an unreadable or wrong-schema FLEET.json
    is rejected loudly (the atomic writer cannot tear it, so garbage
    means foreign writes); recovery never silently re-runs over it.

Compile budget: the fast core (-m 'not slow') keeps the routing /
journal-grammar / gateway-validation tests — submission only enqueues,
so none of them compile.  Everything that drains real quanta (bitwise
migration / member-kill / recovery) is marked slow and runs in the CI
fleet step beside scripts/chaos_fleet.py.
"""
from __future__ import annotations

import dataclasses
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from pumiumtally_tpu import TallyConfig, build_box
from pumiumtally_tpu.serving import (
    FleetJournal,
    FleetRouter,
    TallyGateway,
    decode_result,
    synthetic_requests,
)
from pumiumtally_tpu.serving.fleet import FLEET_FILE, FLEET_SCHEMA
from pumiumtally_tpu.serving.journal import request_to_json

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """Fleet contracts drive faults/ports explicitly — scrub any CI
    sweep's env overrides (PUMI_TPU_FAULTS feeds the scheduler's
    default injector; PROM_PORT would bind real sockets per router)."""
    for var in (
        "PUMI_TPU_MEGASTEP", "PUMI_TPU_KERNEL", "PUMI_TPU_IO_PIPELINE",
        "PUMI_TPU_TUNING", "PUMI_TPU_AOT_FAULT", "PUMI_TPU_PROM_PORT",
        "PUMI_TPU_FAULTS",
    ):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2)


def _cfg(**kw):
    return TallyConfig(tolerance=1e-6, **kw)


def _router(tmp_path, mesh, n_members=2, **kw):
    kw.setdefault("quantum_moves", 2)
    kw.setdefault("max_resident", 2)
    return FleetRouter(
        mesh, _cfg(), fleet_dir=str(tmp_path / "fleet"),
        n_members=n_members, bank=None, **kw,
    )


def _reference_results(tmp_path, mesh, requests, **kw):
    """Fault-free fleet run of the same requests — the bitwise
    reference the chaos'd fleets must match."""
    ref = FleetRouter(
        mesh, _cfg(), fleet_dir=str(tmp_path / "ref"), n_members=2,
        bank=None, quantum_moves=2, max_resident=2, **kw,
    )
    try:
        for r in requests:
            ref.submit(r, idempotency_key=f"key-{r.job_id}")
        ref.run()
        return {r.job_id: np.asarray(ref.result(r.job_id)).copy()
                for r in requests}
    finally:
        ref.close()


# --------------------------------------------------------------------- #
# Idempotent submission + the journaled proof
# --------------------------------------------------------------------- #
def test_idempotent_resubmit_same_id_and_journaled(tmp_path, mesh):
    router = _router(tmp_path, mesh)
    try:
        req = synthetic_requests(mesh, 1, class_sizes=(24,))[0]
        first = router.submit(req, idempotency_key="key-a")
        # The SAME key resubmitted (even with a different payload —
        # acceptance is decided by the journaled map alone) returns
        # the original id and starts nothing new.
        other = dataclasses.replace(req, job_id=None)
        again = router.submit(other, idempotency_key="key-a")
        assert again == first
        assert len(router.jobs()) == 1
        assert router.stats()["placements"] == {
            "member-0": 1, "member-1": 0,
        }
        # The journaled proof: the accepted map is ON DISK (flushed
        # before placement — idempotency-record-before-accept), so
        # the dedup decision survives a router crash.
        doc = FleetJournal(router.journal.dir).load()
        assert doc["accepted"] == {"key-a": first}
        assert first in doc["assignments"]
        assert doc["n_submitted"] == 1
    finally:
        router.close()


def test_submission_validation(tmp_path, mesh):
    router = _router(tmp_path, mesh)
    try:
        req = synthetic_requests(mesh, 1, class_sizes=(24,))[0]
        with pytest.raises(ValueError, match="journal-safe"):
            router.submit(req, idempotency_key="../escape")
        with pytest.raises(ValueError, match="journal-safe"):
            router.submit(req, idempotency_key="")
        router.submit(req)
        with pytest.raises(ValueError, match="duplicate job id"):
            router.submit(req)  # same explicit job_id
        # A rejected request must NOT journal its key: the next use
        # of the key is a fresh acceptance, not a dedup hit.
        doc = FleetJournal(router.journal.dir).load()
        assert doc["accepted"] == {}
    finally:
        router.close()


# --------------------------------------------------------------------- #
# Placement
# --------------------------------------------------------------------- #
def test_placement_balances_across_members(tmp_path, mesh):
    router = _router(tmp_path, mesh, n_members=4)
    try:
        for r in synthetic_requests(mesh, 8, class_sizes=(24,)):
            router.submit(r)
        placed = [m.placed for m in router.members]
        assert placed == [2, 2, 2, 2]
        owners = {router.member_of(f"sat-{i:04d}") for i in range(8)}
        assert owners == {0, 1, 2, 3}
    finally:
        router.close()


def test_placement_prefers_warm_member_on_load_tie(tmp_path, mesh):
    router = _router(tmp_path, mesh, n_members=2)
    try:
        reqs = synthetic_requests(mesh, 3, class_sizes=(24, 130, 24))
        assert router.member_of(router.submit(reqs[0])) == 0
        assert router.member_of(router.submit(reqs[1])) == 1
        # Load tie (1 job each) — member 0 is warm for the small
        # class, so warmth breaks the tie in its favor.
        assert router.member_of(router.submit(reqs[2])) == 0
    finally:
        router.close()


# --------------------------------------------------------------------- #
# Torn / foreign routing journal
# --------------------------------------------------------------------- #
def test_torn_fleet_journal_rejected(tmp_path, mesh):
    fdir = tmp_path / "torn"
    fdir.mkdir()
    (fdir / FLEET_FILE).write_text('{"schema": 1, "members": 2, "acc')
    with pytest.raises(ValueError, match="not valid JSON"):
        FleetJournal(str(fdir)).load()
    with pytest.raises(ValueError, match="not valid JSON"):
        FleetRouter.recover(str(fdir), mesh, _cfg())


def test_wrong_schema_fleet_journal_rejected(tmp_path, mesh):
    fdir = tmp_path / "schema"
    fdir.mkdir()
    (fdir / FLEET_FILE).write_text(
        json.dumps({"schema": FLEET_SCHEMA + 1, "members": 2})
    )
    with pytest.raises(ValueError, match="schema"):
        FleetJournal(str(fdir)).load()


def test_recover_without_journal_rejected(tmp_path, mesh):
    with pytest.raises(ValueError, match="nothing to recover"):
        FleetRouter.recover(str(tmp_path / "empty"), mesh, _cfg())


# --------------------------------------------------------------------- #
# Gateway validation + cancel semantics (no quanta run: every job
# stays queued, so none of this compiles)
# --------------------------------------------------------------------- #
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(url, body: bytes):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_gateway_validation_and_cancel(tmp_path, mesh):
    router = _router(tmp_path, mesh)
    gateway = TallyGateway(router, port=0)
    try:
        url = gateway.url
        assert _get(f"{url}/healthz") == (200, {"ok": True})

        # Malformed / non-object bodies.
        status, body = _post(f"{url}/submit", b"{not json")
        assert status == 400 and "not JSON" in body["error"]
        status, body = _post(f"{url}/submit", b"[1, 2]")
        assert status == 400 and "JSON object" in body["error"]

        # Path-unsafe ids are refused before any filesystem name
        # could be formed from them (journal-grammar check_job_id).
        wire = request_to_json(
            synthetic_requests(mesh, 1, class_sizes=(24,))[0]
        )
        evil = dict(wire, job_id="..")
        status, body = _post(
            f"{url}/submit", json.dumps(evil).encode()
        )
        assert status == 400
        status, body = _post(
            f"{url}/submit",
            json.dumps(dict(wire, idempotency_key=7)).encode(),
        )
        assert status == 400 and "idempotency_key" in body["error"]
        status, body = _post(
            f"{url}/submit",
            json.dumps({"n_moves": 4, "source": {}}).encode(),
        )
        assert status == 400 and "bad request" in body["error"]
        # Over-long id in a GET path: rejected as a 400, not probed.
        status, body = _get(f"{url}/status/{'a' * 200}")
        assert status == 400
        status, _ = _get(f"{url}/result/{'a' * 200}")
        assert status == 400

        # Unknown jobs and unknown paths.
        status, body = _get(f"{url}/status/never-submitted")
        assert status == 404
        status, body = _get(f"{url}/nope")
        assert status == 404 and "POST /submit" in body["routes"]

        # A real submission: idempotent retry over the wire, then
        # status / premature result / cancel semantics.
        accepted = json.dumps(
            dict(wire, idempotency_key="key-g")
        ).encode()
        status, body = _post(f"{url}/submit", accepted)
        assert status == 200
        job = body["job"]
        status, body = _post(f"{url}/submit", accepted)
        assert (status, body["job"]) == (200, job)
        assert len(router.jobs()) == 1

        status, body = _get(f"{url}/status/{job}")
        assert status == 200
        assert body["state"] == "queued" and body["member"] == 0
        status, body = _get(f"{url}/result/{job}")
        assert status == 409  # no result yet — not an unknown job

        status, body = _post(f"{url}/cancel", b'{"job": "ghost"}')
        assert status == 404
        status, body = _post(f"{url}/cancel", b"{}")
        assert status == 400
        status, body = _post(
            f"{url}/cancel", json.dumps({"job": job}).encode()
        )
        assert (status, body["cancelled"]) == (200, True)
        # Idempotent: a second cancel reports false, never un-finishes.
        status, body = _post(
            f"{url}/cancel", json.dumps({"job": job}).encode()
        )
        assert (status, body["cancelled"]) == (200, False)
        status, body = _get(f"{url}/status/{job}")
        assert body["outcome"] == "cancelled"
        status, body = _get(f"{url}/result/{job}")
        assert status == 409
    finally:
        gateway.stop()
        router.close()


def test_exporter_mounts_fleet_endpoint(tmp_path, mesh, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    router = _router(tmp_path, mesh)
    try:
        assert router._exporter is not None
        base = f"http://127.0.0.1:{router._exporter.port}"
        with urllib.request.urlopen(f"{base}/buildz", timeout=30) as r:
            info = json.loads(r.read())
        assert "/fleet" in info["endpoints"]
        with urllib.request.urlopen(f"{base}/fleet", timeout=30) as r:
            fleet = json.loads(r.read())
        assert [m["member"] for m in fleet["members"]] == [0, 1]
        assert all(m["alive"] for m in fleet["members"])
        # Unknown scrape paths teach the mounted surface.
        try:
            urllib.request.urlopen(f"{base}/missing", timeout=30)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404 and "/fleet" in e.read().decode()
    finally:
        router.close()


# --------------------------------------------------------------------- #
# The slow half: real quanta — migration, member death, recovery
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_migration_bitwise_vs_uninterrupted(tmp_path, mesh):
    requests = synthetic_requests(
        mesh, 2, class_sizes=(24,), n_moves=8,
    )
    ref = _reference_results(tmp_path, mesh, requests)
    router = _router(tmp_path, mesh)
    try:
        for r in requests:
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        router.step()
        moving = next(j for j in router.jobs() if not j.terminal)
        src = router.member_of(moving.id)
        dst = router.migrate(moving.id)
        assert dst != src
        assert router.member_of(moving.id) == dst
        router.run()
        for r in requests:
            assert np.array_equal(
                np.asarray(router.result(r.job_id)), ref[r.job_id]
            ), f"{r.job_id} not bitwise across migration"
        stats = router.stats()
        assert stats["migrations"] == 1
        assert stats["outcomes"] == {"completed": 2}
        # The hop is observable: the migrated-source recovery counter
        # and the cross-member trace link both fire exactly once.
        # Member registries are per-scheduler now: the adopting member
        # owns the recovery count, so sum the fleet.
        assert sum(
            m.registry.counter(
                "pumi_jobs_recovered_total"
            ).value(source="migrated")
            for m in router.members
        ) == 1
        trace = [
            json.loads(line)
            for line in open(router.journal.trace_path())
            if line.strip()
        ]
        links = [t for t in trace if t.get("name") == "migrated"]
        assert [t["job_id"] for t in links] == [moving.id]
    finally:
        router.close()


@pytest.mark.slow
def test_member_kill_zero_lost_zero_duplicated(tmp_path, mesh):
    requests = synthetic_requests(
        mesh, 6, class_sizes=(24,), n_moves=6,
    )
    ref = _reference_results(tmp_path, mesh, requests)
    router = _router(tmp_path, mesh, n_members=3)
    try:
        for r in requests:
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        router.step()
        victim_jobs = [
            r.job_id for r in requests if router.member_of(r.job_id) == 0
        ]
        assert victim_jobs  # placement spread means member 0 owns some
        router.kill_member(0)
        assert not router.members[0].alive
        assert router.registry.gauge("pumi_fleet_members").value() == 2
        assert router.registry.gauge(
            "pumi_fleet_queue_depth"
        ).value(member="m0") == 0
        for jid in victim_jobs:
            assert router.member_of(jid) != 0
        router.run()
        # Zero lost, zero duplicated: every accepted job is owned by
        # exactly one alive member (jobs() walks all alive members, so
        # a stale duplicate would surface as a repeated id).
        ids = sorted(j.id for j in router.jobs())
        assert ids == sorted(r.job_id for r in requests)
        for r in requests:
            assert np.array_equal(
                np.asarray(router.result(r.job_id)), ref[r.job_id]
            ), f"{r.job_id} not bitwise across member death"
        stats = router.stats()
        assert stats["alive"] == 2
        assert stats["outcomes"] == {"completed": 6}
        assert stats["migrations"] >= len(victim_jobs)
    finally:
        router.close()


@pytest.mark.slow
def test_recovery_preserves_idempotency_keys(tmp_path, mesh):
    requests = synthetic_requests(
        mesh, 4, class_sizes=(24,), n_moves=6,
    )
    ref = _reference_results(tmp_path, mesh, requests)
    fdir = str(tmp_path / "fleet")
    router = FleetRouter(
        mesh, _cfg(), fleet_dir=fdir, n_members=2, bank=None,
        quantum_moves=2, max_resident=2,
    )
    accepted = {}
    for r in requests:
        accepted[r.job_id] = router.submit(
            r, idempotency_key=f"key-{r.job_id}"
        )
    router.step()
    router.abandon()  # crash model: no graceful flush
    router = FleetRouter.recover(
        fdir, mesh, _cfg(), bank=None,
        quantum_moves=2, max_resident=2,
    )
    try:
        # The client's retry storm after the crash: every key maps to
        # its pre-crash id (the journaled map is the arbiter) and no
        # second execution starts.
        for r in requests:
            assert router.submit(
                r, idempotency_key=f"key-{r.job_id}"
            ) == accepted[r.job_id]
        assert len(router.jobs()) == len(requests)
        router.run()
        for r in requests:
            assert np.array_equal(
                np.asarray(router.result(r.job_id)), ref[r.job_id]
            ), f"{r.job_id} not bitwise across router recovery"
        stats = router.stats()
        assert stats["recovered"] >= 1
        assert stats["outcomes"] == {"completed": len(requests)}
    finally:
        router.close()


@pytest.mark.slow
def test_result_roundtrip_bitwise_over_http(tmp_path, mesh):
    requests = synthetic_requests(
        mesh, 2, class_sizes=(24,), n_moves=4,
    )
    router = _router(tmp_path, mesh)
    gateway = TallyGateway(router, port=0)
    try:
        for r in requests:
            wire = dict(
                request_to_json(r), idempotency_key=f"key-{r.job_id}"
            )
            status, body = _post(
                f"{gateway.url}/submit", json.dumps(wire).encode()
            )
            assert (status, body["job"]) == (200, r.job_id)
        router.run()
        for r in requests:
            status, body = _get(f"{gateway.url}/result/{r.job_id}")
            assert status == 200
            assert np.array_equal(
                decode_result(body), np.asarray(router.result(r.job_id))
            ), "HTTP result payload not bitwise vs in-process flux"
    finally:
        gateway.stop()
        router.close()
