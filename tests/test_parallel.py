"""Multi-chip tests on the virtual 8-device CPU mesh: sharded trace must
agree with the single-chip trace, and the lazy tally reduction must equal
the per-chip partial sums."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pumiumtally_tpu import build_box, make_flux, trace
from pumiumtally_tpu.parallel.particle_sharding import (
    make_device_mesh,
    make_sharded_flux,
    make_sharded_trace,
    reduce_flux,
    replicate,
    shard_particles,
)

N_DEV = 8


@pytest.fixture(scope="module")
def setup():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 CPU devices"
    mesh = build_box(1, 1, 1, 3, 3, 3, dtype=jnp.float64)
    dmesh = make_device_mesh(N_DEV)
    return mesh, dmesh


def _random_batch(n, rng):
    origin = rng.uniform(0.1, 0.9, (n, 3))
    dest = origin + rng.normal(scale=0.4, size=(n, 3))
    weight = rng.uniform(0.5, 2.0, n)
    group = rng.integers(0, 2, n)
    return origin, dest, weight, group


def test_sharded_trace_matches_single_chip(setup):
    mesh, dmesh = setup
    n = 64
    rng = np.random.default_rng(7)
    origin_h, dest_h, weight_h, group_h = _random_batch(n, rng)

    from pumiumtally_tpu.ops.geometry import locate_points

    elem_h = np.asarray(
        locate_points(mesh, jnp.asarray(origin_h), tol=1e-12)
    )
    assert (elem_h >= 0).all()

    # Single chip.
    r1 = trace(
        mesh,
        jnp.asarray(origin_h),
        jnp.asarray(dest_h),
        jnp.asarray(elem_h, jnp.int32),
        jnp.ones(n, bool),
        jnp.asarray(weight_h),
        jnp.asarray(group_h, jnp.int32),
        jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 2, jnp.float64),
        initial=False,
        max_crossings=mesh.ntet + 64,
    )

    # 8-way sharded.
    step = make_sharded_trace(
        dmesh, initial=False, max_crossings=mesh.ntet + 64
    )
    mesh_r = replicate(dmesh, mesh)
    origin, dest, elem, in_flight, weight, group, material = shard_particles(
        dmesh,
        jnp.asarray(origin_h),
        jnp.asarray(dest_h),
        jnp.asarray(elem_h, jnp.int32),
        jnp.ones(n, bool),
        jnp.asarray(weight_h),
        jnp.asarray(group_h, jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    flux = make_sharded_flux(dmesh, mesh.ntet, 2, jnp.float64)
    r8 = step(
        mesh_r, origin, dest, elem, in_flight, weight, group, material, flux
    )

    assert r8.flux.shape == (N_DEV, mesh.ntet, 2, 2)
    np.testing.assert_allclose(
        np.asarray(reduce_flux(r8.flux)), np.asarray(r1.flux), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(r8.position), np.asarray(r1.position), atol=1e-12
    )
    np.testing.assert_array_equal(np.asarray(r8.elem), np.asarray(r1.elem))
    np.testing.assert_array_equal(
        np.asarray(r8.material_id), np.asarray(r1.material_id)
    )
    assert int(r8.n_segments.sum()) == int(r1.n_segments)
    assert bool(np.asarray(r8.done).all())


@pytest.mark.slow
def test_sharded_flux_accumulates_across_steps(setup):
    mesh, dmesh = setup
    n = 32
    rng = np.random.default_rng(11)
    from pumiumtally_tpu.ops.geometry import locate_points

    step = make_sharded_trace(
        dmesh, initial=False, max_crossings=mesh.ntet + 64
    )
    mesh_r = replicate(dmesh, mesh)
    flux = make_sharded_flux(dmesh, mesh.ntet, 2, jnp.float64)
    origin_h, _, _, _ = _random_batch(n, rng)
    elem_h = np.asarray(locate_points(mesh, jnp.asarray(origin_h), 1e-12))
    pos = jnp.asarray(origin_h)
    elem = jnp.asarray(elem_h, jnp.int32)
    total_len = 0.0
    for i in range(3):
        _, dest_h, _, group_h = _random_batch(n, np.random.default_rng(i))
        dest, weight, group = shard_particles(
            dmesh,
            jnp.asarray(dest_h),
            jnp.asarray(np.ones(n)),
            jnp.asarray(group_h, jnp.int32),
        )
        pos_s, elem_s = shard_particles(dmesh, pos, elem)
        in_flight, material = shard_particles(
            dmesh, jnp.ones(n, bool), jnp.full(n, -1, jnp.int32)
        )
        r = step(
            mesh_r, pos_s, dest, elem_s, in_flight, weight, group, material,
            flux,
        )
        flux = r.flux
        total_len += float(
            np.linalg.norm(
                np.asarray(r.position) - np.asarray(pos), axis=1
            ).sum()
        )
        pos, elem = r.position, r.elem
    total = np.asarray(reduce_flux(flux))[..., 0].sum()
    assert total == pytest.approx(total_len, abs=1e-9)
